"""Hierarchical quota tree: cluster -> tenant -> stream.

Scopes are strings: ``"cluster"``, ``"tenant/<ns>"``, ``"stream/<name>"``.
A stream's tenant is its namespace prefix — the part before the first
``/`` or ``.`` in the stream name (``acme/orders`` and ``acme.events``
both belong to tenant ``acme``; an unseparated name has no tenant
level). Admission walks stream -> tenant -> cluster and every
configured level must admit; the reported retry-after is the slowest
level's.

The tree itself is read-mostly: admission fetches nodes with plain dict
gets (GIL-atomic), mutation holds a lock and swaps whole nodes, so the
hot path takes no tree-level lock.
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import asdict, dataclass

from hstream_tpu.flow.bucket import TokenBucket

SCOPE_CLUSTER = "cluster"

_QUOTA_FIELDS = ("records_per_s", "bytes_per_s", "read_records_per_s",
                 "burst_records", "burst_bytes")


@dataclass(frozen=True)
class Quota:
    """Limits of one scope; None = unlimited on that axis. Burst
    defaults to one second's worth of the matching rate. Every set
    field must be positive — a zero rate is not "block everything", it
    is a config error (use stream deletion or ACLs to block)."""

    records_per_s: float | None = None
    bytes_per_s: float | None = None
    read_records_per_s: float | None = None
    burst_records: float | None = None
    burst_bytes: float | None = None

    def __post_init__(self) -> None:
        for field in _QUOTA_FIELDS:
            v = getattr(self, field)
            if v is not None and (v != v or v <= 0.0):  # NaN or <= 0
                raise ValueError(
                    f"quota {field} must be positive, got {v!r}")
        # a burst without its rate builds no bucket — refuse the no-op
        # instead of letting the operator believe a cap exists
        if self.burst_records is not None and self.records_per_s is None:
            raise ValueError("burst_records needs records_per_s")
        if self.burst_bytes is not None and self.bytes_per_s is None:
            raise ValueError("burst_bytes needs bytes_per_s")
        if all(getattr(self, f) is None for f in _QUOTA_FIELDS):
            raise ValueError("quota must set at least one limit")

    def to_json(self) -> dict:
        return {k: v for k, v in asdict(self).items() if v is not None}

    @classmethod
    def from_json(cls, d: dict) -> "Quota":
        unknown = set(d) - set(_QUOTA_FIELDS)
        if unknown:
            raise ValueError(f"unknown quota field(s) {sorted(unknown)}")
        return cls(**{k: (None if d[k] is None else float(d[k]))
                      for k in d})

    @classmethod
    def from_bytes(cls, raw: bytes) -> "Quota":
        return cls.from_json(json.loads(raw))

    def to_bytes(self) -> bytes:
        return json.dumps(self.to_json(), sort_keys=True).encode()


def tenant_of(stream: str) -> str | None:
    """Namespace prefix of a stream name, or None when unseparated."""
    cut = min((i for i in (stream.find("/"), stream.find("."))
               if i > 0), default=-1)
    return stream[:cut] if cut > 0 else None


def validate_scope(scope: str) -> str:
    if scope == SCOPE_CLUSTER:
        return scope
    kind, _, name = scope.partition("/")
    if kind in ("tenant", "stream") and name:
        return scope
    raise ValueError(
        f"bad quota scope {scope!r}: use 'cluster', 'tenant/<ns>' "
        f"or 'stream/<name>'")


class _Node:
    """Buckets of one scope (built whole, swapped atomically)."""

    __slots__ = ("quota", "records", "bytes", "reads")

    def __init__(self, quota: Quota, clock):
        self.quota = quota
        self.records = (None if quota.records_per_s is None else
                        TokenBucket(quota.records_per_s,
                                    quota.burst_records, clock=clock))
        self.bytes = (None if quota.bytes_per_s is None else
                      TokenBucket(quota.bytes_per_s,
                                  quota.burst_bytes, clock=clock))
        self.reads = (None if quota.read_records_per_s is None else
                      TokenBucket(quota.read_records_per_s, clock=clock))


class QuotaTree:
    def __init__(self, clock=time.monotonic):
        self._clock = clock
        self._nodes: dict[str, _Node] = {}
        self._lock = threading.Lock()

    def __len__(self) -> int:
        return len(self._nodes)

    # ---- configuration ----
    def set(self, scope: str, quota: Quota) -> None:
        validate_scope(scope)
        with self._lock:
            self._nodes[scope] = _Node(quota, self._clock)

    def unset(self, scope: str) -> None:
        with self._lock:
            self._nodes.pop(scope, None)

    def get(self, scope: str) -> Quota | None:
        node = self._nodes.get(scope)
        return None if node is None else node.quota

    def scopes(self) -> dict[str, Quota]:
        with self._lock:
            return {s: n.quota for s, n in self._nodes.items()}

    # ---- admission ----
    def _walk(self, stream: str) -> list[_Node]:
        nodes = []
        n = self._nodes.get(f"stream/{stream}")
        if n is not None:
            nodes.append(n)
        ns = tenant_of(stream)
        if ns is not None:
            n = self._nodes.get(f"tenant/{ns}")
            if n is not None:
                nodes.append(n)
        n = self._nodes.get(SCOPE_CLUSTER)
        if n is not None:
            nodes.append(n)
        return nodes

    def admit_append(self, stream: str, n_records: int,
                     n_bytes: int) -> float:
        """0.0 = admitted (tokens consumed at every level), else the
        retry-after in seconds (nothing consumed). Peek-then-take: a
        race between the phases at worst drives a bucket into debt,
        which later refills repay — sustained rate still converges."""
        nodes = self._walk(stream)
        wait = 0.0
        for node in nodes:
            if node.records is not None:
                wait = max(wait, node.records.peek(n_records))
            if node.bytes is not None:
                wait = max(wait, node.bytes.peek(n_bytes))
        if wait > 0.0:
            return wait
        for node in nodes:
            if node.records is not None:
                node.records.take(n_records)
            if node.bytes is not None:
                node.bytes.take(n_bytes)
        return 0.0

    def peek_read(self, stream: str) -> float:
        """Wait until ONE read token is available at every configured
        level (reads charge after the fact via charge_read)."""
        wait = 0.0
        for node in self._walk(stream):
            if node.reads is not None:
                wait = max(wait, node.reads.peek(1.0))
        return wait

    def charge_read(self, stream: str, n_records: int) -> None:
        for node in self._walk(stream):
            if node.reads is not None:
                node.reads.take(n_records)
