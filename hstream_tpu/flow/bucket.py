"""Token bucket: the rate primitive under every quota.

Reference: LogDevice enforces per-log append quotas with token buckets
below the sequencer (the tier our host-side staging plays here). This
implementation is lock-cheap — one short critical section per call, no
waiting inside the lock — and clock-injectable so tier-1 tests drive it
with a fake clock instead of sleeps.

Admission is peek-then-take: `peek` reports the wait (seconds) until
`n` tokens accrue without consuming anything; `take` deducts
unconditionally and may drive the balance negative ("debt"). Debt makes
sustained admission converge exactly on the configured rate even when
callers charge after the fact (read paths that only know the true count
post-read) or when two admitters race between peek and take.
"""

from __future__ import annotations

import threading
import time


class TokenBucket:
    __slots__ = ("rate", "burst", "_tokens", "_t", "_lock", "_clock")

    def __init__(self, rate: float, burst: float | None = None, *,
                 clock=time.monotonic):
        self.rate = float(rate)
        # default burst: one second's worth (never below 1 so a
        # fractional rate can still ever admit a single record)
        self.burst = float(burst if burst is not None
                           else max(self.rate, 1.0))
        self._tokens = self.burst
        self._clock = clock
        self._t = clock()
        self._lock = threading.Lock()

    def _refill_locked(self, now: float) -> None:
        if now > self._t:
            self._tokens = min(self.burst,
                               self._tokens + (now - self._t) * self.rate)
        self._t = now

    def peek(self, n: float = 1.0) -> float:
        """Seconds until `n` tokens are available; 0.0 = admissible now.
        A request larger than the whole burst is admissible once the
        bucket is FULL (it then goes into debt via take) — otherwise the
        advertised wait could never come true, since tokens cap at
        burst."""
        target = min(n, self.burst)
        now = self._clock()
        with self._lock:
            self._refill_locked(now)
            if self._tokens >= target:
                return 0.0
            need = target - self._tokens
        if self.rate <= 0.0:
            return float("inf")
        return need / self.rate

    def take(self, n: float = 1.0) -> None:
        """Deduct `n` tokens unconditionally (balance may go negative —
        the debt is repaid by refill before anything else is admitted)."""
        now = self._clock()
        with self._lock:
            self._refill_locked(now)
            self._tokens -= n

    def try_take(self, n: float = 1.0) -> float:
        """peek+take in one critical section: returns 0.0 and consumes
        on admit, else the wait in seconds with nothing consumed.
        Oversize requests (n > burst) admit at a full bucket and go
        into debt, same as peek/take."""
        target = min(n, self.burst)
        now = self._clock()
        with self._lock:
            self._refill_locked(now)
            if self._tokens >= target:
                self._tokens -= n
                return 0.0
            need = target - self._tokens
        if self.rate <= 0.0:
            return float("inf")
        return need / self.rate

    @property
    def tokens(self) -> float:
        with self._lock:
            self._refill_locked(self._clock())
            return self._tokens
