"""Flow control: admission quotas, overload shedding, delivery credits.

The subsystem between "fast" and "fast under overload": a hierarchical
token-bucket quota tree (cluster -> tenant -> stream) persisted through
the versioned config store, an overload detector that turns the
pipeline/latency/backlog signals the repo already produces into a
graded shed ladder, and credit windows bounding per-consumer in-flight
delivery. `FlowGovernor` (one per ServerContext) fronts all three.
"""

from hstream_tpu.flow.bucket import TokenBucket
from hstream_tpu.flow.credit import CreditWindow
from hstream_tpu.flow.governor import (
    DEFAULT_CREDIT_WINDOW,
    WORK_BACKGROUND,
    WORK_USER,
    FlowGovernor,
)
from hstream_tpu.flow.overload import (
    ADMIT,
    DEFER,
    REJECT,
    OverloadDetector,
)
from hstream_tpu.flow.quota import Quota, QuotaTree, tenant_of

__all__ = [
    "ADMIT", "DEFER", "REJECT", "DEFAULT_CREDIT_WINDOW",
    "WORK_BACKGROUND", "WORK_USER",
    "CreditWindow", "FlowGovernor", "OverloadDetector",
    "Quota", "QuotaTree", "TokenBucket", "tenant_of",
]
