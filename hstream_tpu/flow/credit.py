"""Credit window: per-consumer in-flight bound for push delivery.

Each StreamingFetch consumer carries `window` credits; the dispatcher
takes one credit per delivered record and acks refill them. At zero
credits delivery pauses, so a stalled consumer holds at most its window
of undelivered records server-side — the server's memory per consumer
is bounded no matter how slow the client drains.
"""

from __future__ import annotations

import threading


class CreditWindow:
    def __init__(self, window: int):
        if window <= 0:
            raise ValueError("credit window must be positive")
        self.window = int(window)
        self._avail = int(window)
        self._cv = threading.Condition()

    @property
    def available(self) -> int:
        # found by hstream-analyze (lock-guard): _avail is mutated
        # under _cv by take_up_to (dispatcher) and refill (ack
        # threads); the unlocked read fed torn in-flight values to the
        # credit_inflight gauge
        with self._cv:
            return self._avail

    def take_up_to(self, n: int, timeout: float = 0.0) -> int:
        """Take up to `n` credits; blocks up to `timeout` for the first
        credit. Returns how many were taken (0 = window exhausted)."""
        with self._cv:
            if self._avail <= 0 and timeout > 0.0:
                self._cv.wait_for(lambda: self._avail > 0, timeout)
            take = min(int(n), self._avail)
            if take > 0:
                self._avail -= take
            return take

    def refill(self, n: int) -> None:
        """Return `n` credits (acks, failed deliveries); capped at the
        window so duplicate acks cannot inflate it."""
        if n <= 0:
            return
        with self._cv:
            self._avail = min(self.window, self._avail + int(n))
            self._cv.notify_all()
