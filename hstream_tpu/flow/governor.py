"""FlowGovernor: the one admission-control object the server consults.

Ties together the quota tree (hierarchical token buckets), the overload
detector (graded shed ladder), and the credit-window default for push
delivery. Quotas persist through the CAS-versioned config store under
``flow/quota/<scope>`` so they survive restart and ride store
replication like any other cluster config.

Hot-path contract: when no quota is configured and the detector is at
ADMIT, ``governor.active`` is False and ingress paths skip everything
after one attribute read — no locks, no allocation (the acceptance bar:
unchanged-config throughput within noise).

Shed ladder (overload.ADMIT/DEFER/REJECT):
  * DEFER  — background work (connectors, snapshot cadence, boot-time
    query adoption) is deferred with a retry hint; user traffic flows.
  * REJECT — user appends are refused with RESOURCE_EXHAUSTED + a
    retry-after hint as well. Reads are never shed: draining consumers
    is how backlog-driven overload recovers.
"""

from __future__ import annotations

import threading
import time

from hstream_tpu.common.errors import ResourceExhausted
from hstream_tpu.common.logger import get_logger
from hstream_tpu.flow.overload import ADMIT, DEFER, REJECT, OverloadDetector
from hstream_tpu.flow.quota import Quota, QuotaTree, validate_scope

log = get_logger("flow")

QUOTA_PREFIX = "flow/quota/"
DEFAULT_CREDIT_WINDOW = 256

WORK_USER = "user"
WORK_BACKGROUND = "background"


class FlowGovernor:
    def __init__(self, *, config=None, stats=None, events=None,
                 clock=time.monotonic,
                 credit_window: int = DEFAULT_CREDIT_WINDOW,
                 defer_ms: int = 200, reject_ms: int = 1000,
                 signals: dict[str, tuple[float, float]] | None = None):
        self._config = config          # VersionedConfigStore | None
        self._stats = stats            # StatsHolder | None
        self._events = events          # stats.events.EventJournal | None
        self.clock = clock
        self.credit_window = int(credit_window)
        self.defer_ms = int(defer_ms)
        self.reject_ms = int(reject_ms)
        self.quotas = QuotaTree(clock)
        self.overload = OverloadDetector(
            signals, clock=clock, on_change=self._on_level_change)
        # per-class shed counters (GIL-atomic bumps; flow-status verb).
        # UNIT: denied admission polls, not distinct work items — a
        # deferred connector re-asks every poll cycle, so during a
        # sustained episode `background` grows at poll rate; read it as
        # "how hard the ladder is pushing back", not "tasks shed"
        self.shed_by_class = {WORK_USER: 0, WORK_BACKGROUND: 0}
        self._mutate = threading.Lock()
        # the one-branch hot-path gate: False => ingress skips the
        # governor entirely (plain attribute read, no locks)
        self.active = False

    def _recompute_active(self) -> None:
        self.active = bool(len(self.quotas)) \
            or self.overload.level != ADMIT

    def _on_level_change(self, lvl: int) -> None:
        self._recompute_active()
        if self._events is not None:
            from hstream_tpu.flow.overload import LEVEL_NAMES

            try:
                self._events.append(
                    "shed_level",
                    f"overload ladder -> {LEVEL_NAMES[lvl]}",
                    level=LEVEL_NAMES[lvl])
            except Exception:  # noqa: BLE001 — journaling must never
                pass           # affect admission decisions

    # ---- admission: user ingress -------------------------------------------

    def admit_append(self, stream: str, n_records: int,
                     n_bytes: int) -> None:
        """Raise ResourceExhausted (with retry-after) when the append
        must be refused; otherwise consume quota and return."""
        if self.overload.effective_level() >= REJECT:
            self.shed_by_class[WORK_USER] += 1
            if self._stats is not None:
                self._stats.stream_stat_add("shed_total", stream)
            raise ResourceExhausted(
                f"server overloaded; append to {stream!r} shed",
                retry_after_ms=self.reject_ms)
        wait = self.quotas.admit_append(stream, n_records, n_bytes)
        if wait > 0.0:
            if self._stats is not None:
                self._stats.stream_stat_add("append_throttled", stream)
            raise ResourceExhausted(
                f"quota exceeded on stream {stream!r}",
                retry_after_ms=self._hint_ms(wait))

    def admit_read(self, stream: str) -> None:
        """Gate one read/fetch call on the stream's read quota (reads
        are never overload-shed — draining reduces backlog)."""
        wait = self.quotas.peek_read(stream)
        if wait > 0.0:
            raise ResourceExhausted(
                f"read quota exceeded on stream {stream!r}",
                retry_after_ms=self._hint_ms(wait))

    @staticmethod
    def _hint_ms(wait_s: float) -> int:
        """Retry hint from a bucket wait, capped at 60s so a pathological
        wait (huge deficit) can never overflow or advertise hours."""
        return max(1, int(min(wait_s, 60.0) * 1000.0) + 1)

    def charge_read(self, stream: str, n_records: int) -> None:
        """Charge the actual record count after a read (debt-based, so
        the sustained read rate converges on the quota)."""
        if n_records > 0:
            self.quotas.charge_read(stream, n_records)

    # ---- admission: background work ----------------------------------------

    def admit_background(self, kind: str = "background") -> float:
        """0.0 = proceed; else the suggested wait in seconds before
        retrying. Background work sheds one ladder rung EARLIER than
        user traffic (at DEFER), so connectors/snapshots/adoption give
        their cycles back before any user append is refused."""
        lvl = self.overload.effective_level()
        if lvl >= DEFER:
            self.shed_by_class[WORK_BACKGROUND] += 1
            hint_ms = self.reject_ms if lvl >= REJECT else self.defer_ms
            return hint_ms / 1000.0
        return 0.0

    # ---- quota configuration (persisted) -----------------------------------

    def set_quota(self, scope: str, quota: Quota) -> Quota:
        validate_scope(scope)
        with self._mutate:
            self._persist(scope, quota.to_bytes())
            self.quotas.set(scope, quota)
            self._recompute_active()
        return quota

    def unset_quota(self, scope: str) -> None:
        validate_scope(scope)
        with self._mutate:
            self._persist(scope, None)
            self.quotas.unset(scope)
            self._recompute_active()

    def get_quota(self, scope: str) -> Quota | None:
        return self.quotas.get(scope)

    def list_quotas(self) -> dict[str, Quota]:
        return self.quotas.scopes()

    def _persist(self, scope: str, value: bytes | None) -> None:
        if self._config is None:
            return
        from hstream_tpu.store.versioned import VersionMismatch

        key = QUOTA_PREFIX + scope
        for _ in range(16):
            cur = self._config.get(key)
            try:
                if value is None:
                    if cur is None:
                        return
                    self._config.delete(key, base_version=cur[0])
                else:
                    self._config.put(
                        key, value,
                        base_version=None if cur is None else cur[0])
                return
            except VersionMismatch:
                continue
        log.warning("quota write for %s kept losing CAS", scope)

    def load(self) -> int:
        """Boot-time restore of persisted quotas; returns how many
        scopes were loaded."""
        if self._config is None:
            return 0
        n = 0
        with self._mutate:
            for key in self._config.keys():
                if not key.startswith(QUOTA_PREFIX):
                    continue
                cur = self._config.get(key)
                if cur is None:
                    continue
                scope = key[len(QUOTA_PREFIX):]
                try:
                    self.quotas.set(scope, Quota.from_bytes(cur[1]))
                    n += 1
                except (ValueError, KeyError):
                    log.warning("ignoring malformed quota %s", scope)
            self._recompute_active()
        return n

    # ---- introspection ------------------------------------------------------

    def status(self) -> dict:
        out = self.overload.status()
        out["active"] = self.active
        out["credit_window"] = self.credit_window
        out["shed"] = dict(self.shed_by_class)
        out["quotas"] = {scope: q.to_json()
                         for scope, q in self.list_quotas().items()}
        return out
