"""Overload detector: EWMA'd load signals -> a graded shed level.

Signals are the ones the repo already produces: pipeline stage
occupancy and reorder-ring depth (engine/pipeline, PR 1), per-chunk
step latency (server/tasks), and subscription backlog
(server/subscriptions). Each signal keeps per-SOURCE exponentially
weighted moving averages (source = the query task / subscription that
fed the sample) against a (warn, critical) threshold pair; the level is
the worst fresh source of the worst signal:

    ADMIT  (0)  everything flows
    DEFER  (1)  background work (connectors, snapshots, adoption) sheds
    REJECT (2)  user appends are refused with a retry-after hint too

Per-source max aggregation means an overloaded subscription cannot be
averaged away by idle siblings feeding zeros; per-source staleness
means a producer that died at critical (a deleted subscription, a
terminated query) expires on its own clock instead of pinning the
ladder. The EWMA is fast-attack/slow-release: overload is detected
quickly, recovery needs sustained low samples. `level` is a plain int
attribute so hot-path readers take no lock.
"""

from __future__ import annotations

import threading
import time

ADMIT = 0
DEFER = 1
REJECT = 2

LEVEL_NAMES = {ADMIT: "admit", DEFER: "defer", REJECT: "reject"}

# name -> (warn, critical); reorder_depth is a fraction of ring depth
DEFAULT_SIGNALS: dict[str, tuple[float, float]] = {
    "pipeline_occupancy": (0.85, 0.97),
    "step_latency_ms": (200.0, 1000.0),
    "reorder_depth": (0.75, 1.0),
    "sub_backlog": (10_000.0, 100_000.0),
}

# a source with no fresh samples expires: a producer that died (or went
# idle without feeding zeros) must not pin the shed ladder forever
STALE_AFTER_S = 10.0

_MAX_SOURCES = 64  # prune ceiling per signal (sources churn with tasks)


class _Signal:
    __slots__ = ("warn", "crit", "alpha", "sources")

    def __init__(self, warn: float, crit: float, alpha: float):
        self.warn = warn
        self.crit = crit
        self.alpha = alpha
        # source key -> [ewma value, last-sample clock]
        self.sources: dict[str | None, list[float]] = {}

    def note(self, v: float, source: str | None, now: float) -> None:
        e = self.sources.get(source)
        if e is None:
            e = self.sources[source] = [0.0, now]
        # asymmetric smoothing: attack at alpha, release at alpha/4 —
        # overload is detected quickly but recovery needs sustained low
        # samples, so the shed level cannot flap on a single idle tick
        a = self.alpha if v > e[0] else self.alpha / 4.0
        e[0] += a * (v - e[0])
        e[1] = now
        if len(self.sources) > _MAX_SOURCES:
            cutoff = now - 10.0 * STALE_AFTER_S
            for k in [k for k, s in self.sources.items()
                      if s[1] < cutoff]:
                del self.sources[k]

    def fresh_value(self, now: float, stale_after: float) -> float:
        """Worst EWMA across sources with fresh samples (0.0 if none)."""
        best = 0.0
        for e in self.sources.values():
            if now - e[1] <= stale_after and e[0] > best:
                best = e[0]
        return best

    def level_of(self, value: float) -> int:
        if value >= self.crit:
            return REJECT
        if value >= self.warn:
            return DEFER
        return ADMIT


class OverloadDetector:
    def __init__(self, signals: dict[str, tuple[float, float]] | None = None,
                 *, alpha: float = 0.5, on_change=None,
                 clock=time.monotonic,
                 stale_after_s: float = STALE_AFTER_S):
        self._sigs = {name: _Signal(w, c, alpha)
                      for name, (w, c) in
                      (DEFAULT_SIGNALS if signals is None
                       else signals).items()}
        self._lock = threading.Lock()
        self._on_change = on_change
        self._clock = clock
        self._stale_after = float(stale_after_s)
        self.level = ADMIT  # lock-free hot-path read

    def register(self, name: str, warn: float, crit: float, *,
                 alpha: float = 0.5) -> None:
        with self._lock:
            self._sigs[name] = _Signal(warn, crit, alpha)

    def _level_locked(self, now: float) -> int:
        lvl = ADMIT
        for s in self._sigs.values():
            sl = s.level_of(s.fresh_value(now, self._stale_after))
            if sl > lvl:
                lvl = sl
        return lvl

    def note(self, name: str, value: float,
             source: str | None = None) -> None:
        """Feed one sample from `source` (the query/subscription id);
        recomputes the graded level. Unregistered signal names raise
        (same registry discipline as stats)."""
        cb = None
        now = self._clock()
        with self._lock:
            sig = self._sigs.get(name)
            if sig is None:
                raise KeyError(f"unregistered overload signal {name!r}")
            sig.note(value, source, now)
            lvl = self._level_locked(now)
            if lvl != self.level:
                self.level = lvl
                cb = self._on_change
        if cb is not None:
            cb(lvl)

    def effective_level(self) -> int:
        """The level admission decisions act on: each signal source
        counts only while its own samples are fresh, so a dead producer
        expires instead of pinning the ladder. A stale recompute that
        disagrees writes the level back (and re-fires on_change), so
        the hot-path gate recovers even when no producer ever feeds
        another sample."""
        # deliberate lock-free fast path: the quiet-system gate must
        # cost one attribute read; a stale ADMIT is corrected by the
        # next note(), any non-ADMIT read falls into the locked path
        if self.level == ADMIT:  # analyze: ok lock-guard
            return ADMIT
        now = self._clock()
        cb = None
        with self._lock:
            lvl = self._level_locked(now)
            if lvl != self.level:
                self.level = lvl
                cb = self._on_change
        if cb is not None:
            cb(lvl)
        return lvl

    def status(self) -> dict:
        now = self._clock()
        with self._lock:
            out = {"level": LEVEL_NAMES[self._level_locked(now)],
                   "signals": {}}
            for name, s in self._sigs.items():
                v = s.fresh_value(now, self._stale_after)
                out["signals"][name] = {
                    "value": round(v, 4), "warn": s.warn,
                    "critical": s.crit,
                    "sources": len(s.sources),
                    "level": LEVEL_NAMES[s.level_of(v)]}
            return out
