"""Operator admin CLI (the reference's hstore-admin analogue).

Reference: a Thrift admin CLI with status/nodes-config/logs/
check-impact/maintenance/sql subcommands
(hstream-store/admin/app/cli.hs:56-69). Here the ops surface rides the
gRPC API: cluster status tables, per-entity listings, live stats, and
lifecycle verbs (restart/terminate/delete), printed as aligned tables.

    python -m hstream_tpu.admin [--host H --port P] <command> [args]
"""

from __future__ import annotations

import argparse
import sys

import grpc

from hstream_tpu.client import format_table
from hstream_tpu.proto import api_pb2 as pb
from hstream_tpu.proto.rpc import HStreamApiStub


def _stub(args) -> HStreamApiStub:
    ch = grpc.insecure_channel(f"{args.host}:{args.port}")
    return HStreamApiStub(ch)


def cmd_status(stub, args) -> list[dict]:
    nodes = stub.ListNodes(pb.ListNodesRequest()).nodes
    return [{"id": n.id, "address": n.address, "port": n.port,
             "roles": ",".join(n.roles), "status": n.status}
            for n in nodes]


def cmd_streams(stub, args) -> list[dict]:
    out = stub.ListStreams(pb.ListStreamsRequest()).streams
    return [{"stream": s.stream_name,
             "replication": s.replication_factor} for s in out]


def cmd_queries(stub, args) -> list[dict]:
    out = stub.ListQueries(pb.ListQueriesRequest()).queries
    return [{"id": q.id, "status": q.status,
             "created_ms": q.created_time_ms,
             "sql": q.query_text[:60]} for q in out]


def cmd_views(stub, args) -> list[dict]:
    out = stub.ListViews(pb.ListViewsRequest()).views
    return [{"view": v.view_id, "status": v.status,
             "sql": v.sql[:60]} for v in out]


def cmd_connectors(stub, args) -> list[dict]:
    out = stub.ListConnectors(pb.ListConnectorsRequest()).connectors
    return [{"id": c.id, "status": c.status,
             "config": c.config[:60]} for c in out]


def cmd_subscriptions(stub, args) -> list[dict]:
    out = stub.ListSubscriptions(pb.ListSubscriptionsRequest())
    return [{"id": s.subscription_id, "stream": s.stream_name}
            for s in out.subscription]


def cmd_stats(stub, args) -> list[dict]:
    """Declarative-family rate tables (the `hadmin server stats`
    analogue): one row per entity with every family's rate at the
    requested ladder interval (1min/10min/1h) + all-time totals;
    --json prints the raw verb output for scripting."""
    out = _admin(stub, "stats", entity=args.entity,
                 interval=args.interval)
    if getattr(args, "json", False):
        import json

        print(json.dumps({r.pop("key"): r for r in out}, indent=2,
                         sort_keys=True))
        return []
    label = {"streams": "stream", "subscriptions": "subscription",
             "queries": "query"}.get(args.entity, "key")
    return [{label: r.pop("key"), **r} for r in out]


def cmd_cluster_stats(stub, args) -> list[dict]:
    """Federated node load reports (ISSUE 15): fan the ClusterStats
    RPC out to --peers (or the leader's followers) and print ONE
    merged per-node table — a node summary row per node, then one row
    per (node, stream) with the family rate ladder."""
    from hstream_tpu.stats.cluster import merge_rows

    kwargs = {"interval": args.interval, "timeout_s": args.timeout}
    if args.peers:
        kwargs["peers"] = args.peers
    out = _admin(stub, "cluster-stats", **kwargs)
    reports = {r.pop("key"): r for r in out}
    if getattr(args, "json", False):
        import json

        print(json.dumps(reports, indent=2, sort_keys=True))
        return []
    return merge_rows([reports[k] for k in sorted(reports)],
                      interval=args.interval)


def cmd_trace(stub, args) -> list[dict]:
    from hstream_tpu.common import records as rec

    if getattr(args, "spans", False):
        # Chrome trace-event JSON of the query's span ring (ISSUE 13):
        # printed raw so it pipes straight into a .json file for
        # chrome://tracing / Perfetto
        import json

        out = _admin(stub, "trace-spans", scope=args.id)
        print(json.dumps(out[0] if out else {}))
        return []
    summary = rec.struct_to_dict(
        stub.GetQueryTrace(pb.GetQueryRequest(id=args.id)))
    return [{"stage": stage, **vals}
            for stage, vals in sorted(summary.items())]


def cmd_health(stub, args) -> list[dict]:
    """Per-query health rollup (ISSUE 13): OK/DEGRADED/STALLED with
    reasons, one row per query (or one query with --id)."""
    if args.id:
        rows = _admin(stub, "health", query=args.id)
    else:
        # the verb returns qid -> health dict; _admin renders that as
        # one {"key": qid, **health} row per query, already sorted
        rows = _admin(stub, "health")
    return [{"query": h.get("query"), "verdict": h.get("verdict"),
             "reasons": ",".join(h.get("reasons") or []) or "-",
             "status": h.get("status"),
             "wm_lag_ms": h.get("watermark_lag_ms"),
             "backlog": h.get("backlog"),
             "fallbacks": h.get("device_fallbacks"),
             "late_drops": h.get("late_drops")}
            for h in rows]


def cmd_programs(stub, args) -> list[dict]:
    """Compiled-program inventory (ISSUE 18): one row per resident
    executable with XLA cost-analysis columns; --json dumps the raw
    summary + rows."""
    out = _admin(stub, "programs")
    data = out[0] if out else {}
    if getattr(args, "json", False):
        import json

        print(json.dumps(data, indent=2, sort_keys=True))
        return []
    return [{"shape_key": r.get("shape_key"),
             "family": r.get("family") or "-",
             "name": (r.get("name") or "")[:40],
             "compiles": r.get("compiles"),
             "compile_ms": round(r.get("compile_ms") or 0.0, 1),
             "gflops": (round(r["flops"] / 1e9, 3)
                        if r.get("flops") else "-"),
             "mbytes_acc": (round(r["bytes_accessed"] / 1e6, 3)
                            if r.get("bytes_accessed") else "-")}
            for r in data.get("programs", [])]


def cmd_flightrec(stub, args) -> list[dict]:
    """Flight-recorder bundles (ISSUE 18): with a query id, print the
    raw postmortem bundles as JSON (pipe to a file); without, the
    recorder index."""
    import json

    if args.id:
        out = _admin(stub, "flightrec", query=args.id)
        print(json.dumps(out[0] if out else {}, indent=2,
                         sort_keys=True))
        return []
    out = _admin(stub, "flightrec")
    data = out[0] if out else {}
    return [{"query": q, "bundles": n}
            for q, n in sorted((data.get("queries") or {}).items())]


def cmd_restart_query(stub, args) -> list[dict]:
    stub.RestartQuery(pb.RestartQueryRequest(id=args.id))
    return [{"restarted": args.id}]


def cmd_terminate_query(stub, args) -> list[dict]:
    req = (pb.TerminateQueriesRequest(all=True) if args.id == "all"
           else pb.TerminateQueriesRequest(query_ids=[args.id]))
    done = stub.TerminateQueries(req)
    return [{"terminated": qid} for qid in done.query_ids]


def cmd_delete_stream(stub, args) -> list[dict]:
    stub.DeleteStream(pb.DeleteStreamRequest(stream_name=args.name))
    return [{"deleted": args.name}]


def _admin(stub, command: str, **kwargs) -> list[dict]:
    """Store-ops verbs over SendAdminCommand (reference hstore-admin
    trim/findTime/offsets, admin/app/cli.hs:56-69)."""
    import json

    from hstream_tpu.common import records as rec

    resp = stub.SendAdminCommand(pb.AdminCommandRequest(
        command=command, args=rec.dict_to_struct(kwargs)))
    out = json.loads(resp.result)
    if isinstance(out, dict) and not out:
        return []
    if isinstance(out, dict) and out and all(
            isinstance(v, dict) for v in out.values()):
        return [{"key": k, **v} for k, v in sorted(out.items())]
    if isinstance(out, dict):
        return [out]
    return list(out)


def cmd_trim(stub, args) -> list[dict]:
    return _admin(stub, "trim", stream=args.stream, lsn=args.lsn)


def cmd_find_time(stub, args) -> list[dict]:
    return _admin(stub, "find-time", stream=args.stream, ts_ms=args.ts_ms)


def cmd_offsets(stub, args) -> list[dict]:
    return _admin(stub, "offsets", stream=args.stream)


def cmd_sub_lag(stub, args) -> list[dict]:
    return _admin(stub, "sub-lag", subscription=args.id)


def cmd_snapshots(stub, args) -> list[dict]:
    return _admin(stub, "snapshots")


def cmd_replicas(stub, args) -> list[dict]:
    out = _admin(stub, "replicas")
    if out and "followers" in out[0]:
        rows = []
        leader = out[0].get("leader")
        if leader:
            # leadership state first (ISSUE 9): epoch, fencing, ack
            # tuning, dedup footprint — sorted keys so operator diffs
            # and test assertions are stable
            rows.append({"role": "leader-status",
                         **{k: leader[k] for k in sorted(leader)}})
        fols = sorted(out[0]["followers"],
                      key=lambda f: f.get("addr", ""))
        rows.extend({"role": out[0]["role"], **f} for f in fols)
        return rows or [{"role": out[0]["role"]}]
    return out


def cmd_promote(stub, args) -> list[dict]:
    """Epoch-fenced leader failover (ISSUE 9): planned handoff
    (--target, through the current leader) or leader-death promotion
    (--replicas, most-caught-up reachable replica wins)."""
    kwargs = {}
    if args.leader_addr:
        kwargs["leader_addr"] = args.leader_addr
    if args.target:
        return _admin(stub, "promote", target=args.target, **kwargs)
    if args.replicas:
        return _admin(stub, "promote", replicas=args.replicas, **kwargs)
    raise SystemExit("promote needs --target ADDR (planned handoff) "
                     "or --replicas A,B,... (leader death)")


def cmd_assignments(stub, args) -> list[dict]:
    return _admin(stub, "assignments")


def cmd_placer(stub, args) -> list[dict]:
    """Placement plane (ISSUE 17): per-node scores with skip reasons,
    current placements, the last decision + machine-readable reason,
    and any co-compile packs."""
    import json

    resp = _admin(stub, "placer")
    st = resp[0] if resp else {}
    if getattr(args, "json", False):
        print(json.dumps(st, indent=2, sort_keys=True))
        return []
    rows = [{"": "placer",
             "value": "armed" if st.get("armed") else "disarmed",
             "detail": (f"node {st.get('node')} lease "
                        f"{st.get('lease_ms')}ms ticks "
                        f"{st.get('ticks')}")}]
    for node, n in sorted((st.get("nodes") or {}).items()):
        rows.append({
            "": f"node {node}",
            "value": (f"SKIP {n['skip']}" if n.get("skip")
                      else f"score {n.get('score')}"),
            "detail": (f"queries {n.get('running_queries')} rss "
                       f"{n.get('rss_mb')}MB p99 "
                       f"{n.get('dispatch_p99_ms')}ms hb_age "
                       f"{n.get('hb_age_ms')}ms")})
    for qid, p in sorted((st.get("placements") or {}).items()):
        age = p.get("hb_age_ms")
        rows.append({
            "": f"query {qid}",
            "value": f"{p.get('state')} @ {p.get('node')}",
            "detail": (f"epoch {p.get('epoch')}"
                       + ("" if age is None else f" hb_age {age}ms"))})
    for pack in st.get("packs") or []:
        members = pack.get("members") or []
        rows.append({
            "": f"pack {pack.get('signature')}",
            "value": f"{len(members)} member(s)",
            "detail": ",".join(members)})
    last = st.get("last_decision")
    if last:
        rows.append({
            "": "last-decision",
            "value": f"{last.get('action')} {last.get('query')}",
            "detail": (f"-> {last.get('target')} "
                       f"reason={last.get('reason')}")})
    return rows


def cmd_quota(stub, args) -> list[dict]:
    """Flow-control quota CRUD over the hierarchical quota tree
    (scopes: cluster | tenant/<ns> | stream/<name>)."""
    if args.action == "list":
        return _admin(stub, "quota-list")
    if args.scope is None:
        raise SystemExit(f"quota {args.action} needs a scope")
    if args.action == "get":
        return _admin(stub, "quota-get", scope=args.scope)
    if args.action == "unset":
        return _admin(stub, "quota-unset", scope=args.scope)
    fields = {}
    for field, flag in (("records_per_s", args.records),
                        ("bytes_per_s", args.bytes),
                        ("read_records_per_s", args.read_records),
                        ("burst_records", args.burst_records),
                        ("burst_bytes", args.burst_bytes)):
        if flag is not None:
            fields[field] = flag
    if not fields:
        raise SystemExit("quota set needs at least one of --records/"
                         "--bytes/--read-records/--burst-records/"
                         "--burst-bytes")
    return _admin(stub, "quota-set", scope=args.scope, **fields)


def cmd_events(stub, args) -> list[dict]:
    """Operator event journal: shed transitions, degraded appends,
    adoption/restart/death, snapshot failures."""
    kwargs = {"limit": args.limit, "since": args.since}
    if args.kind:
        kwargs["kind"] = args.kind
    out = _admin(stub, "events", **kwargs)
    rows = out[0].get("events", []) if out else []
    return [{"seq": e.get("seq"), "ts_ms": e.get("ts_ms"),
             "kind": e.get("kind"), "message": e.get("message")}
            for e in rows]


def cmd_metrics(stub, args) -> list[dict]:
    """Raw Prometheus exposition (what GET /metrics serves)."""
    out = _admin(stub, "metrics")
    print(out[0]["text"], end="")
    return []


def cmd_fault(stub, args) -> list[dict]:
    """Chaos fault sites: arm/clear/list deterministic fault schedules
    (fail:N / prob:P:SEED / delay:MS / torn:N:SEED) on named sites."""
    if args.action == "list":
        out = _admin(stub, "fault-list")[0]
        sites = out.get("sites", {})
        return ([{"site": s, **v} for s, v in sorted(sites.items())]
                or [{"active": out.get("active", False)}])
    if args.site is None:
        if args.action == "clear":
            return _admin(stub, "fault-clear")  # no site: clear ALL
        raise SystemExit(f"fault {args.action} needs a site")
    if args.action == "set":
        if args.spec is None:
            raise SystemExit("fault set needs a spec (e.g. fail:3)")
        return _admin(stub, "fault-set", site=args.site, spec=args.spec)
    return _admin(stub, "fault-clear", site=args.site)


def cmd_locks(stub, args) -> list[dict]:
    """Lock-order witness ledger (ISSUE 14): named locks with
    acquire/contention counts and wait/hold percentiles, the observed
    order graph, and any detected cycles; --arm/--disarm flip the
    witness at runtime."""
    kwargs = {}
    if args.arm:
        kwargs["action"] = "arm"
    elif args.disarm:
        kwargs["action"] = "disarm"
    out = _admin(stub, "locks", **kwargs)
    st = out[0] if out else {}
    rows = [{"lock": "(witness)",
             "value": "armed" if st.get("armed") else "disarmed",
             "detail": f"cycles={len(st.get('cycles', []))}"}]
    for name, row in sorted((st.get("locks") or {}).items()):
        detail = " ".join(
            f"{k}={row[k]}" for k in ("wait_p50_ms", "wait_p99_ms",
                                      "hold_p50_ms", "hold_p99_ms")
            if row.get(k) is not None)
        rows.append({"lock": name,
                     "value": f"acq={row.get('acquires', 0)} "
                              f"cont={row.get('contentions', 0)}",
                     "detail": detail or "-"})
    for a, bs in sorted((st.get("edges") or {}).items()):
        rows.append({"lock": f"order {a}",
                     "value": "->", "detail": ",".join(bs)})
    for c in st.get("cycles") or []:
        ring = " -> ".join(e[0] for e in c.get("ring", []))
        rows.append({"lock": "CYCLE", "value": ring,
                     "detail": str(c.get("witness", ""))[:60]})
    return rows


def cmd_supervisor(stub, args) -> list[dict]:
    """Query-supervision status: pending restarts + open breakers."""
    resp = _admin(stub, "supervisor")
    out = resp[0] if resp else {}
    rows = [{"": "restarts", "value": out.get("restarts", 0),
             "detail": ""}]
    for qid, p in sorted(out.get("pending", {}).items()):
        rows.append({"": f"pending {qid}",
                     "value": f"attempt {p.get('attempt')}",
                     "detail": f"due in {p.get('due_in_s')}s"})
    for qid in out.get("breaker_open", []):
        rows.append({"": f"breaker {qid}", "value": "OPEN",
                     "detail": "RestartQuery to reset"})
    return rows


def cmd_flow(stub, args) -> list[dict]:
    """Live flow-control status: shed level, overload signals, active
    quotas, per-class shed counters."""
    out = _admin(stub, "flow-status")[0]
    rows = [{"": "level", "value": out.get("level"),
             "detail": f"active={out.get('active')} "
                       f"credit_window={out.get('credit_window')}"}]
    for name, sig in sorted(out.get("signals", {}).items()):
        rows.append({"": f"signal {name}", "value": sig.get("value"),
                     "detail": f"warn={sig.get('warn')} "
                               f"crit={sig.get('critical')} "
                               f"-> {sig.get('level')}"})
    for cls, n in sorted(out.get("shed", {}).items()):
        rows.append({"": f"shed {cls}", "value": n, "detail": ""})
    for scope, q in sorted(out.get("quotas", {}).items()):
        rows.append({"": f"quota {scope}", "value": "",
                     "detail": " ".join(f"{k}={v}"
                                        for k, v in sorted(q.items()))})
    return rows


def cmd_read_cache(stub, args) -> list[dict]:
    """Read-plane snapshot/expansion cache counters: hit ratio, byte
    budget occupancy, extracts, evictions, invalidations."""
    out = _admin(stub, "read-cache")[0]
    if not out.get("enabled"):
        return [{"": "enabled", "value": False,
                 "detail": "started with --read-cache-bytes 0"}]
    rows = []
    for key in sorted(out):
        if key == "enabled":
            continue
        rows.append({"": key, "value": out[key], "detail": ""})
    return rows


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        "hstream-tpu-admin",
        description="operator CLI over the gRPC admin surface")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=6570)
    sub = ap.add_subparsers(dest="cmd", required=True)
    for name in ("status", "streams", "queries", "views", "connectors",
                 "subscriptions"):
        sub.add_parser(name)
    p = sub.add_parser("stats",
                       help="per-entity rate-family tables off the "
                            "multi-level ladders (1min/10min/1h)")
    p.add_argument("entity", nargs="?", default="streams",
                   choices=["streams", "subscriptions", "queries"])
    p.add_argument("--interval", default="1min",
                   choices=["1min", "10min", "1h"],
                   help="trailing ladder window the rates cover")
    p.add_argument("--json", action="store_true",
                   help="raw JSON instead of the table")
    p = sub.add_parser("cluster-stats",
                       help="federated node load reports: one merged "
                            "per-node table (rates, health, rss, "
                            "queue depths) across --peers/followers")
    p.add_argument("--peers", default=None, metavar="ADDR,ADDR",
                   help="peer server addresses to fan out to "
                        "(default: this leader's store followers)")
    p.add_argument("--interval", default="1min",
                   choices=["1min", "10min", "1h"])
    p.add_argument("--timeout", type=float, default=5.0,
                   help="per-peer fan-out timeout (seconds)")
    p.add_argument("--json", action="store_true",
                   help="raw per-node reports instead of the table")
    p = sub.add_parser("trace")
    p.add_argument("id", help="running query id (e.g. view-<name>)")
    p.add_argument("--spans", action="store_true",
                   help="print the query's span ring as Chrome "
                        "trace-event JSON (server needs "
                        "--trace-sample > 0)")
    p = sub.add_parser("health",
                       help="per-query health rollup: OK/DEGRADED/"
                            "STALLED with reasons")
    p.add_argument("id", nargs="?", default=None,
                   help="one query id (default: every query)")
    p = sub.add_parser("programs",
                       help="compiled-program inventory: every XLA "
                            "executable this process compiled, with "
                            "cost-analysis flops/bytes and compile "
                            "times")
    p.add_argument("--json", action="store_true",
                   help="raw summary + rows as JSON")
    p = sub.add_parser("flightrec",
                       help="flight-recorder postmortem bundles "
                            "captured at STALLED / crash-loop edges")
    p.add_argument("id", nargs="?", default=None,
                   help="query id: print its bundles as JSON "
                        "(default: the recorder index)")
    p = sub.add_parser("restart-query")
    p.add_argument("id")
    p = sub.add_parser("terminate-query")
    p.add_argument("id", help="query id, or 'all'")
    p = sub.add_parser("delete-stream")
    p.add_argument("name")
    p = sub.add_parser("trim", help="drop records with lsn <= LSN")
    p.add_argument("stream")
    p.add_argument("lsn", type=int)
    p = sub.add_parser("find-time",
                       help="first lsn at/after an epoch-ms timestamp")
    p.add_argument("stream")
    p.add_argument("ts_ms", type=int)
    p = sub.add_parser("offsets", help="trim point / tail lsn of a stream")
    p.add_argument("stream")
    p = sub.add_parser("sub-lag", help="consumer lag of a subscription")
    p.add_argument("id")
    sub.add_parser("snapshots", help="per-query state snapshot sizes")
    sub.add_parser("replicas", help="store replication follower status "
                                    "+ leader epoch/fencing state")
    p = sub.add_parser("promote",
                       help="promote a store replica to leader "
                            "(epoch-fenced failover)")
    p.add_argument("--target", default=None, metavar="ADDR",
                   help="planned handoff: the current leader promotes "
                        "this follower and fences itself")
    p.add_argument("--replicas", default=None, metavar="A,B,...",
                   help="leader death: promote the most-caught-up "
                        "reachable replica (highest (epoch, "
                        "applied_seq, node_id) wins)")
    p.add_argument("--leader-addr", default=None, metavar="ADDR",
                   help="client-facing address served as the redirect "
                        "hint (defaults to the promoted replica addr)")
    sub.add_parser("assignments", help="query -> server scheduler records")
    p = sub.add_parser("placer",
                       help="placement plane: per-node load scores + "
                            "skip reasons, query placements with "
                            "heartbeat ages, co-compile packs, last "
                            "decision with machine-readable reason")
    p.add_argument("--json", action="store_true",
                   help="dump the full status (decision ring, raw "
                        "scores map) as JSON")
    p = sub.add_parser("quota",
                       help="flow-control quotas: get/set/list/unset "
                            "on cluster | tenant/<ns> | stream/<name>")
    p.add_argument("action", choices=["get", "set", "list", "unset"])
    p.add_argument("scope", nargs="?", default=None)
    p.add_argument("--records", type=float, default=None,
                   help="append records/s")
    p.add_argument("--bytes", type=float, default=None,
                   help="append bytes/s")
    p.add_argument("--read-records", type=float, default=None,
                   help="read records/s (Fetch)")
    p.add_argument("--burst-records", type=float, default=None)
    p.add_argument("--burst-bytes", type=float, default=None)
    sub.add_parser("flow",
                   help="live flow-control status: shed level, "
                        "overload signals, quotas")
    sub.add_parser("read-cache",
                   help="read-plane snapshot cache counters: hit "
                        "ratio, bytes, extracts, evictions")
    p = sub.add_parser("events",
                       help="operator event journal: shed transitions, "
                            "degraded appends, adoption, snapshot "
                            "failures")
    p.add_argument("--kind", default=None,
                   help="filter to one event kind")
    p.add_argument("--since", type=int, default=0,
                   help="only events with seq > SINCE")
    p.add_argument("--limit", type=int, default=100)
    sub.add_parser("metrics",
                   help="raw Prometheus text exposition "
                        "(same as gateway GET /metrics)")
    p = sub.add_parser("fault",
                       help="chaos fault sites: set/clear/list "
                            "deterministic fault schedules")
    p.add_argument("action", choices=["set", "clear", "list"])
    p.add_argument("site", nargs="?", default=None,
                   help="fault site name (e.g. store.append); "
                        "clear with no site disarms every site")
    p.add_argument("spec", nargs="?", default=None,
                   help="schedule: fail:N | prob:P[:SEED] | "
                        "delay:MS | torn:N[:SEED]")
    sub.add_parser("supervisor",
                   help="query supervision: pending restarts and "
                        "crash-loop breakers")
    p = sub.add_parser("locks",
                       help="lock-order witness: named locks, wait/"
                            "hold p50/p99, contention, order graph, "
                            "cycle reports")
    p.add_argument("--arm", action="store_true",
                   help="arm the witness at runtime")
    p.add_argument("--disarm", action="store_true",
                   help="disarm and forget witness state")
    args = ap.parse_args(argv)

    fn = globals()[f"cmd_{args.cmd.replace('-', '_')}"]
    stub = _stub(args)
    try:
        rows = fn(stub, args)
    except grpc.RpcError as e:
        print(f"error: {e.details()}", file=sys.stderr)
        return 1
    print(format_table(rows))
    return 0


if __name__ == "__main__":
    sys.exit(main())
