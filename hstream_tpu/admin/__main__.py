import sys

from hstream_tpu.admin import main

sys.exit(main())
