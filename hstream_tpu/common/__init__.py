from hstream_tpu.common.idgen import gen_unique
from hstream_tpu.common.records import (
    build_record,
    parse_record,
    payload_to_struct,
    record_to_dict,
    struct_to_dict,
    dict_to_struct,
    flatten_json,
)

__all__ = [
    "gen_unique",
    "build_record",
    "parse_record",
    "payload_to_struct",
    "record_to_dict",
    "struct_to_dict",
    "dict_to_struct",
    "flatten_json",
]
