"""Structured logging for hstream-tpu.

The reference uses a leveled, colored builder logger (common/HStream/Logger.hs);
here we configure the stdlib logger once with the same spirit: level control via
HSTREAM_LOG_LEVEL, compact single-line format with timestamps.
"""

from __future__ import annotations

import logging
import os
import sys

_FORMAT = "%(asctime)s.%(msecs)03d %(levelname).1s %(name)s: %(message)s"
_DATEFMT = "%H:%M:%S"

_configured = False


def get_logger(name: str) -> logging.Logger:
    global _configured
    if not _configured:
        level = os.environ.get("HSTREAM_LOG_LEVEL", "INFO").upper()
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(logging.Formatter(_FORMAT, _DATEFMT))
        root = logging.getLogger("hstream_tpu")
        root.addHandler(handler)
        root.setLevel(getattr(logging, level, logging.INFO))
        root.propagate = False
        _configured = True
    return logging.getLogger(f"hstream_tpu.{name}")
