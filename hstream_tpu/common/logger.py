"""Structured logging for hstream-tpu.

The reference uses a leveled, colored builder logger (common/HStream/Logger.hs);
here we configure the stdlib logger once with the same spirit: level control via
HSTREAM_LOG_LEVEL, compact single-line format with timestamps.

Request correlation (ISSUE 3): handlers bind the caller's request id
(gRPC metadata `x-request-id`, stamped by the client/gateway) into a
contextvar; a logging filter threads it into every record emitted while
the request runs, so one grep over the server log follows one request
across client -> gateway -> handler -> task launch.
"""

from __future__ import annotations

import contextlib
import contextvars
import logging
import os
import sys

_FORMAT = "%(asctime)s.%(msecs)03d %(levelname).1s %(name)s%(rid)s: " \
          "%(message)s"
_DATEFMT = "%H:%M:%S"

# the gRPC metadata key correlation ids travel under (client and
# gateway stamp it; handlers read it) — defined here so every layer
# shares one spelling
REQUEST_ID_KEY = "x-request-id"

_configured = False

# the active request's correlation id ("" outside any request)
_request_id: contextvars.ContextVar[str] = contextvars.ContextVar(
    "hstream_request_id", default="")


def set_request_id(rid: str | None):
    """Bind the current context's correlation id; returns the reset
    token (pass to reset_request_id when the request finishes)."""
    return _request_id.set(rid or "")


def reset_request_id(token) -> None:
    _request_id.reset(token)


def current_request_id() -> str:
    return _request_id.get()


@contextlib.contextmanager
def request_context(rid: str | None):
    """Scope a correlation id over a block (handler body)."""
    token = set_request_id(rid)
    try:
        yield
    finally:
        reset_request_id(token)


class _RequestIdFilter(logging.Filter):
    """Stamps `rid` (" [rid=...]" or "") onto every record so the
    format string can always reference it."""

    def filter(self, record: logging.LogRecord) -> bool:
        rid = _request_id.get()
        record.rid = f" [rid={rid}]" if rid else ""
        return True


def get_logger(name: str) -> logging.Logger:
    global _configured
    if not _configured:
        level = os.environ.get("HSTREAM_LOG_LEVEL", "INFO").upper()
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(logging.Formatter(_FORMAT, _DATEFMT))
        # on the HANDLER, not the logger: logger-level filters skip
        # records propagated up from child loggers; handler filters see
        # every record they format
        handler.addFilter(_RequestIdFilter())
        root = logging.getLogger("hstream_tpu")
        root.addHandler(handler)
        root.setLevel(getattr(logging, level, logging.INFO))
        root.propagate = False
        _configured = True
    return logging.getLogger(f"hstream_tpu.{name}")
