"""Unique id generation.

Snowflake-style 64-bit ids: 40 bits of milliseconds since a custom epoch,
14 bits of per-process sequence, 10 bits of node id — unique, roughly
time-ordered, and safe to mint concurrently. Mirrors the capability of the
reference's `genUnique` (common/HStream/Utils.hs:57-76) without copying its
exact bit split.
"""

from __future__ import annotations

import itertools
import os
import threading
import time

_EPOCH_MS = 1_577_836_800_000  # 2020-01-01T00:00:00Z

_SEQ_BITS = 14
_NODE_BITS = 10
_SEQ_MASK = (1 << _SEQ_BITS) - 1
_NODE_MASK = (1 << _NODE_BITS) - 1

_counter = itertools.count()
_node_id = (os.getpid() ^ (threading.get_ident() & 0xFFFF)) & _NODE_MASK


def gen_unique() -> int:
    """Return a fresh 64-bit id (time-ordered across one process)."""
    ms = int(time.time() * 1000) - _EPOCH_MS
    seq = next(_counter) & _SEQ_MASK
    return (ms << (_SEQ_BITS + _NODE_BITS)) | (seq << _NODE_BITS) | _node_id
