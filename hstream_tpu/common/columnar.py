"""Columnar batch payload: the high-throughput producer format.

A RAW-flagged HStreamRecord whose payload starts with the HSCB1 magic
carries a whole COLUMN-oriented event batch: one i64 timestamp array
plus named columns (f32 / i64 / bool / dictionary-encoded strings).
Appending one columnar record per micro-batch skips per-event protobuf
and JSON entirely — the server's query tasks detect the magic and feed
the columns straight into the jitted lattice step (engine ingest
contract), the path the 10M events/s target is specified against.

The reference's wire is one protobuf per event (BuildRecord.hs:28-70);
this is the TPU-first divergence SURVEY §7 prescribes ("protobuf decode
+ key dictionary off the critical path — columnar staging").

Layout: MAGIC | u32 header_len | header JSON | ts i64[n] | col bytes...
        | null-mask bytes (u8[n] per masked column, ISSUE 12)...
header: {"n": int, "cols": [[name, kind], ...], "dicts": {name: [str]},
         "nulls": [name, ...]}        # optional; names masks in order
kinds: "f32" | "i64" | "bool" | "str" (i32 ids into header dict)

The optional per-column null masks carry missing/NULL cells on the
wire (the framed append path's staging layout): a masked cell behaves
exactly like a field a per-record producer never sent. Payloads
without the "nulls" header key are the legacy layout — old producers
and old decoders interoperate unchanged.
"""

from __future__ import annotations

import json
from collections.abc import Sequence
from typing import Any, Mapping

import numpy as np

MAGIC = b"HSCB1\x00"

_KIND_DTYPE = {"f32": np.float32, "f64": np.float64, "i64": np.int64,
               "bool": np.uint8, "str": np.int32}


def is_columnar(payload: bytes) -> bool:
    return payload[: len(MAGIC)] == MAGIC


def encode_columnar(ts_ms: np.ndarray,
                    cols: Mapping[str, np.ndarray | list],
                    *, float_kind: str = "f32",
                    nulls: Mapping[str, np.ndarray] | None = None
                    ) -> bytes:
    """Columns -> payload bytes. String columns (lists or object/str
    arrays) are dictionary-encoded; numeric arrays are cast to
    f32/i64/bool. float_kind="f64" keeps float columns at full double
    precision (sink emission of host-finalized aggregates). `nulls`
    (name -> bool[n]) marks missing cells; masks ride after the column
    bytes and decode back via decode_columnar_nulls."""
    ts = np.ascontiguousarray(ts_ms, np.int64)
    n = len(ts)
    meta_cols: list[list[str]] = []
    dicts: dict[str, list[str]] = {}
    bufs: list[bytes] = [ts.tobytes()]
    for name, v in cols.items():
        arr = np.asarray(v)
        if arr.dtype.kind in ("U", "S", "O"):
            uniq, inv = np.unique(arr.astype(str), return_inverse=True)
            dicts[name] = uniq.tolist()
            data = inv.astype(np.int32)
            kind = "str"
        elif arr.dtype.kind == "b":
            data = arr.astype(np.uint8)
            kind = "bool"
        elif arr.dtype.kind in ("i", "u"):
            data = arr.astype(np.int64)
            kind = "i64"
        else:
            kind = float_kind
            data = arr.astype(_KIND_DTYPE[kind])
        if len(data) != n:
            raise ValueError(f"column {name!r} length {len(data)} != {n}")
        meta_cols.append([name, kind])
        bufs.append(np.ascontiguousarray(data).tobytes())
    meta = {"n": n, "cols": meta_cols, "dicts": dicts}
    if nulls:
        mask_names = []
        for name, m in nulls.items():
            if name not in cols:
                raise ValueError(
                    f"null mask for unknown column {name!r}")
            m = np.asarray(m, np.bool_)
            if len(m) != n:
                raise ValueError(
                    f"null mask {name!r} length {len(m)} != {n}")
            mask_names.append(name)
            bufs.append(np.ascontiguousarray(m, np.uint8).tobytes())
        meta["nulls"] = mask_names
    header = json.dumps(meta, separators=(",", ":")).encode()
    out = bytearray(MAGIC)
    out += np.uint32(len(header)).tobytes()
    out += header
    for b in bufs:
        out += b
    return bytes(out)


def decode_columnar_nulls(payload) -> tuple[np.ndarray, dict[str, Any],
                                            dict[str, np.ndarray] | None]:
    """payload -> (ts i64[n], {name: (kind, array, dict|None)},
    {name: bool[n]} | None).

    Arrays are zero-copy views into the payload where alignment allows;
    accepts bytes or a memoryview (the framed append path hands the
    frame's payload view straight in). Every declared size is checked
    against the actual bytes BEFORE any array is built — a forged or
    torn payload fails here, not deep inside the engine."""
    if not is_columnar(payload):
        raise ValueError("not a columnar payload")
    off = len(MAGIC)
    if len(payload) < off + 4:
        raise ValueError("truncated columnar header")
    hlen = int(np.frombuffer(payload, np.uint32, 1, off)[0])
    off += 4
    if len(payload) - off < hlen:
        raise ValueError("columnar header shorter than declared")
    try:
        header = json.loads(bytes(payload[off: off + hlen]))
    except ValueError as e:
        raise ValueError(f"bad columnar header JSON: {e}") from None
    off += hlen
    n = header["n"]
    # forged headers must fail HERE, not deep inside the engine: a
    # negative n would make frombuffer read "the rest", a giant n would
    # over-read; both are rejected by explicit bounds checks
    if not isinstance(n, int) or n < 0:
        raise ValueError(f"bad columnar n={n!r}")
    mask_names = header.get("nulls") or []
    col_names = [name for name, _kind in header["cols"]]
    if not isinstance(mask_names, list) \
            or not set(mask_names) <= set(col_names):
        raise ValueError("null masks name unknown columns")
    need = 8 * n + len(mask_names) * n
    for _, kind in header["cols"]:
        if kind not in _KIND_DTYPE:
            raise ValueError(f"unknown column kind {kind!r}")
        need += np.dtype(_KIND_DTYPE[kind]).itemsize * n
    if len(payload) - off < need:
        raise ValueError("columnar payload shorter than header claims")
    ts = np.frombuffer(payload, np.int64, n, off)
    off += 8 * n
    cols: dict[str, Any] = {}
    for name, kind in header["cols"]:
        dt = _KIND_DTYPE[kind]
        arr = np.frombuffer(payload, dt, n, off)
        off += arr.itemsize * n
        if kind == "bool":
            arr = arr.astype(np.bool_)
        d = header["dicts"].get(name)
        if kind == "str":
            if not isinstance(d, list):
                raise ValueError(f"string column {name!r} missing dict")
            if n and (int(arr.min()) < 0 or int(arr.max()) >= len(d)):
                raise ValueError(
                    f"string column {name!r} ids out of dict range")
        cols[name] = (kind, arr, d)
    nulls: dict[str, np.ndarray] | None = None
    if mask_names:
        nulls = {}
        for name in mask_names:
            nulls[name] = np.frombuffer(payload, np.uint8, n,
                                        off).astype(np.bool_)
            off += n
    if off != len(payload):
        # exact-bounds contract: trailing undeclared bytes mean either
        # a corrupt/forged block or a NEWER layout this decoder does
        # not understand — refusing beats silently misreading it (an
        # extension section ignored as junk could change row meaning,
        # exactly what unread null masks would have done)
        raise ValueError(
            f"columnar payload longer than header claims "
            f"({len(payload) - off} trailing bytes)")
    return ts, cols, nulls


def decode_columnar(payload) -> tuple[np.ndarray, dict[str, Any]]:
    """Legacy 2-tuple decode (ts, cols) — null masks, if any, dropped;
    null-aware consumers use decode_columnar_nulls."""
    ts, cols, _nulls = decode_columnar_nulls(payload)
    return ts, cols


def validate_block(payload) -> tuple[int, int]:
    """Bounds-check one columnar block withOUT materializing a single
    row: header sizes vs actual bytes, column kinds, string dict
    ranges, null-mask coverage (all via the zero-copy decode). Returns
    (n_rows, last_ts_ms). Raises ValueError on anything malformed —
    the ingress door (colframe.open_block) maps that to the typed
    INVALID_ARGUMENT refusal. Empty blocks are refused: an append of
    zero rows is a producer bug, not a no-op."""
    ts, _cols, _nulls = decode_columnar_nulls(payload)
    n = int(len(ts))
    if n == 0:
        raise ValueError("empty columnar block (n=0)")
    return n, int(ts[-1])


def to_rows(ts: np.ndarray, cols: dict,
            nulls: Mapping[str, np.ndarray] | None = None,
            *, drop_null: bool = False) -> list[dict[str, Any]]:
    """Materialize decoded columns back into per-row dicts (consumers
    that need row shape: joins, sessions, connectors, push-query
    streaming). `nulls` marks missing/null cells -> None. f64 columns
    (native JSON decode, sink emission) intify integral values, matching
    records.record_to_dict's Struct number decoding.

    drop_null=True omits null-masked cells from the row dicts instead of
    carrying explicit Nones — the shape the per-record decode path
    produces for a heterogeneous batch (a record never mentions columns
    it doesn't carry), so executors see the same rows regardless of how
    the producer batched its appends."""
    host = {}
    masks = {}
    for name, (kind, arr, d) in cols.items():
        if kind == "str":
            vals = [d[int(i)] for i in arr]
        elif kind == "f64":
            vals = [int(v) if v.is_integer() else v
                    for v in arr.tolist()]
        else:
            vals = arr.tolist()
        nm = nulls.get(name) if nulls else None
        if nm is not None and nm.any():
            if drop_null:
                masks[name] = nm.tolist()
            else:
                vals = [None if isnull else v
                        for v, isnull in zip(vals, nm.tolist())]
        host[name] = vals
    names = list(host)
    if not names:
        # empty-payload records still ARE records: n empty dicts, like
        # the per-record decode path (record_to_dict returns {})
        return [{} for _ in range(len(ts))]
    rows = [dict(zip(names, vals))
            for vals in zip(*(host[c] for c in names))]
    for name, mask in masks.items():
        for row, isnull in zip(rows, mask):
            if isnull:
                del row[name]
    return rows


def payload_rows(payload: bytes) -> list[dict[str, Any]] | None:
    """Rows from a RAW record payload when it carries a columnar batch;
    None when it is not columnar or is malformed (callers skip it, like
    any other unrecognized RAW record). The one shared expansion for
    every columnar-record consumer (push-query streaming, connectors,
    gateway)."""
    if not is_columnar(payload):
        return None
    try:
        ts, cols, nulls = decode_columnar_nulls(payload)
    except Exception:  # noqa: BLE001 — malformed payloads are skipped
        return None
    # drop_null: a masked cell is a field the producer never sent, so
    # the row shape matches the per-record decode path
    return to_rows(ts, cols, nulls, drop_null=True)


class ColumnarEmit(Sequence):
    """A batch of emitted aggregate rows kept COLUMNAR until the wire.

    The window-close path finalizes whole slot columns on device; this
    carries the result as named columns (numpy arrays, or object arrays
    for strings / TOPK lists) instead of N per-row dicts. Consumers that
    can stay columnar (the stream sink's columnar record, the native
    codec) read `.cols` / `to_payload()` directly; everything else sees
    a lazy Sequence of per-row dicts identical to the legacy list shape
    (len / bool / iterate / index / extend-into-a-list all work), so the
    row materialization happens at most once, at the first row-shaped
    consumer — ideally the wire boundary.
    """

    __slots__ = ("cols", "n", "_rows")

    def __init__(self, cols: Mapping[str, Any], n: int):
        self.cols = dict(cols)
        self.n = int(n)
        self._rows: list[dict[str, Any]] | None = None

    def __len__(self) -> int:
        return self.n

    def rows(self) -> list[dict[str, Any]]:
        """Materialize (and cache) the per-row dict view."""
        if self._rows is None:
            names = list(self.cols)
            if not names:
                self._rows = [{} for _ in range(self.n)]
            else:
                pyd = [v.tolist() if isinstance(v, np.ndarray) else list(v)
                       for v in self.cols.values()]
                self._rows = [dict(zip(names, vals))
                              for vals in zip(*pyd)]
        return self._rows

    def __getitem__(self, i):
        return self.rows()[i]

    def __iter__(self):
        return iter(self.rows())

    # list-concat ergonomics: emitted batches historically were plain
    # lists, so `acc += ex.process(...)` and `rows + more` must keep
    # working when either side is a columnar batch (materializes —
    # callers that care use extend_rows to stay columnar)
    def __add__(self, other):
        return self.rows() + list(other)

    def __radd__(self, other):
        return list(other) + self.rows()

    def __repr__(self) -> str:
        return (f"ColumnarEmit(n={self.n}, "
                f"cols={list(self.cols)})")

    def to_payload(self, ts_ms: int) -> bytes | None:
        """ONE columnar wire record for the whole batch, straight from
        the columns (no per-row dicts); None when a column is not
        wire-encodable (TOPK lists, mixed/None values) — the caller
        falls back to per-row records."""
        if self.n == 0:
            return None
        wire: dict[str, np.ndarray] = {}
        for name, v in self.cols.items():
            arr = np.asarray(v) if not isinstance(v, np.ndarray) else v
            if arr.dtype.kind == "O":
                if not all(isinstance(x, str) for x in arr.tolist()):
                    return None  # None / lists -> per-row records
            elif arr.dtype.kind == "f":
                arr = arr.astype(np.float64, copy=False)
            elif arr.dtype.kind not in ("i", "u", "b", "U", "S"):
                return None
            wire[name] = arr
        ts = np.full(self.n, int(ts_ms), np.int64)
        return encode_columnar(ts, wire, float_kind="f64")


def extend_rows(acc, rows):
    """Accumulate emitted row batches across pipeline stages while
    keeping a LONE ColumnarEmit columnar: acc is None | list |
    ColumnarEmit; returns the new accumulator. Only when a second batch
    arrives does the first materialize into a plain list — the common
    case (one close cycle per drain) reaches the sink columnar."""
    if rows is None or len(rows) == 0:
        return acc
    if acc is None or (isinstance(acc, list) and not acc):
        return rows
    if not isinstance(acc, list):
        acc = list(acc)
    acc.extend(rows)
    return acc


def rows_to_payload(rows: list[Mapping[str, Any]],
                    ts_ms: int) -> bytes | None:
    """One columnar payload for a homogeneous batch of flat scalar rows
    (the steady-state changelog / window-close output), or None when the
    rows are not uniformly shaped (heterogeneous keys, NULLs, list
    values like TOPK) — the caller falls back to per-row records.

    Emitting the sink batch as ONE columnar record instead of N protobuf
    Structs keeps the server's emit stage off the per-row Python path
    (the reference serializes one protobuf per sunk record,
    HStore.hs:152-163). A ColumnarEmit batch encodes straight from its
    columns — no per-row dicts at all."""
    if isinstance(rows, ColumnarEmit):
        return rows.to_payload(ts_ms)
    if not rows:
        return None
    names = list(rows[0])
    nlen = len(names)
    if any(len(r) != nlen for r in rows):
        return None
    cols: dict[str, Any] = {}
    try:
        for c in names:
            vals = [r[c] for r in rows]
            v0 = vals[0]
            if isinstance(v0, bool):
                if not all(isinstance(v, bool) for v in vals):
                    return None
                cols[c] = np.asarray(vals, np.bool_)
            elif isinstance(v0, int):
                if not all(type(v) is int for v in vals):
                    # ints mixed with floats -> f64 keeps exactness of
                    # both (i64 would truncate, f32 would round counts)
                    if not all(isinstance(v, (int, float))
                               and not isinstance(v, bool) for v in vals):
                        return None
                    cols[c] = np.asarray(vals, np.float64)
                else:
                    cols[c] = np.asarray(vals, np.int64)
            elif isinstance(v0, float):
                if not all(isinstance(v, (int, float))
                           and not isinstance(v, bool) for v in vals):
                    return None
                cols[c] = np.asarray(vals, np.float64)
            elif isinstance(v0, str):
                if not all(isinstance(v, str) for v in vals):
                    return None
                cols[c] = np.asarray(vals, object)
            else:
                return None  # None / lists / nested -> per-row records
    except (KeyError, OverflowError):
        return None
    ts = np.full(len(rows), ts_ms, np.int64)
    return encode_columnar(ts, cols, float_kind="f64")
