"""ctypes binding for the native batch record decoder (engine/cpp/
jsondec.cpp): a whole appended batch of HStreamRecord payloads ->
columnar arrays in one C++ pass.

Feeds the server's JSON ingest (server/tasks._ingest_results): per-record
protobuf + Struct decode in Python costs ~8us/record — at changelog
rates that IS the query loop (SURVEY §7 "protobuf decode + key
dictionary off the critical path"). Falls back to None when no
toolchain is available; callers keep the pure-Python path.
"""

from __future__ import annotations

import ctypes as C
import os
import threading
from typing import Any

import numpy as np

from hstream_tpu.common.nativebuild import build_so

_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SRC = os.path.join(_DIR, "engine", "cpp", "jsondec.cpp")
SO = os.path.join(_DIR, "engine", "cpp", "libjsondec.so")

_lock = threading.Lock()
_lib: C.CDLL | None = None
_tried = False

_p_u8 = C.POINTER(C.c_uint8)
_p_i32 = C.POINTER(C.c_int32)
_p_i64 = C.POINTER(C.c_int64)
_p_f64 = C.POINTER(C.c_double)

# record classes (jsondec.cpp)
CLS_JSON = 0   # decoded into columns
CLS_RAW = 1    # RAW-flagged record: route by payload magic in Python
CLS_PY = 2     # Python fallback (nested values, type conflicts, bad bytes)


def load() -> C.CDLL | None:
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        try:
            lib = C.CDLL(build_so(SRC, SO, opt="-O3"))
        except Exception:
            return None
        lib.jd_scan.argtypes = [_p_u8, _p_i64, C.c_int64, _p_i64,
                                _p_i64, _p_u8]
        lib.jd_scan.restype = C.c_void_p
        lib.jd_ncols.argtypes = [C.c_void_p]
        lib.jd_ncols.restype = C.c_int64
        lib.jd_col_meta.argtypes = [C.c_void_p, C.c_int64, C.c_char_p,
                                    _p_i32, _p_i32, _p_i32, _p_i64]
        lib.jd_col_data.argtypes = [C.c_void_p, C.c_int64, _p_f64,
                                    _p_i32, _p_u8, _p_u8]
        lib.jd_dict_data.argtypes = [C.c_void_p, C.c_int64, _p_u8,
                                     _p_i32]
        lib.jd_free.argtypes = [C.c_void_p]
        _lib = lib
        return _lib


def _ptr(arr: np.ndarray, ctype):
    return arr.ctypes.data_as(ctype)


def decode_batch(payloads: list[bytes], default_ts: np.ndarray):
    """Batch-decode appended record payloads.

    Returns (ts i64[n], cls u8[n], cols, nulls) where cols maps column
    name -> (kind, array, dict|None) in the decode_columnar shape
    (kinds: "f64" | "str" | "bool") and nulls maps name -> bool[n]
    missing/null mask. None when the native library is unavailable.
    Rows with cls != CLS_JSON have null entries in every column; the
    caller routes them to the Python path by class.
    """
    lib = load()
    if lib is None:
        return None
    n = len(payloads)
    offs = np.zeros(n + 1, np.int64)
    for i, p in enumerate(payloads):
        offs[i + 1] = offs[i] + len(p)
    buf = b"".join(payloads)
    ts = np.empty(n, np.int64)
    cls = np.empty(n, np.uint8)
    dts = np.ascontiguousarray(default_ts, np.int64)
    h = lib.jd_scan(C.cast(C.c_char_p(buf), _p_u8), _ptr(offs, _p_i64),
                    n, _ptr(dts, _p_i64), _ptr(ts, _p_i64),
                    _ptr(cls, _p_u8))
    try:
        cols: dict[str, Any] = {}
        nulls: dict[str, np.ndarray] = {}
        name_buf = C.create_string_buffer(256)
        name_len = C.c_int32()
        ctype = C.c_int32()
        ndict = C.c_int32()
        dict_bytes = C.c_int64()
        for i in range(lib.jd_ncols(h)):
            lib.jd_col_meta(h, i, name_buf, C.byref(name_len),
                            C.byref(ctype), C.byref(ndict),
                            C.byref(dict_bytes))
            name = name_buf.raw[:name_len.value].decode("utf-8",
                                                        "replace")
            t = ctype.value
            msk = np.empty(n, np.uint8)
            if t == 1:  # string
                sids = np.empty(n, np.int32)
                lib.jd_col_data(h, i, None, _ptr(sids, _p_i32), None,
                                _ptr(msk, _p_u8))
                nd = ndict.value
                concat = np.empty(max(dict_bytes.value, 1), np.uint8)
                lens = np.empty(max(nd, 1), np.int32)
                lib.jd_dict_data(h, i, _ptr(concat, _p_u8),
                                 _ptr(lens, _p_i32))
                d: list[str] = []
                off = 0
                raw = concat.tobytes()
                for j in range(nd):
                    ln = int(lens[j])
                    d.append(raw[off:off + ln].decode("utf-8", "replace"))
                    off += ln
                cols[name] = ("str", sids, d)
            elif t == 2:  # bool
                bools = np.empty(n, np.uint8)
                lib.jd_col_data(h, i, None, None, _ptr(bools, _p_u8),
                                _ptr(msk, _p_u8))
                cols[name] = ("bool", bools.astype(np.bool_), None)
            else:  # num, or -1 == all-null (shape as num)
                nums = np.empty(n, np.float64)
                lib.jd_col_data(h, i, _ptr(nums, _p_f64), None, None,
                                _ptr(msk, _p_u8))
                cols[name] = ("f64", nums, None)
            nulls[name] = msk.astype(np.bool_)
    finally:
        lib.jd_free(h)
    return ts, cls, cols, nulls
