"""Seeded jittered exponential backoff, shared by every retry loop
that must not spin hot (follower reconnect, query supervision).

One formula so a fix to the jitter/cap/floor semantics reaches every
caller: ``base * 2^attempt`` capped, +/- uniform jitter from the
caller's seeded RNG (chaos runs replay the same wait sequence)."""
from __future__ import annotations

import random

__all__ = ["jittered_backoff"]


def jittered_backoff(attempt: int, *, base: float, cap: float,
                     jitter: float, rng: random.Random,
                     floor: float = 0.0, max_exp: int = 16) -> float:
    """Wait before retry ``attempt`` (zero-based: the first retry is
    attempt 0). ``max_exp`` bounds the exponent so a long outage can't
    overflow the float before ``cap`` clamps it."""
    b = min(base * (2.0 ** min(max(attempt, 0), max_exp)), cap)
    span = b * jitter
    return max(floor, b + rng.uniform(-span, span))
