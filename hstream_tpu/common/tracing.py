"""First-class step tracing (SURVEY §5.1).

The reference has no tracing at all — its closest artifact is a
logDebug inside the poll loop (Processor.hs:131-133). Here every query
task records per-batch stage timings (decode, key-encode, device step,
emission, snapshot) into a bounded ring per query, cheap enough to stay
always-on: one perf_counter pair per stage, no allocation beyond the
ring slot.

`trace_span(tracer, stage)` is the instrumentation point;
`QueryTracer.summary()` aggregates count/total/mean/p50/p95 per stage
for the admin surface (admin CLI `trace` command, HTTP /queries/<id>).
`jax_profiler(path)` wraps jax.profiler.trace for deep device profiles
(TensorBoard format) when an operator asks for one.
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import defaultdict, deque


class QueryTracer:
    """Bounded per-stage duration rings for one query.

    `observer(stage, seconds)` (optional) is invoked on every record —
    the hook the stats holder's stage-latency histograms ride, so the
    rings stay self-contained while /metrics sees every span.
    `request_id` carries the correlation id of the request that created
    the query (ISSUE 3), surfaced by summary() / admin trace."""

    def __init__(self, capacity: int = 512, *, observer=None):
        self._cap = capacity
        self._rings: dict[str, deque[float]] = defaultdict(
            lambda: deque(maxlen=capacity))
        self._counts: dict[str, int] = defaultdict(int)
        self._totals: dict[str, float] = defaultdict(float)
        self._lock = threading.Lock()
        self._observer = observer
        self.request_id: str | None = None

    def record(self, stage: str, seconds: float) -> None:
        with self._lock:
            self._rings[stage].append(seconds)
            self._counts[stage] += 1
            self._totals[stage] += seconds
        if self._observer is not None:
            try:
                self._observer(stage, seconds)
            except Exception:  # noqa: BLE001 — observers are metrics
                pass           # plumbing; never fail the traced stage

    def summary(self) -> dict[str, dict[str, float]]:
        """stage -> {count, total_ms, mean_ms, p50_ms, p95_ms} over the
        ring (percentiles) and lifetime (count/total)."""
        out: dict[str, dict[str, float]] = {}
        with self._lock:
            for stage, ring in self._rings.items():
                if not ring:
                    continue
                xs = sorted(ring)
                n = len(xs)
                out[stage] = {
                    "count": self._counts[stage],
                    "total_ms": round(self._totals[stage] * 1e3, 3),
                    "mean_ms": round(
                        self._totals[stage] / self._counts[stage] * 1e3,
                        3),
                    "p50_ms": round(xs[n // 2] * 1e3, 3),
                    "p95_ms": round(xs[min(n - 1, (n * 95) // 100)] * 1e3,
                                    3),
                }
        if self.request_id:
            out["request"] = {"id": self.request_id}
        return out


@contextlib.contextmanager
def trace_span(tracer: QueryTracer | None, stage: str):
    """Time a stage into the tracer; no-op when tracer is None."""
    if tracer is None:
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        tracer.record(stage, time.perf_counter() - t0)


@contextlib.contextmanager
def jax_profiler(log_dir: str):
    """Deep device profile (TensorBoard trace format) around a block —
    the jax.profiler hook SURVEY §5.1 prescribes."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


# ---- recompile guard (ISSUE 7) ----------------------------------------------
#
# The hot-path contracts (one fused dispatch per cycle, pow2-padded
# shapes sharing compiled programs, lru_cache'd kernel factories) all
# cash out as ONE observable: steady-state batches compile ZERO new XLA
# executables. The static passes (tools/analyze: dispatch/retrace)
# check the idioms; RetraceGuard checks the outcome at runtime by
# counting backend compiles via jax.monitoring — the
# '/jax/core/compile/backend_compile_duration' event fires exactly once
# per executable build (incl. the tiny utility jits jnp allocations
# create, which steady loops must also not re-trigger).
#
# One process-wide listener is registered lazily and dispatches to
# every active guard plus the optional stats sink — jax.monitoring has
# no unregister, so guards attach/detach through the module-level set
# instead of the listener itself.

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_active_guards: set["RetraceGuard"] = set()
_guard_lock = threading.Lock()
# weakrefs: a ServerContext torn down mid-process (tests spin up many)
# must not be kept alive by the process-wide listener
_stats_sinks: list[tuple[object, str]] = []  # (weakref to holder, stream)
_listener_installed = False


def _ensure_compile_listener() -> None:
    global _listener_installed
    with _guard_lock:
        if _listener_installed:
            return
        import jax.monitoring

        def _on_event(event: str, duration: float, **kw) -> None:
            if event != _COMPILE_EVENT:
                return
            with _guard_lock:
                guards = list(_active_guards)
                sinks = list(_stats_sinks)
            for g in guards:
                g._bump()
            dead = []
            for ref, stream in sinks:
                stats = ref()
                if stats is None:
                    dead.append((ref, stream))
                    continue
                try:
                    stats.stream_stat_add("kernel_recompiles", stream)
                except Exception:  # noqa: BLE001 — monitoring must
                    pass           # never break a compile
            if dead:
                with _guard_lock:
                    for ent in dead:
                        if ent in _stats_sinks:
                            _stats_sinks.remove(ent)

        jax.monitoring.register_event_duration_secs_listener(_on_event)
        _listener_installed = True


def install_recompile_counter(stats, stream: str = "_process") -> None:
    """Bump the `kernel_recompiles` per-stream counter on every XLA
    compile in this process — the /metrics face of the retrace
    contract. Idempotent per (holder, stream)."""
    import weakref

    _ensure_compile_listener()
    with _guard_lock:
        if not any(ref() is stats and s == stream
                   for ref, s in _stats_sinks):
            _stats_sinks.append((weakref.ref(stats), stream))


class RetraceGuard:
    """Counts XLA executable builds while active.

    Usage (tests, bench):

        with RetraceGuard() as g:
            for batch in batches:
                ex.process_columnar(...)
        assert g.count == 0   # steady state must not recompile

    `count` is exact: one per backend compile anywhere in the process
    while the guard is active (guards are process-global, like the
    compiles they observe — do not run two guarded regions
    concurrently and expect per-region attribution)."""

    def __init__(self):
        self.count = 0
        self._lock = threading.Lock()

    def _bump(self) -> None:
        with self._lock:
            self.count += 1

    def __enter__(self) -> "RetraceGuard":
        _ensure_compile_listener()
        with _guard_lock:
            _active_guards.add(self)
        return self

    def __exit__(self, *exc) -> None:
        with _guard_lock:
            _active_guards.discard(self)
