"""First-class step tracing (SURVEY §5.1).

The reference has no tracing at all — its closest artifact is a
logDebug inside the poll loop (Processor.hs:131-133). Here every query
task records per-batch stage timings (decode, key-encode, device step,
emission, snapshot) into a bounded ring per query, cheap enough to stay
always-on: one perf_counter pair per stage, no allocation beyond the
ring slot.

`trace_span(tracer, stage)` is the instrumentation point;
`QueryTracer.summary()` aggregates count/total/mean/p50/p95 per stage
for the admin surface (admin CLI `trace` command, HTTP /queries/<id>).
`jax_profiler(path)` wraps jax.profiler.trace for deep device profiles
(TensorBoard format) when an operator asks for one.

ISSUE 13 grows the request-id correlation into cross-component trace
spans: `SpanCollector` keeps bounded per-scope rings of completed spans
(trace id + span id + parent), exported as Chrome trace-event JSON via
`GET /queries/<id>/trace` / `admin trace --spans`. The trace id IS the
request id (already propagated client -> gateway -> handler), so one
sampled request's journey — RPC handler, append-front stages, the
query task's pipeline stages, subscription delivery — shares one id.
Disarmed cost is ONE attribute read + one branch (`collector.active`,
the FlowGovernor / FAULTS discipline); the sampling decision is a
deterministic hash of the trace id so every component agrees without
coordination.
"""

from __future__ import annotations

import contextlib
import contextvars
import threading
import time
import uuid
import zlib
from collections import defaultdict, deque, OrderedDict

from hstream_tpu.stats.devicecost import DEVICE_TIME as _DEVICE_TIME


class QueryTracer:
    """Bounded per-stage duration rings for one query.

    `observer(stage, seconds)` (optional) is invoked on every record —
    the hook the stats holder's stage-latency histograms ride, so the
    rings stay self-contained while /metrics sees every span.
    `request_id` carries the correlation id of the request that created
    the query (ISSUE 3), surfaced by summary() / admin trace."""

    def __init__(self, capacity: int = 512, *, observer=None):
        self._cap = capacity
        self._rings: dict[str, deque[float]] = defaultdict(
            lambda: deque(maxlen=capacity))
        self._counts: dict[str, int] = defaultdict(int)
        self._totals: dict[str, float] = defaultdict(float)
        self._lock = threading.Lock()
        self._observer = observer
        self.request_id: str | None = None
        # cross-component trace binding (ISSUE 13): when the request
        # that created this query was SAMPLED, every completed stage
        # timing also lands as a span in the collector's per-query
        # ring, under the creating request's trace id. Unbound cost:
        # one attribute read + one branch per record().
        self._spans: "SpanCollector | None" = None
        self._span_scope: str | None = None
        self._trace_id: str | None = None
        self._parent_span: str = ""

    def bind_trace(self, collector: "SpanCollector", *, scope: str,
                   trace_id: str, parent_id: str = "") -> None:
        """Attach this tracer's stage timings to a sampled trace: spans
        land in `collector` under `scope` (the query id), parented on
        the creating request's handler span."""
        self._span_scope = scope
        self._trace_id = trace_id
        self._parent_span = parent_id
        self._spans = collector

    def record(self, stage: str, seconds: float) -> None:
        with self._lock:
            self._rings[stage].append(seconds)
            self._counts[stage] += 1
            self._totals[stage] += seconds
        if self._observer is not None:
            try:
                self._observer(stage, seconds)
            except Exception:  # noqa: BLE001 — observers are metrics
                pass           # plumbing; never fail the traced stage
        spans = self._spans
        if spans is not None:
            try:
                dur_ms = seconds * 1e3
                spans.record_span(
                    self._span_scope, stage,
                    trace_id=self._trace_id, span_id=new_span_id(),
                    parent_id=self._parent_span,
                    t0_ms=time.time() * 1e3 - dur_ms, dur_ms=dur_ms)
            except Exception:  # noqa: BLE001 — span plumbing must
                pass           # never fail the traced stage

    def summary(self) -> dict[str, dict[str, float]]:
        """stage -> {count, total_ms, mean_ms, p50_ms, p95_ms} over the
        ring (percentiles) and lifetime (count/total)."""
        out: dict[str, dict[str, float]] = {}
        with self._lock:
            for stage, ring in self._rings.items():
                if not ring:
                    continue
                xs = sorted(ring)
                n = len(xs)
                out[stage] = {
                    "count": self._counts[stage],
                    "total_ms": round(self._totals[stage] * 1e3, 3),
                    "mean_ms": round(
                        self._totals[stage] / self._counts[stage] * 1e3,
                        3),
                    "p50_ms": round(xs[n // 2] * 1e3, 3),
                    "p95_ms": round(xs[min(n - 1, (n * 95) // 100)] * 1e3,
                                    3),
                }
        if self.request_id:
            out["request"] = {"id": self.request_id}
        return out


@contextlib.contextmanager
def trace_span(tracer: QueryTracer | None, stage: str):
    """Time a stage into the tracer; no-op when tracer is None."""
    if tracer is None:
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        tracer.record(stage, time.perf_counter() - t0)


# ---- cross-component trace spans (ISSUE 13) --------------------------------

# gRPC metadata / HTTP header keys the trace context travels under.
# The trace id itself rides the existing x-request-id; only the parent
# span id needs a new key.
TRACE_ID_KEY = "x-trace-id"
PARENT_SPAN_KEY = "x-parent-span"

# THE declared stage vocabulary: every span name / trace_span stage /
# append-stage literal must come from this set. The analyzer registry
# pass cross-checks call sites against it (a renamed stage would
# otherwise silently orphan its stage_latency_ms series and its spans).
TRACE_STAGES = frozenset({
    # query-task pipeline stages (QueryTracer rings + stage_latency_ms)
    "decode", "key_encode", "step", "emit", "snapshot", "close",
    # framed-append stages (handlers.APPEND_STAGES)
    "append_decode", "append_admit", "append_handoff", "append_store",
    # RPC entry span + the freshness lag taxonomy (freshness_lag_ms
    # stage labels double as span names where a span exists)
    "rpc", "ingest", "engine", "delivery",
})

# kernel dispatch families (per-family dispatch histograms + recompile
# attribution) — also cross-checked by the analyzer registry pass
KERNEL_FAMILIES = frozenset({"step", "close", "probe", "session"})


def new_span_id() -> str:
    return uuid.uuid4().hex[:12]


# the active span (trace_id, span_id) of the current request, bound by
# the handler wrapper so nested instrumentation (append stages,
# subscription delivery) can parent its spans without plumbing
_span_ctx: "contextvars.ContextVar[tuple[str, str] | None]" = \
    contextvars.ContextVar("hstream_span", default=None)


def current_span() -> tuple[str, str] | None:
    """(trace_id, span_id) of the active sampled request, or None."""
    return _span_ctx.get()


@contextlib.contextmanager
def span_scope(trace_id: str, span_id: str):
    token = _span_ctx.set((trace_id, span_id))
    try:
        yield
    finally:
        _span_ctx.reset(token)


class SpanCollector:
    """Bounded per-scope rings of completed spans + the sampling knob.

    A scope is the unit of export: a query id (`GET
    /queries/<id>/trace`), a stream name (append-path spans), or a
    subscription id (delivery spans). Rings are bounded per scope AND
    the scope set itself is LRU-bounded, so a client looping over
    random stream names cannot grow the collector without bound.

    `active` is a plain attribute (False at sample rate 0) — the
    disarmed hot-path cost is one attribute read + one branch, the
    FlowGovernor / FAULTS discipline; `bench.py --smoke` gates that
    arming the collector compiles nothing."""

    def __init__(self, sample_rate: float = 0.0, *,
                 ring_capacity: int = 512, max_scopes: int = 256):
        self.sample_rate = max(0.0, min(float(sample_rate), 1.0))
        self.active = self.sample_rate > 0.0
        self._cap = int(ring_capacity)
        self._max_scopes = int(max_scopes)
        self._rings: "OrderedDict[str, deque]" = OrderedDict()
        self._lock = threading.Lock()

    def sampled(self, trace_id: str) -> bool:
        """Deterministic per-trace sampling decision: every component
        hashing the same trace id reaches the same verdict, so a trace
        is recorded whole or not at all."""
        if not self.active or not trace_id:
            return False
        if self.sample_rate >= 1.0:
            return True
        return (zlib.crc32(trace_id.encode()) % 10_000
                < self.sample_rate * 10_000)

    def record_span(self, scope: str, stage: str, *, trace_id: str,
                    span_id: str, parent_id: str = "",
                    t0_ms: float, dur_ms: float, **attrs) -> None:
        """Append one completed span to the scope's ring. `t0_ms` is
        wall epoch milliseconds; attrs must be JSON-serializable."""
        span = {"stage": stage, "trace_id": trace_id,
                "span_id": span_id, "parent_id": parent_id,
                "t0_ms": round(float(t0_ms), 3),
                "dur_ms": round(float(dur_ms), 3)}
        if attrs:
            span["attrs"] = attrs
        with self._lock:
            ring = self._rings.get(scope)
            if ring is None:
                while len(self._rings) >= self._max_scopes:
                    self._rings.popitem(last=False)  # LRU scope bound
                ring = deque(maxlen=self._cap)
                self._rings[scope] = ring
            else:
                self._rings.move_to_end(scope)
            ring.append(span)

    def spans(self, scope: str) -> list[dict]:
        with self._lock:
            ring = self._rings.get(scope)
            return list(ring) if ring is not None else []

    def scopes(self) -> list[str]:
        with self._lock:
            return sorted(self._rings)

    def export_chrome(self, scope: str) -> dict:
        """The scope's ring as Chrome trace-event JSON (load in
        chrome://tracing or Perfetto): complete ("ph": "X") events,
        microsecond timestamps, trace/span ids in args."""
        events = []
        for s in self.spans(scope):
            events.append({
                "name": s["stage"],
                "cat": "hstream",
                "ph": "X",
                "ts": round(s["t0_ms"] * 1000.0, 1),   # us
                "dur": max(round(s["dur_ms"] * 1000.0, 1), 1),
                "pid": 1,
                "tid": scope,
                "args": {"trace_id": s["trace_id"],
                         "span_id": s["span_id"],
                         "parent_id": s["parent_id"],
                         **s.get("attrs", {})},
            })
        return {"traceEvents": events, "displayTimeUnit": "ms"}


# ---- kernel dispatch families (ISSUE 13 tentpole c) ------------------------
#
# One thread-local scope names the kernel family currently being
# dispatched on this thread. jit compiles synchronously inside the
# first call, so the process-wide compile listener reads the scope to
# attribute a recompile to the factory family that triggered it —
# RetraceGuard's listener otherwise collapses everything into one
# undifferentiated count.

_family_tls = threading.local()


def current_kernel_family() -> str | None:
    return getattr(_family_tls, "name", None)


@contextlib.contextmanager
def kernel_family(family: str, observer=None, *, ready=None):
    """Scope a kernel dispatch under a family name. When `observer`
    (a callable (family, seconds)) is set, the dispatch's host time
    lands there — the per-family dispatch-time histograms ride this.
    Cost with no observer: two thread-local attribute writes.

    `ready` (ISSUE 18) — a zero-arg callable returning the dispatch's
    live device values — opts the site into the device-time sampler:
    on a deterministically sampled dispatch the values are fenced
    (block-until-ready BEFORE the body drains in-flight work), the
    body runs, and a second block-until-ready bounds the device
    execution time into `kernel_device_ms{family}`. Disarmed cost is
    one attribute read + one branch (the FAULTS / FlowGovernor
    discipline); the disarmed sampler records zero state."""
    prev = getattr(_family_tls, "name", None)
    _family_tls.name = family
    sampled = (ready is not None and _DEVICE_TIME.active
               and _DEVICE_TIME.tick(family))
    if sampled:
        try:
            _DEVICE_TIME.fence(ready)
        except Exception:  # noqa: BLE001 — sampling must never fail
            sampled = False    # a dispatch
    t0 = time.perf_counter() \
        if (observer is not None or sampled) else 0.0
    try:
        yield
    finally:
        _family_tls.name = prev
        if sampled:
            try:
                _DEVICE_TIME.measure(family, ready, t0)
            except Exception:  # noqa: BLE001 — sampling must never
                pass           # fail a dispatch
        if observer is not None:
            try:
                observer(family, time.perf_counter() - t0)
            except Exception:  # noqa: BLE001 — observers are metrics
                pass           # plumbing; never fail a dispatch


@contextlib.contextmanager
def jax_profiler(log_dir: str):
    """Deep device profile (TensorBoard trace format) around a block —
    the jax.profiler hook SURVEY §5.1 prescribes."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


# ---- recompile guard (ISSUE 7) ----------------------------------------------
#
# The hot-path contracts (one fused dispatch per cycle, pow2-padded
# shapes sharing compiled programs, lru_cache'd kernel factories) all
# cash out as ONE observable: steady-state batches compile ZERO new XLA
# executables. The static passes (tools/analyze: dispatch/retrace)
# check the idioms; RetraceGuard checks the outcome at runtime by
# counting backend compiles via jax.monitoring — the
# '/jax/core/compile/backend_compile_duration' event fires exactly once
# per executable build (incl. the tiny utility jits jnp allocations
# create, which steady loops must also not re-trigger).
#
# One process-wide listener is registered lazily and dispatches to
# every active guard plus the optional stats sink — jax.monitoring has
# no unregister, so guards attach/detach through the module-level set
# instead of the listener itself.

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_active_guards: set["RetraceGuard"] = set()
_guard_lock = threading.Lock()
# weakrefs: a ServerContext torn down mid-process (tests spin up many)
# must not be kept alive by the process-wide listener
_stats_sinks: list[tuple[object, str]] = []  # (weakref to holder, stream)
_listener_installed = False


def _ensure_compile_listener() -> None:
    global _listener_installed
    with _guard_lock:
        if _listener_installed:
            return
        import jax.monitoring

        def _on_event(event: str, duration: float, **kw) -> None:
            if event != _COMPILE_EVENT:
                return
            with _guard_lock:
                guards = list(_active_guards)
                sinks = list(_stats_sinks)
            for g in guards:
                g._bump()
            # stream attribution (ISSUE 13 satellite): a compile seen
            # while a NAMED guard is active counts against that guard's
            # stream (the query/bench scope being driven), not the
            # sink's default "_process" pseudo-stream — previously every
            # recompile collapsed into _process and per-query recompile
            # evidence was unrecoverable
            names = sorted({g.name for g in guards if g.name})
            # factory attribution: jit compiles synchronously inside
            # the triggering call, so the dispatching thread's
            # kernel_family scope names the factory family
            family = current_kernel_family()
            dead = []
            for ref, stream in sinks:
                stats = ref()
                if stats is None:
                    dead.append((ref, stream))
                    continue
                try:
                    for target in (names or [stream]):
                        stats.stream_stat_add("kernel_recompiles",
                                              target)
                    if family:
                        stats.stream_stat_add("factory_recompiles",
                                              family)
                except Exception:  # noqa: BLE001 — monitoring must
                    pass           # never break a compile
            if dead:
                with _guard_lock:
                    for ent in dead:
                        if ent in _stats_sinks:
                            _stats_sinks.remove(ent)

        jax.monitoring.register_event_duration_secs_listener(_on_event)
        _listener_installed = True


def install_recompile_counter(stats, stream: str = "_process") -> None:
    """Bump the `kernel_recompiles` per-stream counter on every XLA
    compile in this process — the /metrics face of the retrace
    contract. Idempotent per (holder, stream)."""
    import weakref

    _ensure_compile_listener()
    with _guard_lock:
        if not any(ref() is stats and s == stream
                   for ref, s in _stats_sinks):
            _stats_sinks.append((weakref.ref(stats), stream))


class RetraceGuard:
    """Counts XLA executable builds while active.

    Usage (tests, bench):

        with RetraceGuard() as g:
            for batch in batches:
                ex.process_columnar(...)
        assert g.count == 0   # steady state must not recompile

    `count` is exact: one per backend compile anywhere in the process
    while the guard is active (guards are process-global, like the
    compiles they observe — do not run two guarded regions
    concurrently and expect per-region attribution).

    `name` (optional) attributes compiles observed while this guard is
    active to that stream in every installed stats sink — the query id
    or bench scope being driven — instead of the sink's default
    `_process` pseudo-stream (ISSUE 13)."""

    def __init__(self, name: str | None = None):
        self.count = 0
        self.name = name
        self._lock = threading.Lock()

    def _bump(self) -> None:
        with self._lock:
            self.count += 1

    def __enter__(self) -> "RetraceGuard":
        _ensure_compile_listener()
        with _guard_lock:
            _active_guards.add(self)
        return self

    def __exit__(self, *exc) -> None:
        with _guard_lock:
            _active_guards.discard(self)
