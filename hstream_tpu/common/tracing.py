"""First-class step tracing (SURVEY §5.1).

The reference has no tracing at all — its closest artifact is a
logDebug inside the poll loop (Processor.hs:131-133). Here every query
task records per-batch stage timings (decode, key-encode, device step,
emission, snapshot) into a bounded ring per query, cheap enough to stay
always-on: one perf_counter pair per stage, no allocation beyond the
ring slot.

`trace_span(tracer, stage)` is the instrumentation point;
`QueryTracer.summary()` aggregates count/total/mean/p50/p95 per stage
for the admin surface (admin CLI `trace` command, HTTP /queries/<id>).
`jax_profiler(path)` wraps jax.profiler.trace for deep device profiles
(TensorBoard format) when an operator asks for one.
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import defaultdict, deque


class QueryTracer:
    """Bounded per-stage duration rings for one query.

    `observer(stage, seconds)` (optional) is invoked on every record —
    the hook the stats holder's stage-latency histograms ride, so the
    rings stay self-contained while /metrics sees every span.
    `request_id` carries the correlation id of the request that created
    the query (ISSUE 3), surfaced by summary() / admin trace."""

    def __init__(self, capacity: int = 512, *, observer=None):
        self._cap = capacity
        self._rings: dict[str, deque[float]] = defaultdict(
            lambda: deque(maxlen=capacity))
        self._counts: dict[str, int] = defaultdict(int)
        self._totals: dict[str, float] = defaultdict(float)
        self._lock = threading.Lock()
        self._observer = observer
        self.request_id: str | None = None

    def record(self, stage: str, seconds: float) -> None:
        with self._lock:
            self._rings[stage].append(seconds)
            self._counts[stage] += 1
            self._totals[stage] += seconds
        if self._observer is not None:
            try:
                self._observer(stage, seconds)
            except Exception:  # noqa: BLE001 — observers are metrics
                pass           # plumbing; never fail the traced stage

    def summary(self) -> dict[str, dict[str, float]]:
        """stage -> {count, total_ms, mean_ms, p50_ms, p95_ms} over the
        ring (percentiles) and lifetime (count/total)."""
        out: dict[str, dict[str, float]] = {}
        with self._lock:
            for stage, ring in self._rings.items():
                if not ring:
                    continue
                xs = sorted(ring)
                n = len(xs)
                out[stage] = {
                    "count": self._counts[stage],
                    "total_ms": round(self._totals[stage] * 1e3, 3),
                    "mean_ms": round(
                        self._totals[stage] / self._counts[stage] * 1e3,
                        3),
                    "p50_ms": round(xs[n // 2] * 1e3, 3),
                    "p95_ms": round(xs[min(n - 1, (n * 95) // 100)] * 1e3,
                                    3),
                }
        if self.request_id:
            out["request"] = {"id": self.request_id}
        return out


@contextlib.contextmanager
def trace_span(tracer: QueryTracer | None, stage: str):
    """Time a stage into the tracer; no-op when tracer is None."""
    if tracer is None:
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        tracer.record(stage, time.perf_counter() - t0)


@contextlib.contextmanager
def jax_profiler(log_dir: str):
    """Deep device profile (TensorBoard trace format) around a block —
    the jax.profiler hook SURVEY §5.1 prescribes."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
