"""Record codec and JSON helpers.

Mirrors the capability of the reference's record plumbing
(common/HStream/Utils/BuildRecord.hs:28-70 builds/parses `HStreamRecord`
protobufs with a publish timestamp; common/HStream/Utils.hs:42-55 flattens
nested JSON for connector sinks). Payloads flagged JSON carry a serialized
`google.protobuf.Struct`.
"""

from __future__ import annotations

import time
from typing import Any, Mapping

from google.protobuf import struct_pb2

from hstream_tpu.proto import api_pb2 as pb


def now_ms() -> int:
    return int(time.time() * 1000)


def dict_to_struct(d: Mapping[str, Any]) -> struct_pb2.Struct:
    s = struct_pb2.Struct()
    # Struct.update handles nested dicts/lists/scalars.
    s.update(d)
    return s


def _value_to_py(v: struct_pb2.Value) -> Any:
    kind = v.WhichOneof("kind")
    if kind == "null_value":
        return None
    if kind == "number_value":
        n = v.number_value
        return int(n) if float(n).is_integer() else n
    if kind == "string_value":
        return v.string_value
    if kind == "bool_value":
        return v.bool_value
    if kind == "struct_value":
        return struct_to_dict(v.struct_value)
    if kind == "list_value":
        return [_value_to_py(x) for x in v.list_value.values]
    return None


def struct_to_dict(s: struct_pb2.Struct) -> dict[str, Any]:
    """Struct -> plain dict, decoding integral floats back to ints."""
    return {k: _value_to_py(v) for k, v in s.fields.items()}


def build_record(
    payload: Mapping[str, Any] | bytes,
    *,
    key: str = "",
    attributes: Mapping[str, str] | None = None,
    publish_time_ms: int | None = None,
) -> pb.HStreamRecord:
    """Build an HStreamRecord. A mapping payload is encoded as a JSON Struct;
    bytes are carried raw."""
    if isinstance(payload, (bytes, bytearray)):
        flag = pb.RECORD_FLAG_RAW
        body = bytes(payload)
    else:
        flag = pb.RECORD_FLAG_JSON
        body = dict_to_struct(payload).SerializeToString()
    header = pb.HStreamRecordHeader(
        flag=flag,
        publish_time_ms=now_ms() if publish_time_ms is None else publish_time_ms,
        key=key,
    )
    if attributes:
        header.attributes.update(attributes)
    return pb.HStreamRecord(header=header, payload=body)


def build_columnar_record(ts_ms, cols, *, key: str = "") -> pb.HStreamRecord:
    """One RAW record carrying a whole columnar event batch (the
    high-throughput producer path — common/columnar.py)."""
    from hstream_tpu.common import columnar

    payload = columnar.encode_columnar(ts_ms, cols)
    last = int(ts_ms[-1]) if len(ts_ms) else None
    return build_record(payload, key=key, publish_time_ms=last)


def parse_record(data: bytes) -> pb.HStreamRecord:
    return pb.HStreamRecord.FromString(data)


def payload_to_struct(rec: pb.HStreamRecord) -> struct_pb2.Struct | None:
    """Decode a JSON-flagged record's payload; None for raw records."""
    if rec.header.flag != pb.RECORD_FLAG_JSON:
        return None
    return struct_pb2.Struct.FromString(rec.payload)


def record_to_dict(rec: pb.HStreamRecord) -> dict[str, Any] | None:
    s = payload_to_struct(rec)
    return None if s is None else struct_to_dict(s)


def flatten_json(d: Mapping[str, Any], *, sep: str = ".") -> dict[str, Any]:
    """Flatten nested objects: {"a": {"b": 1}} -> {"a.b": 1}.

    Used by relational sinks (MySQL/ClickHouse) which need flat columns,
    matching the reference's flattening of nested JSON objects."""
    out: dict[str, Any] = {}
    for k, v in d.items():
        if isinstance(v, Mapping):
            for kk, vv in flatten_json(v, sep=sep).items():
                out[f"{k}{sep}{kk}"] = vv
        else:
            out[k] = v
    return out
