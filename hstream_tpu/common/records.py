"""Record codec and JSON helpers.

Mirrors the capability of the reference's record plumbing
(common/HStream/Utils/BuildRecord.hs:28-70 builds/parses `HStreamRecord`
protobufs with a publish timestamp; common/HStream/Utils.hs:42-55 flattens
nested JSON for connector sinks). Payloads flagged JSON carry a serialized
`google.protobuf.Struct`.
"""

from __future__ import annotations

import time
from typing import Any, Mapping

from google.protobuf import struct_pb2

from hstream_tpu.proto import api_pb2 as pb


def now_ms() -> int:
    return int(time.time() * 1000)


def dict_to_struct(d: Mapping[str, Any]) -> struct_pb2.Struct:
    s = struct_pb2.Struct()
    # Struct.update handles nested dicts/lists/scalars.
    s.update(d)
    return s


def _value_to_py(v: struct_pb2.Value) -> Any:
    kind = v.WhichOneof("kind")
    if kind == "null_value":
        return None
    if kind == "number_value":
        n = v.number_value
        return int(n) if float(n).is_integer() else n
    if kind == "string_value":
        return v.string_value
    if kind == "bool_value":
        return v.bool_value
    if kind == "struct_value":
        return struct_to_dict(v.struct_value)
    if kind == "list_value":
        return [_value_to_py(x) for x in v.list_value.values]
    return None


def struct_to_dict(s: struct_pb2.Struct) -> dict[str, Any]:
    """Struct -> plain dict, decoding integral floats back to ints."""
    return {k: _value_to_py(v) for k, v in s.fields.items()}


def build_record(
    payload: Mapping[str, Any] | bytes,
    *,
    key: str = "",
    attributes: Mapping[str, str] | None = None,
    publish_time_ms: int | None = None,
) -> pb.HStreamRecord:
    """Build an HStreamRecord. A mapping payload is encoded as a JSON Struct;
    bytes are carried raw."""
    if isinstance(payload, (bytes, bytearray)):
        flag = pb.RECORD_FLAG_RAW
        body = bytes(payload)
    else:
        flag = pb.RECORD_FLAG_JSON
        body = dict_to_struct(payload).SerializeToString()
    header = pb.HStreamRecordHeader(
        flag=flag,
        publish_time_ms=now_ms() if publish_time_ms is None else publish_time_ms,
        key=key,
    )
    if attributes:
        header.attributes.update(attributes)
    return pb.HStreamRecord(header=header, payload=body)


def build_columnar_record(ts_ms, cols, *, key: str = "") -> pb.HStreamRecord:
    """One RAW record carrying a whole columnar event batch (the
    high-throughput producer path — common/columnar.py)."""
    from hstream_tpu.common import columnar

    payload = columnar.encode_columnar(ts_ms, cols)
    last = int(ts_ms[-1]) if len(ts_ms) else None
    return build_record(payload, key=key, publish_time_ms=last)


def parse_record(data: bytes) -> pb.HStreamRecord:
    return pb.HStreamRecord.FromString(data)


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _splice_record(header_bytes: bytes, payload) -> bytes:
    """Serialized HStreamRecord from an already-serialized header and
    raw payload bytes: field 1 (header submessage) + field 2 (payload
    bytes), spliced by hand so the — possibly megabytes-large — payload
    is never walked by protobuf. Parses identically to
    HStreamRecord(header=..., payload=...).SerializeToString()."""
    if not len(payload):
        return b"\x0a" + _varint(len(header_bytes)) + header_bytes
    # one join so the payload is copied exactly once (a bytearray
    # build would copy it again at the final bytes() conversion)
    return b"".join((b"\x0a", _varint(len(header_bytes)), header_bytes,
                     b"\x12", _varint(len(payload)), payload))


def wrap_raw_record(payload, publish_time_ms: int) -> bytes:
    """One RAW record's wire bytes around an existing payload (the
    framed append path: the validated columnar block goes to the store
    with ONE header serialize + one memcpy — no protobuf round-trip)."""
    header = pb.HStreamRecordHeader(
        flag=pb.RECORD_FLAG_RAW,
        publish_time_ms=int(publish_time_ms)).SerializeToString()
    return _splice_record(header, payload)


# payloads below this take the plain SerializeToString path: the splice
# only pays off once the payload memcpy dominates the message walk
_SPLICE_MIN_PAYLOAD = 4096


def _read_uvarint(mv, off: int) -> tuple[int, int]:
    val = 0
    shift = 0
    while True:
        b = mv[off]
        off += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            return val, off
        shift += 7
        if shift > 63:
            raise ValueError("varint too long")


# contract: dispatches<=0 fetches<=0
def peek_columnar_payload(data) -> memoryview | None:
    """Zero-copy view of the columnar payload inside a serialized
    RAW-flagged HStreamRecord, or None when the record is anything else
    (or the quick walk can't be sure — the caller falls back to the
    full protobuf parse). The read-side half of the wire-speed ingest
    contract (ISSUE 12): a columnar record travels socket -> store ->
    staging ring without protobuf ever walking its megabytes — at
    bunched columnar arrival the per-record parse plus the batch
    classifier walk were ~40% of the task thread's time."""
    mv = memoryview(data)
    end = len(mv)
    header = None
    payload = None
    off = 0
    try:
        while off < end:
            tag = mv[off]
            off += 1
            if tag == 0x0A:    # field 1: header submessage
                ln, off = _read_uvarint(mv, off)
                header = mv[off:off + ln]
                off += ln
            elif tag == 0x12:  # field 2: payload bytes
                ln, off = _read_uvarint(mv, off)
                payload = mv[off:off + ln]
                off += ln
            else:
                return None    # unexpected field: not ours to judge
    except (IndexError, ValueError):
        return None
    if off != end or payload is None:
        return None
    from hstream_tpu.common import columnar

    if not columnar.is_columnar(payload):
        return None
    if header is not None and len(header):
        # the header is tiny — confirm the RAW flag the long way so a
        # JSON record whose Struct bytes open with the magic can't
        # masquerade as a column batch
        try:
            h = pb.HStreamRecordHeader.FromString(bytes(header))
        except Exception:  # noqa: BLE001 — undecodable: full parse
            return None
        if h.flag != pb.RECORD_FLAG_RAW:
            return None
    return payload


def record_bytes(r: pb.HStreamRecord, *, default_ts: int | None = None
                 ) -> bytes:
    """Wire bytes for an incoming record, stamping `default_ts` into
    the header ONLY when the record carries no publish time (the Append
    satellite, ISSUE 12): an already-stamped record is never mutated,
    and large payloads (columnar batches) are spliced around a
    header-only serialize instead of re-walked whole."""
    if default_ts is not None and not r.header.publish_time_ms:
        r.header.publish_time_ms = default_ts
    if len(r.payload) < _SPLICE_MIN_PAYLOAD:
        return r.SerializeToString()
    return _splice_record(r.header.SerializeToString(), r.payload)


def payload_to_struct(rec: pb.HStreamRecord) -> struct_pb2.Struct | None:
    """Decode a JSON-flagged record's payload; None for raw records."""
    if rec.header.flag != pb.RECORD_FLAG_JSON:
        return None
    return struct_pb2.Struct.FromString(rec.payload)


def record_to_dict(rec: pb.HStreamRecord) -> dict[str, Any] | None:
    s = payload_to_struct(rec)
    return None if s is None else struct_to_dict(s)


def flatten_json(d: Mapping[str, Any], *, sep: str = ".") -> dict[str, Any]:
    """Flatten nested objects: {"a": {"b": 1}} -> {"a.b": 1}.

    Used by relational sinks (MySQL/ClickHouse) which need flat columns,
    matching the reference's flattening of nested JSON objects."""
    out: dict[str, Any] = {}
    for k, v in d.items():
        if isinstance(v, Mapping):
            for kk, vv in flatten_json(v, sep=sep).items():
                out[f"{k}{sep}{kk}"] = vv
        else:
            out[k] = v
    return out
