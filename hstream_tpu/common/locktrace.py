"""Runtime lock-order witness: named locks, held-sets, cycle reports.

The static `lockorder` pass proves the acquisition orders it can SEE
are acyclic — but call chains it cannot type (callbacks, ctx objects
threaded through parameters, native readers) and instance-level
inversions (two locks of one class taken in both orders on different
objects) are invisible to any AST. GoodLock (Havelund) and the kernel's
lockdep close that gap at runtime: maintain each thread's held-set,
grow a global lock-order graph on every acquire-while-holding, and
report a POTENTIAL deadlock the moment the second edge direction
appears — no need for the unlucky schedule that actually deadlocks.

``TracedLock`` is a named wrapper around ``threading.Lock``/``RLock``
adopted by the high-risk subsystems (append front, supervisor,
replica, subscriptions, gateway, query tasks). Names are lock ROLES
(lockdep "lock classes"): every instance of a subsystem shares one
node, so the graph stays small and order rules read like the
documentation ("tasks.state before views.materialization").

Disarmed cost is the FAULTS / FlowGovernor discipline: ``acquire``
pays one attribute read + one branch per registry (LOCKTRACE and
FAULTS) and delegates straight to the inner lock — no held-set, no
graph, no timing. Arm with ``HSTREAM_LOCKTRACE=1`` / server
``--locktrace`` / ``admin locks --arm``; then every acquire maintains
the held-set and graph, ``lock_wait_ms``/``lock_hold_ms`` histograms
and the ``lock_contention`` counter feed /metrics, a detected cycle
journals a ``lock_cycle`` event, and ``admin locks`` renders the
per-lock ledger.

Every traced acquire is also a fault site ``lock.acquire.<name>`` —
the seeded interleaving perturber (``faultinject`` ``yield:N[:SEED]``
schedules) injects deterministic scheduler yields exactly where the
witness watches, so the chaos scenarios explore adversarial
interleavings with the deadlock detector armed.

Semantics notes (unit-tested):

  * re-entrant acquisition of one RLock instance adds no edge and no
    double entry (depth-counted per thread);
  * same-NAME different-instance nesting adds no edge either — a
    self-edge on a lock class needs instance identity to mean
    anything, and the static pass already skips it for the same
    reason;
  * ``threading.Condition(TracedLock(...))`` stays fully traced: the
    condition releases/reacquires through the wrapper, so the held-set
    correctly excludes the lock while waiting.
"""

from __future__ import annotations

import os
import threading
import time

from hstream_tpu.common.faultinject import FAULTS
from hstream_tpu.common.logger import get_logger

log = get_logger("locktrace")

ENV_VAR = "HSTREAM_LOCKTRACE"
SITE_PREFIX = "lock.acquire."


class LockTraceRegistry:
    """Process-wide witness state: per-thread held stacks, the
    lock-order graph, per-lock accounting, and reported cycles.

    ``active`` is a plain attribute read unlocked on the hot path
    (same idiom as ``FAULTS.active``); all mutation happens under the
    registry's own plain (untraced) lock."""

    def __init__(self) -> None:
        self.active = False
        self._mu = threading.Lock()
        self._tls = threading.local()
        # held-set generation: disarm() bumps it, and _held() discards
        # any thread's stack tagged with an older generation — so an
        # acquire that straddled a disarm can never leave a stale
        # holder that fabricates edges after a re-arm
        self._gen = 0
        # src name -> {dst names}; witness per edge (first occurrence)
        self._edges: dict[str, set[str]] = {}
        self._witness: dict[tuple[str, str], dict] = {}
        self._cycles: list[dict] = []
        self._cycle_keys: set[frozenset] = set()
        # name -> {"acquires": n, "contentions": n}
        self._counts: dict[str, dict[str, int]] = {}
        self._stats = None   # StatsHolder (bound by ServerContext)
        self._events = None  # EventJournal

    # ---- configuration -----------------------------------------------------

    def bind(self, *, stats=None, events=None) -> None:
        if stats is not None:
            self._stats = stats
        if events is not None:
            self._events = events

    def arm(self) -> None:
        self.active = True
        log.warning("lock-order witness armed")

    def disarm(self) -> None:
        """Disarm and forget: graph, witnesses, counts, cycles. Held
        stacks of live threads are dropped too (generation bump — a
        stack tagged pre-disarm is discarded at its next use), so a
        later re-arm starts from scratch: mid-critical-section arming
        tolerates missing outer holders, which only costs edges, never
        fabricates false ones."""
        with self._mu:
            self.active = False
            self._gen += 1
            self._edges.clear()
            self._witness.clear()
            self._cycles.clear()
            self._cycle_keys.clear()
            self._counts.clear()
        self._tls = threading.local()

    def load_env(self, env: str | None = None) -> bool:
        raw = (env if env is not None
               else os.environ.get(ENV_VAR, "")).strip().lower()
        if raw in ("1", "true", "on", "yes"):
            self.arm()
            return True
        return False

    # ---- witness core ------------------------------------------------------

    def _held(self) -> list:
        ent = getattr(self._tls, "held", None)
        if ent is None or ent[0] != self._gen:
            ent = (self._gen, [])
            self._tls.held = ent
        return ent[1]

    def note_acquire(self, lock: "TracedLock", wait_s: float,
                     contended: bool) -> None:
        """Armed-path bookkeeping after the inner lock is taken."""
        # re-check under no lock: an acquire that passed the wrapper's
        # gate just before a disarm must not record into the fresh
        # state (its release will run disarmed and never pair up)
        if not self.active:
            return
        held = self._held()
        for ent in held:
            if ent[0] is lock:
                ent[2] += 1  # re-entrant: depth only, no edge
                return
        name = lock.name
        new_cycle = None
        with self._mu:
            c = self._counts.setdefault(
                name, {"acquires": 0, "contentions": 0})
            c["acquires"] += 1
            if contended:
                c["contentions"] += 1
            for ent in held:
                src = ent[0].name
                if src == name:
                    continue  # same lock class on another instance
                outs = self._edges.setdefault(src, set())
                if name in outs:
                    continue
                outs.add(name)
                self._witness[(src, name)] = {
                    "thread": threading.current_thread().name,
                    "holding": [e[0].name for e in held],
                }
                ring = self._find_cycle(src, name)
                if ring is not None:
                    key = frozenset(n for e in ring for n in e)
                    if key not in self._cycle_keys:
                        self._cycle_keys.add(key)
                        new_cycle = {
                            "ring": [list(e) for e in ring],
                            "witness": {f"{a}->{b}": self._witness[(a, b)]
                                        for a, b in ring},
                        }
                        self._cycles.append(new_cycle)
        held.append([lock, time.perf_counter(), 1])
        stats = self._stats
        if stats is not None:
            try:
                stats.observe("lock_wait_ms", name, wait_s * 1e3)
                if contended:
                    stats.stream_stat_add("lock_contention", name)
            except Exception:  # noqa: BLE001 — metrics plumbing must
                pass           # never fail an acquire
        if new_cycle is not None:
            self._report_cycle(new_cycle)

    def note_release(self, lock: "TracedLock") -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] is lock:
                held[i][2] -= 1
                if held[i][2] > 0:
                    return
                t0 = held[i][1]
                del held[i]
                stats = self._stats
                if stats is not None:
                    try:
                        stats.observe("lock_hold_ms", lock.name,
                                      (time.perf_counter() - t0) * 1e3)
                    except Exception:  # noqa: BLE001
                        pass
                return
        # release of a lock acquired before arming: nothing tracked

    def _find_cycle(self, src: str, dst: str
                    ) -> list[tuple[str, str]] | None:
        """Caller holds self._mu. The edge src->dst was just added:
        a path dst ->* src closes a ring."""
        prev: dict[str, str | None] = {dst: None}
        queue = [dst]
        while queue:
            cur = queue.pop(0)
            if cur == src:
                break
            for nxt in sorted(self._edges.get(cur, ())):
                if nxt not in prev:
                    prev[nxt] = cur
                    queue.append(nxt)
        if src not in prev:
            return None
        chain = [src]
        cur = src
        while prev[cur] is not None:
            cur = prev[cur]
            chain.append(cur)
        chain.reverse()  # dst, ..., src
        return [(src, dst)] + [(chain[i], chain[i + 1])
                               for i in range(len(chain) - 1)]

    def _report_cycle(self, cycle: dict) -> None:
        ring = cycle["ring"]
        ring_str = " -> ".join([e[0] for e in ring] + [ring[0][0]])
        log.error("POTENTIAL DEADLOCK: lock-order cycle %s "
                  "(witness: %s)", ring_str, cycle["witness"])
        events = self._events
        if events is not None:
            try:
                events.append(
                    "lock_cycle",
                    f"lock-order cycle detected: {ring_str}",
                    ring=ring_str, witness=cycle["witness"])
            except Exception:  # noqa: BLE001 — journaling must never
                pass           # alter witness behavior

    # ---- introspection -----------------------------------------------------

    def edge_count(self) -> int:
        with self._mu:
            return sum(len(v) for v in self._edges.values())

    def cycles(self) -> list[dict]:
        with self._mu:
            return [dict(c) for c in self._cycles]

    def status(self) -> dict:
        """The `admin locks` payload: armed state, per-lock counters
        (+ wait/hold percentiles when a StatsHolder is bound), the
        order graph, and any cycle reports. The percentiles come from
        the bound holder's histograms and are PROCESS-LIFETIME
        cumulative — disarm forgets the graph and counts but does not
        rewind /metrics (histograms are monotone by contract)."""
        with self._mu:
            counts = {n: dict(c) for n, c in self._counts.items()}
            edges = {a: sorted(b) for a, b in self._edges.items() if b}
            cycles = [dict(c) for c in self._cycles]
        stats = self._stats
        locks: dict[str, dict] = {}
        for name, c in sorted(counts.items()):
            row = dict(c)
            if stats is not None:
                for metric, key in (("lock_wait_ms", "wait"),
                                    ("lock_hold_ms", "hold")):
                    for q in (50, 99):
                        try:
                            v = stats.histogram_percentile(
                                metric, name, q)
                        except Exception:  # noqa: BLE001
                            v = None
                        row[f"{key}_p{q}_ms"] = (round(v, 3)
                                                 if v is not None
                                                 else None)
            locks[name] = row
        return {"armed": self.active, "locks": locks,
                "edges": edges, "cycles": cycles}


LOCKTRACE = LockTraceRegistry()


class TracedLock:
    """Named lock wrapper; see the module docstring. Use the
    :func:`lock` / :func:`rlock` constructors."""

    def __init__(self, name: str, *, reentrant: bool = False):
        # _reentrant FIRST: __getattr__ reads it, and it must resolve
        # before any other attribute lookup can fall through
        self._reentrant = reentrant
        self.name = name
        self.site = SITE_PREFIX + name
        self._inner = (threading.RLock() if reentrant
                       else threading.Lock())

    # contract: dispatches<=0 fetches<=0
    def acquire(self, blocking: bool = True, timeout: float = -1
                ) -> bool:
        # the seeded interleaving perturber hooks every traced acquire
        # (one attribute read + one branch when no faults are armed)
        if FAULTS.active:
            FAULTS.point(self.site)
        if not LOCKTRACE.active:
            return self._inner.acquire(blocking, timeout)
        if not blocking:
            got = self._inner.acquire(False)
            if got:
                LOCKTRACE.note_acquire(self, 0.0, contended=False)
            return got
        t0 = time.perf_counter()
        contended = False
        if not self._inner.acquire(False):
            contended = True
            if not self._inner.acquire(True, timeout):
                return False
        LOCKTRACE.note_acquire(self, time.perf_counter() - t0,
                               contended=contended)
        return True

    # contract: dispatches<=0 fetches<=0
    def release(self) -> None:
        # note BEFORE the inner release: the hold ends when the owner
        # decides to let go, and noting after would race the next
        # owner's acquire bookkeeping for this thread's entry
        if LOCKTRACE.active:
            LOCKTRACE.note_release(self)
        self._inner.release()

    def __enter__(self) -> "TracedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        inner_locked = getattr(self._inner, "locked", None)
        return inner_locked() if inner_locked is not None else False

    # Condition-protocol forwarding, REENTRANT wrappers only. A
    # Condition over an RLock must see a real _release_save /
    # _acquire_restore / _is_owned (recursion counts); a PLAIN lock
    # must NOT expose them — Condition probes the attributes at
    # construction (try/except AttributeError) and falls back to the
    # wrapper's traced acquire/release, so existence is conditional
    # via __getattr__, not methods that raise at call time.
    def __getattr__(self, name: str):
        if self._reentrant:
            if name == "_release_save":
                return self._traced_release_save
            if name == "_acquire_restore":
                return self._traced_acquire_restore
            if name == "_is_owned":
                return self._inner._is_owned
        raise AttributeError(name)

    def _traced_release_save(self):
        # the wait window drops the held-set entry (the lock really is
        # released while waiting — edges formed then would be false)
        if LOCKTRACE.active:
            LOCKTRACE.note_release(self)
        return self._inner._release_save()

    def _traced_acquire_restore(self, state) -> None:
        self._inner._acquire_restore(state)
        if LOCKTRACE.active:
            LOCKTRACE.note_acquire(self, 0.0, contended=False)


def lock(name: str) -> TracedLock:
    """Named traced mutex (threading.Lock semantics)."""
    return TracedLock(name)


def rlock(name: str) -> TracedLock:
    """Named traced re-entrant mutex (threading.RLock semantics)."""
    return TracedLock(name, reentrant=True)


def lock_list(name: str, n: int) -> list[TracedLock]:
    """A lock FAMILY sharing one name (e.g. append-front lanes)."""
    return [TracedLock(name) for _ in range(max(int(n), 1))]
