"""Framed columnar append blocks: the wire-speed ingest fast path.

The protobuf Append path costs one full ``HStreamRecord`` parse on the
gRPC boundary plus one re-``SerializeToString()`` per record before the
bytes reach the store — at columnar batch sizes (megabytes per
micro-batch) that host staging work, not the engine, bounds the served
ingest rate (BENCH_r05: kernel 22.6M ev/s, served 1.04M). The framed
path ships the staging layout itself: the client encodes exactly the
columnar block the encode workers already consume (``HSCB1``: ts vector
+ named fixed-width columns + null masks, ``common/columnar.py``),
wrapped in a 13-byte frame the server can bounds-check WITHOUT
materializing a single row. The server's whole job is: check the frame,
check the block's declared sizes against its actual bytes, splice a
precomputed record header around the payload (one memcpy — no protobuf
walk), and hand the bytes to the append front.

Frame layout (little-endian)::

    MAGIC "HSAF" | u8 version | u32 payload_len | u32 crc32(payload)
    | payload (one HSCB1 columnar block)

The version byte gates evolution: a frame with an unknown version is a
typed INVALID_ARGUMENT refusal, never a guess. ``payload_len`` must
match the remaining bytes EXACTLY — a truncated (torn) or overlong
frame is refused before any byte is appended. The CRC catches torn
writes that happen to preserve the length (the ``faultinject`` torn
schedule cuts mid-payload); integrity is checked at the ingress door so
a corrupt frame can never become a partially-ingested batch.
"""

from __future__ import annotations

import struct
import zlib

from hstream_tpu.common.errors import InvalidFrame

FRAME_MAGIC = b"HSAF"
FRAME_VERSION = 1
# MAGIC(4) + version(1) + payload_len(4) + crc32(4)
FRAME_HEADER_LEN = 13

_HEAD = struct.Struct("<4sBII")


def encode_frame(payload: bytes) -> bytes:
    """Wrap one columnar block (``columnar.encode_columnar`` bytes) in
    the append frame. The producer-side half of the wire format."""
    payload = bytes(payload)
    return _HEAD.pack(FRAME_MAGIC, FRAME_VERSION, len(payload),
                      zlib.crc32(payload)) + payload


# contract: dispatches<=0 fetches<=0
def open_frame(frame: bytes) -> memoryview:
    """Validate a frame and return a zero-copy view of its payload.

    Every malformed shape — short header, wrong magic, unknown version,
    truncated/overlong body, CRC mismatch — raises the typed
    ``InvalidFrame`` (gRPC INVALID_ARGUMENT): the contract is refuse
    loudly at the door, never a partial ingest."""
    mv = memoryview(frame)
    if len(mv) < FRAME_HEADER_LEN:
        raise InvalidFrame(
            f"frame shorter than the {FRAME_HEADER_LEN}-byte header "
            f"({len(mv)} bytes)")
    magic, version, plen, crc = _HEAD.unpack_from(mv, 0)
    if magic != FRAME_MAGIC:
        raise InvalidFrame(f"bad frame magic {bytes(magic)!r}")
    if version != FRAME_VERSION:
        raise InvalidFrame(
            f"unsupported frame version {version} "
            f"(this server speaks version {FRAME_VERSION})")
    body = mv[FRAME_HEADER_LEN:]
    if len(body) != plen:
        kind = "truncated" if len(body) < plen else "overlong"
        raise InvalidFrame(
            f"{kind} frame: header declares {plen} payload bytes, "
            f"{len(body)} present")
    if zlib.crc32(body) != crc:
        raise InvalidFrame("frame CRC mismatch (torn or corrupt bytes)")
    return body


# contract: dispatches<=0 fetches<=0
def open_block(frame: bytes) -> tuple[memoryview, int, int]:
    """Frame -> (payload view, n_rows, last_ts_ms), fully validated:
    the frame envelope (open_frame) AND the embedded columnar block's
    declared sizes (columnar.validate_block). The ONE door every framed
    append passes through — after this returns, the payload is exactly
    the columnar record the query tasks already decode."""
    from hstream_tpu.common import columnar

    payload = open_frame(frame)
    try:
        n, last_ts = columnar.validate_block(payload)
    except (ValueError, KeyError, TypeError) as e:
        raise InvalidFrame(f"bad columnar block: {e}") from e
    return payload, n, last_ts
