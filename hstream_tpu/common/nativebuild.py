"""Shared build-on-demand for native shared libraries.

One canonical g++ invocation for every cbits-style source in the tree
(store/cpp/nstore.cpp, engine/cpp/encode.cpp) — the dev-friendly
analogue of the reference's cabal cxx-sources builds."""

from __future__ import annotations

import os
import subprocess
import threading

_lock = threading.Lock()


def build_so(src: str, so: str, *, libs: tuple[str, ...] = (),
             opt: str = "-O2", force: bool = False) -> str:
    """Compile `src` -> `so` if stale; returns the .so path."""
    with _lock:
        if (not force and os.path.exists(so)
                and os.path.getmtime(so) >= os.path.getmtime(src)):
            return so
        tmp = so + ".tmp"
        cmd = ["g++", "-std=c++17", opt, "-fPIC", "-shared", "-pthread",
               src, "-o", tmp] + [f"-l{lib}" for lib in libs]
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"native build of {os.path.basename(src)} failed:\n"
                f"{proc.stderr[-4000:]}")
        os.replace(tmp, so)
        return so
