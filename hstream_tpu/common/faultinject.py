"""Deterministic fault injection: named sites, seeded schedules.

Production log/stream systems gate replication and recovery changes
behind a chaos harness (LogDevice's failure simulations, Kafka's
Trogdor): every failure mode the recovery code claims to handle must be
*provokable on demand*, deterministically, in a test. Before this
module the tree had exactly one injection point (``stop(crash=True)``);
everything else — torn snapshot writes, follower flaps, corrupt
checkpoint files, device activation failures — could only happen for
real.

A **fault site** is a named host-side probe compiled into the code
path it guards::

    from hstream_tpu.common.faultinject import FAULTS
    ...
    if FAULTS.active:                  # one-branch no-op when inactive
        FAULTS.point("store.append")   # may raise / delay

``FAULTS.active`` is a plain attribute that is False unless at least
one site is armed — the same hot-path discipline as
``FlowGovernor.active`` (ingress pays one attribute read + one branch,
no locks, no allocation). Torn-write sites use ``mutate`` which
passes bytes through unchanged when inactive::

    blob = FAULTS.mutate("snapshot.persist", blob)

Schedules are **deterministic**: fail-Nth counts invocations; the
probability schedule draws from a per-site ``random.Random(seed)``;
torn-write truncation picks its cut point from the same seeded stream.
Re-running a chaos test with the same seed injects the same faults at
the same hits.

Spec grammar (env var ``HSTREAM_FAULTS``, admin ``fault-set``, tests):

    fail:N            raise InjectedFault on the Nth hit (1-based), once
    fail:N:K          raise on hits N, N+1, ... N+K-1 (K consecutive)
    prob:P[:SEED]     raise with probability P per hit (seeded RNG)
    delay:MS[:N]      sleep MS milliseconds on every hit (or only hit N)
    torn:N[:SEED]     mutate(): truncate the Nth write at a seeded point
    yield:N[:SEED]    interleaving perturber (ISSUE 14): on ~1/N of
                      hits (seeded RNG, deterministic decision stream)
                      sleep a seeded sub-millisecond jitter — a forced
                      scheduler yield that explores adversarial thread
                      interleavings at the site; armed at the
                      lock.acquire.* sites with the locktrace witness,
                      this is the seeded schedule-perturbation harness

``HSTREAM_FAULTS="store.append=fail:3;snapshot.persist=torn:2:7"``
arms two sites for the whole process. The registry is process-global
(fault sites live in layers that never see a ServerContext); a
ServerContext binds its event journal so every injection lands as a
``fault_injected`` event.

Instrumented sites (the registry accepts any name; these exist today):

    store.append            leader/local append path (memstore)
    store.read              reader poll (memstore)
    store.oplog.apply       replica op application (leader + follower)
    store.follower.connect  leader-side sender (re)connect to a follower
    store.follower.ack      leader-side Replicate RPC entry
    replica.heartbeat.drop  leader-side idle heartbeat (lease expiry)
    replica.partition       follower-side Replicate entry: the follower
                            is unreachable from its leader (the RPC
                            fails before the epoch/bind checks)
    replica.promote.race    follower-side Promote entry (widens the
                            dueling-promotion race window)
    snapshot.persist        operator-state blob write (mutate: torn)
    snapshot.restore        operator-state blob read at task start
    checkpoint.flush        checkpoint store write (mutate: torn)
    device.dispatch         staged lattice step dispatch
    device.fetch            deferred close/changelog D2H drain
    device.activate         device-join / fused-close kernel activation
    device.session.dispatch session step / extract kernel dispatch
    device.session.activate session arena activation + host migration
    task.step               query-task ingest of one read chunk
    rpc.handler             unary gRPC handler entry
    lock.acquire.<name>     every TracedLock acquire (common/locktrace):
                            one site per lock ROLE — appendfront.submit,
                            scheduler.supervisor, tasks.state, ... —
                            the natural home of yield: schedules
"""

from __future__ import annotations

import os
import random
import threading
import time

from hstream_tpu.common.errors import ServerError
from hstream_tpu.common.logger import get_logger

log = get_logger("faultinject")

ENV_VAR = "HSTREAM_FAULTS"


class InjectedFault(ServerError):
    """Raised by an armed fail/prob fault site. Subclasses ServerError
    so the gRPC boundary maps it to INTERNAL like any other server
    fault (the error-contract pass already admits that status)."""

    def __init__(self, site: str, hit: int):
        super().__init__(f"injected fault at site {site!r} (hit {hit})")
        self.site = site
        self.hit = hit


class _Site:
    """One armed site: parsed schedule + hit/injection accounting."""

    __slots__ = ("name", "spec", "kind", "arg", "count", "seed",
                 "hits", "injected", "_rng")

    def __init__(self, name: str, spec: str):
        self.name = name
        self.spec = spec
        parts = spec.split(":")
        self.kind = parts[0]
        self.hits = 0
        self.injected = 0
        if self.kind == "fail":
            if len(parts) < 2:
                raise ValueError(f"fail needs N: {spec!r}")
            self.arg = int(parts[1])          # first failing hit
            self.count = int(parts[2]) if len(parts) > 2 else 1
            self.seed = 0
            self._rng = None
        elif self.kind == "prob":
            if len(parts) < 2:
                raise ValueError(f"prob needs P: {spec!r}")
            p = float(parts[1])
            if not 0.0 <= p <= 1.0:
                raise ValueError(f"prob P out of [0,1]: {spec!r}")
            self.arg = p
            self.seed = int(parts[2]) if len(parts) > 2 else 0
            self.count = 0
            self._rng = random.Random(self.seed)
        elif self.kind == "delay":
            if len(parts) < 2:
                raise ValueError(f"delay needs MS: {spec!r}")
            self.arg = float(parts[1]) / 1000.0
            self.count = int(parts[2]) if len(parts) > 2 else 0  # 0=all
            self.seed = 0
            self._rng = None
        elif self.kind == "torn":
            if len(parts) < 2:
                raise ValueError(f"torn needs N: {spec!r}")
            self.arg = int(parts[1])
            self.seed = int(parts[2]) if len(parts) > 2 else 0
            self.count = 1
            self._rng = random.Random(self.seed)
        elif self.kind == "yield":
            if len(parts) < 2:
                raise ValueError(f"yield needs N: {spec!r}")
            n = int(parts[1])
            if n < 1:
                raise ValueError(f"yield N must be >= 1: {spec!r}")
            self.arg = n
            self.seed = int(parts[2]) if len(parts) > 2 else 0
            self.count = 0
            self._rng = random.Random(self.seed)
        else:
            raise ValueError(f"unknown fault kind {self.kind!r} "
                             f"(fail/prob/delay/torn/yield)")

    def fire(self) -> tuple[str, float] | None:
        """Advance the schedule one point() hit. Returns None (no
        fault), ("fail", 0) to raise, or ("delay", seconds) to sleep.
        Torn schedules only advance on mutate() (a site may host both
        a point and a mutate probe; their hit counts must not blend)."""
        if self.kind == "torn":
            return None
        self.hits += 1
        if self.kind == "fail":
            if self.arg <= self.hits < self.arg + self.count:
                self.injected += 1
                return ("fail", 0.0)
        elif self.kind == "prob":
            if self._rng.random() < self.arg:
                self.injected += 1
                return ("fail", 0.0)
        elif self.kind == "delay":
            if self.count == 0 or self.hits == self.count:
                self.injected += 1
                return ("delay", self.arg)
        elif self.kind == "yield":
            # two seeded draws per hit: the 1/N decision, then the
            # jitter magnitude — one deterministic stream per spec, so
            # a seed replays the same perturbation SEQUENCE even when
            # threads race for the next decision
            r = self._rng.random()
            jitter = self._rng.random()
            if r < 1.0 / self.arg:
                self.injected += 1
                return ("yield", jitter * 0.002)
        return None

    def tear(self, data: bytes) -> bytes | None:
        """Advance one mutate() write hit; returns truncated bytes when
        this is the scheduled torn write, else None."""
        if self.kind != "torn":
            return None
        self.hits += 1
        if self.hits != self.arg:
            return None
        self.injected += 1
        if len(data) <= 1:
            return b""
        # seeded cut point in the middle half so the tear is neither a
        # trivially-empty file nor a nearly-complete one
        lo = max(1, len(data) // 4)
        hi = max(lo + 1, (3 * len(data)) // 4)
        return data[:self._rng.randrange(lo, hi)]

    def status(self) -> dict:
        return {"spec": self.spec, "hits": self.hits,
                "injected": self.injected}


class FaultRegistry:
    """Process-wide registry of armed fault sites.

    Hot-path contract: with no sites armed, ``active`` is False and an
    instrumented site costs one attribute read + one branch. Arming any
    site flips ``active``; ``point``/``mutate`` then take the registry
    lock (fault runs are test/debug runs — injection determinism beats
    contention here)."""

    def __init__(self) -> None:
        self._sites: dict[str, _Site] = {}
        self._lock = threading.Lock()
        self._events = None  # EventJournal bound by ServerContext
        self.active = False

    # ---- configuration -----------------------------------------------------

    def arm(self, site: str, spec: str) -> None:
        """Arm (or re-arm, resetting counters) one site."""
        s = _Site(site, spec)
        with self._lock:
            self._sites[site] = s
            self.active = True
        log.warning("fault site %s armed: %s", site, spec)

    def disarm(self, site: str | None = None) -> None:
        """Disarm one site (or every site when None)."""
        with self._lock:
            if site is None:
                self._sites.clear()
            else:
                self._sites.pop(site, None)
            self.active = bool(self._sites)

    def bind_events(self, events) -> None:
        """Attach an event journal; every injection appends a
        ``fault_injected`` event (best-effort)."""
        self._events = events

    def load_env(self, env: str | None = None) -> int:
        """Arm sites from ``HSTREAM_FAULTS`` (or an explicit spec
        string); returns how many sites were armed. Malformed entries
        are skipped loudly — a typo'd chaos run must not boot clean."""
        raw = env if env is not None else os.environ.get(ENV_VAR, "")
        n = 0
        for ent in raw.split(";"):
            ent = ent.strip()
            if not ent:
                continue
            site, _, spec = ent.partition("=")
            try:
                self.arm(site.strip(), spec.strip())
                n += 1
            except ValueError as e:
                log.error("ignoring malformed fault spec %r: %s", ent, e)
        return n

    def status(self) -> dict:
        with self._lock:
            return {name: s.status()
                    for name, s in sorted(self._sites.items())}

    # ---- injection ---------------------------------------------------------

    def point(self, site: str) -> None:
        """One probe hit. Raises InjectedFault or sleeps per the armed
        schedule; a no-op for unarmed sites. Callers on hot paths guard
        with ``if FAULTS.active``."""
        # deliberate unlocked fast-path read (one stale branch at
        # worst), same idiom as FlowGovernor.active
        # analyze: ok lock-guard — hot-path gate read
        if not self.active:
            return
        with self._lock:
            s = self._sites.get(site)
            fired = s.fire() if s is not None else None
        if fired is None:
            return
        kind, arg = fired
        if kind == "delay":
            self._journal(site, s, "delay")
            time.sleep(arg)
            return
        if kind == "yield":
            # no journal: a perturbation run yields thousands of times
            # and the journal ring must keep the interesting events
            time.sleep(arg)
            return
        self._journal(site, s, "fail")
        raise InjectedFault(site, s.hits)

    def mutate(self, site: str, data: bytes) -> bytes:
        """Torn-write probe: pass bytes through, truncated at the
        scheduled hit. Identity when inactive/unarmed."""
        # analyze: ok lock-guard — deliberate unlocked fast-path read
        if not self.active:
            return data
        with self._lock:
            s = self._sites.get(site)
            torn = s.tear(data) if s is not None else None
        if torn is None:
            return data
        self._journal(site, s, "torn")
        log.warning("fault site %s: torn write %d -> %d bytes",
                    site, len(data), len(torn))
        return torn

    def _journal(self, site: str, s: _Site, what: str) -> None:
        events = self._events
        if events is None:
            return
        try:
            events.append("fault_injected",
                          f"fault {what} injected at {site} "
                          f"(hit {s.hits}, spec {s.spec})",
                          site=site, fault=what, hit=s.hits)
        except Exception:  # noqa: BLE001 — journaling must never alter
            pass           # injection behavior


# the process singleton every instrumented site reaches
FAULTS = FaultRegistry()
