"""Exception hierarchy for hstream-tpu.

The reference maps low-level store error codes to a typed exception table
(hstream-store/HStream/Store/Exception.hs) and catches them at the server
boundary into gRPC statuses (hstream/src/HStream/Server/Exception.hs:27-50).
We keep a compact hierarchy with the same separation: store errors, SQL
errors, server/user errors — each knows its gRPC status code.
"""

from __future__ import annotations

import grpc


class HStreamError(Exception):
    grpc_status = grpc.StatusCode.INTERNAL

    def __init__(self, message: str = ""):
        super().__init__(message)
        self.message = message


# ---- store -----------------------------------------------------------------

class StoreError(HStreamError):
    pass


class StreamNotFound(StoreError):
    grpc_status = grpc.StatusCode.NOT_FOUND


class StreamExists(StoreError):
    grpc_status = grpc.StatusCode.ALREADY_EXISTS


class LogNotFound(StoreError):
    grpc_status = grpc.StatusCode.NOT_FOUND


class CheckpointNotFound(StoreError):
    grpc_status = grpc.StatusCode.NOT_FOUND


class StoreIOError(StoreError):
    pass


class ReplicaDivergence(StoreIOError):
    """A replica's local store no longer matches the op-log it is
    asked to apply (an append would land at the wrong LSN). The
    replica halts loudly and refuses further entries — an operator
    re-bootstraps it from a copy of a live store; drifting quietly is
    never an option."""


class NotLeaderError(StoreError):
    """This node no longer leads the replicated store (fenced by a
    higher epoch). The NOT_LEADER contract: rides UNAVAILABLE — the
    one status that means "not here, maybe elsewhere" — with the new
    leader's address attached twice, as an ``x-leader-hint``
    trailing-metadata entry at the gRPC boundary and a
    ``not_leader leader_hint=ADDR`` token in the message text.
    Clients follow the hint with jittered backoff
    (client/retry.HINTED_RETRYABLE_CODES) instead of failing the
    statement; a bare UNAVAILABLE (mid-call transport drop, no hint)
    stays non-retryable at that layer."""

    grpc_status = grpc.StatusCode.UNAVAILABLE

    def __init__(self, message: str = "",
                 leader_hint: str | None = None):
        if leader_hint:
            message = f"{message} (not_leader leader_hint={leader_hint})"
        super().__init__(message)
        self.leader_hint = leader_hint


class DuplicateAppend(StoreError):
    """A producer-stamped append whose seq fell behind the bounded
    dedup window: the original may already be stored, so re-appending
    could duplicate — refused loudly instead."""

    grpc_status = grpc.StatusCode.ALREADY_EXISTS


# ---- SQL -------------------------------------------------------------------

class SQLError(HStreamError):
    grpc_status = grpc.StatusCode.INVALID_ARGUMENT

    def __init__(self, message: str, pos: tuple[int, int] | None = None):
        super().__init__(message)
        self.pos = pos  # (line, column), 1-based

    def __str__(self) -> str:
        if self.pos:
            return f"{self.message} at line {self.pos[0]}, column {self.pos[1]}"
        return self.message


class SQLParseError(SQLError):
    pass


class SQLValidateError(SQLError):
    pass


class SQLCodegenError(SQLError):
    pass


# ---- server ----------------------------------------------------------------

class ServerError(HStreamError):
    pass


class InvalidFrame(ServerError):
    """A framed columnar append block failed validation at the ingress
    door — bad magic/version, truncated or overlong body, CRC mismatch,
    or an embedded columnar block whose declared sizes don't fit its
    bytes. The refusal contract (ISSUE 12): typed INVALID_ARGUMENT
    before ANY byte reaches the store, never a partial ingest."""

    grpc_status = grpc.StatusCode.INVALID_ARGUMENT


class SubscriptionNotFound(ServerError):
    grpc_status = grpc.StatusCode.NOT_FOUND


class SubscriptionExists(ServerError):
    grpc_status = grpc.StatusCode.ALREADY_EXISTS


class QueryNotFound(ServerError):
    grpc_status = grpc.StatusCode.NOT_FOUND


class ViewNotFound(ServerError):
    grpc_status = grpc.StatusCode.NOT_FOUND


class ConnectorNotFound(ServerError):
    grpc_status = grpc.StatusCode.NOT_FOUND


class QueryTerminated(ServerError):
    grpc_status = grpc.StatusCode.ABORTED


class ResourceExhausted(ServerError):
    """Admission refused by flow control (quota or overload shed). The
    retry-after hint rides both the message text (retry_after_ms=N) and
    — at the gRPC boundary — a `retry-after-ms` trailing-metadata entry,
    so any client can back off without a custom status proto."""

    grpc_status = grpc.StatusCode.RESOURCE_EXHAUSTED

    def __init__(self, message: str = "",
                 retry_after_ms: int | None = None):
        if retry_after_ms is not None:
            retry_after_ms = max(1, int(retry_after_ms))
            message = f"{message} (retry_after_ms={retry_after_ms})"
        super().__init__(message)
        self.retry_after_ms = retry_after_ms
