"""Exception hierarchy for hstream-tpu.

The reference maps low-level store error codes to a typed exception table
(hstream-store/HStream/Store/Exception.hs) and catches them at the server
boundary into gRPC statuses (hstream/src/HStream/Server/Exception.hs:27-50).
We keep a compact hierarchy with the same separation: store errors, SQL
errors, server/user errors — each knows its gRPC status code.
"""

from __future__ import annotations

import grpc


class HStreamError(Exception):
    grpc_status = grpc.StatusCode.INTERNAL

    def __init__(self, message: str = ""):
        super().__init__(message)
        self.message = message


# ---- store -----------------------------------------------------------------

class StoreError(HStreamError):
    pass


class StreamNotFound(StoreError):
    grpc_status = grpc.StatusCode.NOT_FOUND


class StreamExists(StoreError):
    grpc_status = grpc.StatusCode.ALREADY_EXISTS


class LogNotFound(StoreError):
    grpc_status = grpc.StatusCode.NOT_FOUND


class CheckpointNotFound(StoreError):
    grpc_status = grpc.StatusCode.NOT_FOUND


class StoreIOError(StoreError):
    pass


# ---- SQL -------------------------------------------------------------------

class SQLError(HStreamError):
    grpc_status = grpc.StatusCode.INVALID_ARGUMENT

    def __init__(self, message: str, pos: tuple[int, int] | None = None):
        super().__init__(message)
        self.pos = pos  # (line, column), 1-based

    def __str__(self) -> str:
        if self.pos:
            return f"{self.message} at line {self.pos[0]}, column {self.pos[1]}"
        return self.message


class SQLParseError(SQLError):
    pass


class SQLValidateError(SQLError):
    pass


class SQLCodegenError(SQLError):
    pass


# ---- server ----------------------------------------------------------------

class ServerError(HStreamError):
    pass


class SubscriptionNotFound(ServerError):
    grpc_status = grpc.StatusCode.NOT_FOUND


class SubscriptionExists(ServerError):
    grpc_status = grpc.StatusCode.ALREADY_EXISTS


class QueryNotFound(ServerError):
    grpc_status = grpc.StatusCode.NOT_FOUND


class ViewNotFound(ServerError):
    grpc_status = grpc.StatusCode.NOT_FOUND


class ConnectorNotFound(ServerError):
    grpc_status = grpc.StatusCode.NOT_FOUND


class QueryTerminated(ServerError):
    grpc_status = grpc.StatusCode.ABORTED


class ResourceExhausted(ServerError):
    """Admission refused by flow control (quota or overload shed). The
    retry-after hint rides both the message text (retry_after_ms=N) and
    — at the gRPC boundary — a `retry-after-ms` trailing-metadata entry,
    so any client can back off without a custom status proto."""

    grpc_status = grpc.StatusCode.RESOURCE_EXHAUSTED

    def __init__(self, message: str = "",
                 retry_after_ms: int | None = None):
        if retry_after_ms is not None:
            retry_after_ms = max(1, int(retry_after_ms))
            message = f"{message} (retry_after_ms={retry_after_ms})"
        super().__init__(message)
        self.retry_after_ms = retry_after_ms
