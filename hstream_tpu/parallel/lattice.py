"""Multi-chip sharding of the window-state lattice.

The reference is single-process for compute — its only cross-host axes are
storage replication and round-robin consumer dispatch (SURVEY §2.3;
hstream/src/HStream/Server/Handler.hs:896-922). The TPU-native design
scales the aggregation hot path itself over a 2-D device mesh:

  * ``data`` axis — records of each micro-batch are sharded across chips;
    every chip scatters its shard into a **partial lattice**. Because all
    accumulator planes are commutative monoids (lattice.plane_merge_kinds),
    partials merge exactly at drain points.
  * ``key`` axis — the key dimension of every plane is sharded, bounding
    per-chip HBM. Records are broadcast along ``key`` (the batch in_spec
    only names the data axis) and each chip masks the scatter to the key
    range it owns — no all-to-all in the hot path; the scatter itself does
    the routing.

State arrays carry a leading device axis of length ``D`` (the data-axis
size): a keyed plane is ``[D, K, W, ...]`` sharded
``P(data, key)``. The hot step runs under ``jax.shard_map`` with **zero
collectives**; merges (psum / pmin / pmax over ``data``, all riding ICI)
happen only when the host drains state — window close, changelog pull,
view peek — amortized over the window length.

This mirrors the scaling-book recipe: pick a mesh, annotate shardings, let
the compiled collectives ride ICI. DCN never sees lattice traffic; it is
reserved for the log-store replication plane (hstream_tpu.store).

The shard_map hygiene here (collectives only inside mesh bodies, no
host callbacks/fetches in them, axis names spelled consistently) is
checked by the tools/analyze shardmap pass, and the kernels run for
real in CI on a virtual 8-device CPU mesh
(XLA_FLAGS=--xla_force_host_platform_device_count=8); the static pass
still catches the classes — per-shard host syncs, axis typos — that
only real ICI latency or multi-host meshes would trip.
"""

from __future__ import annotations

import functools
from typing import Callable, Mapping, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from hstream_tpu.engine import lattice
from hstream_tpu.engine.lattice import (
    EMPTY_START,
    LatticeSpec,
    build_step_fn,
    compile_agg_inputs,
    finalize_column,
    init_value,
    plane_merge_kinds,
)


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=False):
    """jax.shard_map across jax versions: new enough builds export it
    top-level (`check_vma`); older ones ship the same transform as
    jax.experimental.shard_map (`check_rep`). One wrapper keeps every
    sharded kernel importable — and testable on the CPU mesh — on both."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check_vma)

_MERGE = {
    "sum": jax.lax.psum,
    "min": jax.lax.pmin,
    "max": jax.lax.pmax,
}


def _keyed(name: str) -> bool:
    return name != "slot_start"


class ShardedLattice:
    """The lattice of one query, sharded over a (data, key) mesh.

    Drop-in provider of the CompiledLattice callables with identical
    signatures (state first, host scalars as np types), so the host
    executor drives single-chip and multi-chip lattices the same way.
    ``n_keys`` of ``spec`` is the GLOBAL key capacity; it must divide by
    the key-axis size.
    """

    def __init__(self, spec: LatticeSpec, schema, filter_expr,
                 max_out: int, mesh: Mesh, layout,
                 data_axis: str = "data", key_axis: str = "key"):
        from hstream_tpu.engine.expr import compile_device

        self.layout = layout
        self.mesh = mesh
        self.data_axis = data_axis
        self.key_axis = key_axis if key_axis in mesh.axis_names else None
        self.n_data = mesh.shape[data_axis]
        self.n_key = mesh.shape[self.key_axis] if self.key_axis else 1
        if spec.n_keys % self.n_key != 0:
            raise ValueError(
                f"global key capacity {spec.n_keys} not divisible by "
                f"key-axis size {self.n_key}")
        self.spec = spec
        self.local_spec = LatticeSpec(
            n_keys=spec.n_keys // self.n_key, window=spec.window,
            aggs=spec.aggs, hll=spec.hll, qcfg=spec.qcfg,
            track_touched=spec.track_touched)
        self.max_out = max_out

        agg_inputs, self.null_keys = compile_agg_inputs(spec, schema)
        filter_fn = (compile_device(filter_expr, schema)
                     if filter_expr is not None else None)
        self._local_step = build_step_fn(self.local_spec, agg_inputs,
                                         filter_fn)
        self._merge_kinds = plane_merge_kinds(spec)
        bad = sorted(k for k, v in self._merge_kinds.items()
                     if v not in _MERGE)
        if bad:
            raise ValueError(
                f"plane(s) {bad} have no elementwise merge (TOPK): "
                "sharded execution is not supported for this query")
        self._state_specs = None  # built lazily from init_state's tree
        self._build()

    # ---- sharding specs ----------------------------------------------------

    def state_spec(self, name: str) -> P:
        if _keyed(name):
            return P(self.data_axis, self.key_axis)
        return P(self.data_axis)

    def state_sharding(self, name: str) -> NamedSharding:
        return NamedSharding(self.mesh, self.state_spec(name))

    def init_state(self) -> dict[str, jnp.ndarray]:
        """Global sharded state: local init replicated along ``data`` (all
        init values are merge identities, so D partial copies are exact)."""
        local = lattice.init_state(self.spec)  # global K, host-side
        out = {}
        for name, v in local.items():
            g = jnp.broadcast_to(v[None], (self.n_data,) + v.shape)
            out[name] = jax.device_put(g, self.state_sharding(name))
        return out

    def _specs_of(self, state_tree: Mapping[str, jnp.ndarray]):
        return {k: self.state_spec(k) for k in state_tree}

    # ---- compiled callables ------------------------------------------------

    def _build(self) -> None:
        mesh = self.mesh
        data_axis, key_axis = self.data_axis, self.key_axis
        Kl = self.local_spec.n_keys
        merge = self._merge_kinds
        spec_tree = {k: self.state_spec(k)
                     for k in lattice.init_state(self.spec)}
        local_spec = self.local_spec

        def key_offset():
            if key_axis is None:
                return 0
            return jax.lax.axis_index(key_axis) * Kl

        layout, null_keys = self.layout, self.null_keys

        def step_local(state, watermark, packed):
            local = {k: v[0] for k, v in state.items()}
            key_ids, ts, valid, cols = lattice.unpack_batch_device(
                packed, layout, null_keys)
            kid = key_ids - key_offset()
            ok = valid & (kid >= 0) & (kid < Kl)
            # slot_valid = the pre-key-ownership mask: slot_start is
            # key-independent, so every key shard must update it from ALL
            # valid records for the replicated out-spec to hold.
            new = self._local_step(local, watermark, kid, ts, ok, cols,
                                   slot_valid=valid)
            return {k: v[None] for k, v in new.items()}

        # packed batch [rows, B]: rows replicated, records sharded on data
        self.step = jax.jit(shard_map(
            step_local, mesh=mesh,
            in_specs=(spec_tree, P(), P(None, data_axis)),
            out_specs=spec_tree, check_vma=False))

        def merged_col(state, slot):
            """One slot column, merged over the data axis -> {plane: [Kl]}"""
            col = {}
            for k, v in state.items():
                if k in ("slot_start", "touched"):
                    continue
                col[k] = _MERGE[merge[k]](v[0, :, slot], data_axis)
            return col

        def extract_local(state, slot):
            col = merged_col(state, slot)
            outs = finalize_column(local_spec, col)
            ws = jax.lax.pmax(state["slot_start"][0, slot], data_axis)
            return lattice.pack_extract_rows(local_spec, col["count"],
                                             ws, outs)

        # packed [2+n_aggs, K] — key axis concatenated over shards
        self.extract_slot = jax.jit(shard_map(
            extract_local, mesh=mesh,
            in_specs=(spec_tree, P()),
            out_specs=P(None, key_axis), check_vma=False))

        def reset_local(state, slot):
            out = dict(state)
            for i, agg in enumerate(local_spec.aggs):
                if agg.kind == lattice.AggKind.COUNT_ALL:
                    continue  # aliases `count`, reset below
                name = lattice._plane_name(i, agg)
                out[name] = state[name].at[:, :, slot].set(init_value(agg))
                if agg.kind == lattice.AggKind.AVG:
                    out[name + "_n"] = state[name + "_n"].at[
                        :, :, slot].set(0)
            out["count"] = state["count"].at[:, :, slot].set(0)
            out["touched"] = state["touched"].at[:, :, slot].set(False)
            out["slot_start"] = state["slot_start"].at[:, slot].set(
                EMPTY_START)
            return out

        self.reset_slot = jax.jit(shard_map(
            reset_local, mesh=mesh,
            in_specs=(spec_tree, P()),
            out_specs=spec_tree, check_vma=False))

        # ---- fused multi-slot close (one dispatch per close cycle) ----
        # Same contract as lattice.build_extract_reset_slots, with the
        # monoid merge riding ICI (psum/pmin/pmax over `data`) BEFORE
        # the single host fetch: slots i32[P] (entries < 0 pad), packed
        # out [P, 2+rows, K] with the key axis concatenated over shards
        # so kid indices in the buffer are GLOBAL key ids.

        def _extract_slots_local(state, slots):
            valid = slots >= 0
            safe = jnp.where(valid, slots, 0)

            def one(slot):
                col = merged_col(state, slot)
                outs = finalize_column(local_spec, col)
                ws = jax.lax.pmax(state["slot_start"][0, slot], data_axis)
                return lattice.pack_extract_rows(local_spec,
                                                 col["count"], ws, outs)

            packed = jax.vmap(one)(safe)
            return jnp.where(valid[:, None, None], packed, 0)

        def _reset_slots_local(state, slots):
            rs = jnp.where(slots >= 0, slots, local_spec.n_slots)
            out = dict(state)
            for i, agg in enumerate(local_spec.aggs):
                if agg.kind == lattice.AggKind.COUNT_ALL:
                    continue  # aliases `count`, reset below
                name = lattice._plane_name(i, agg)
                out[name] = state[name].at[:, :, rs].set(
                    init_value(agg), mode="drop")
                if agg.kind == lattice.AggKind.AVG:
                    out[name + "_n"] = state[name + "_n"].at[
                        :, :, rs].set(0, mode="drop")
            out["count"] = state["count"].at[:, :, rs].set(0, mode="drop")
            out["touched"] = state["touched"].at[:, :, rs].set(
                False, mode="drop")
            out["slot_start"] = state["slot_start"].at[:, rs].set(
                EMPTY_START, mode="drop")
            return out

        def extract_reset_local(state, slots):
            packed = _extract_slots_local(state, slots)
            return _reset_slots_local(state, slots), packed

        self.extract_reset_slots = jax.jit(shard_map(
            extract_reset_local, mesh=mesh,
            in_specs=(spec_tree, P()),
            out_specs=(spec_tree, P(None, None, key_axis)),
            check_vma=False))

        self.extract_slots = jax.jit(shard_map(
            _extract_slots_local, mesh=mesh,
            in_specs=(spec_tree, P()),
            out_specs=P(None, None, key_axis), check_vma=False))

        self.reset_slots = jax.jit(shard_map(
            _reset_slots_local, mesh=mesh,
            in_specs=(spec_tree, P()),
            out_specs=spec_tree, check_vma=False))

        max_out = self.max_out

        def touched_local(state):
            # changelog across shards: merge the full lattice over `data`
            # (the one drain that pays a whole-lattice collective), then
            # enumerate per key-shard
            mask = jax.lax.pmax(state["touched"][0].astype(jnp.int32),
                                data_axis).astype(jnp.bool_)
            n = jnp.sum(mask.astype(jnp.int32))
            kidx, sidx = jnp.nonzero(mask, size=max_out, fill_value=0)
            col = {}
            for k, v in state.items():
                if k in ("slot_start", "touched"):
                    continue
                m = _MERGE[merge[k]](v[0], data_axis)
                col[k] = m[kidx, sidx]
            outs = finalize_column(local_spec, col)
            ws_merged = jax.lax.pmax(state["slot_start"][0], data_axis)
            valid = jnp.arange(max_out) < n
            out_state = dict(state)
            out_state["touched"] = jnp.zeros_like(state["touched"])
            kid_global = kidx + key_offset()
            packed = lattice.pack_touched_rows(
                local_spec, n, kid_global,
                jnp.where(valid, ws_merged[sidx], 0), outs, max_out)
            return out_state, packed[None]

        # packed per-key-shard buffers stacked on a leading axis
        self.extract_touched = jax.jit(shard_map(
            touched_local, mesh=mesh,
            in_specs=(spec_tree,),
            out_specs=(spec_tree, P(key_axis)), check_vma=False))

# ---- key-sharded interval join ----------------------------------------------
#
# The shard_map mirror of engine.lattice's interval-join kernels: each
# key shard owns the join-key codes with ``code % n_shards == shard``,
# holds its own slice of both side stores, probes/inserts only the
# batch records it owns (the batch is replicated along the key axis —
# the ownership mask does the routing, like the aggregation lattice),
# and the per-shard match buffers CONCATENATE over ICI into one
# [rows, n_shards * match_cap] buffer before the single host fetch.
# Per-shard headers sit at column s * match_cap.


class ShardedJoinLattice:
    """Both sides of one interval join, key-sharded over a mesh axis.

    Capacities are PER SHARD. Drop-in twin of the single-chip kernels:
    ``probe_insert(mine, other, batch, n, within, cutoff)`` returns
    (mine', packed [rows, n_shards * match_cap]); ``evict(left, right,
    cutoff, delta)`` compacts both sides per shard and returns the
    per-shard live counts [n_shards, 2]. Kernels are built lazily and
    cached per (batch capacity, match capacity) — the sharded mirror of
    the lru-cached single-chip factories — so the executor's sticky
    capacity ladders reuse compiled shapes instead of retracing.

    ``probe_insert_step`` is the fully fused form: the per-shard match
    feed is CONCATenated over ICI (one ``all_gather`` along the key
    axis — the only collective in the hot path) and scattered straight
    into the already-sharded downstream aggregate lattice, so matched
    pairs never leave the device."""

    def __init__(self, mesh: Mesh, key_axis: str, cap: int, bcap: int,
                 match_cap: int, n_cols_l: int, n_cols_r: int):
        self.mesh = mesh
        self.key_axis = key_axis
        self.n_shards = mesh.shape[key_axis]
        self.cap = cap
        self.bcap = bcap
        self.match_cap = match_cap
        self.n_cols = {"l": n_cols_l, "r": n_cols_r}
        self._store_spec = {k: P(key_axis) for k in ("code", "ts",
                                                     "flags", "cols")}
        self._probe_kerns: dict = {}
        self._probe_only_kerns: dict = {}
        self._evict_kerns: dict = {}
        self._fused_kerns: dict = {}

    def store_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, P(self.key_axis))

    def init_store(self, side: str, cap: int | None = None
                   ) -> dict[str, jnp.ndarray]:
        """Per-shard empty stores stacked on a leading shard axis and
        placed with the key-axis sharding."""
        local = lattice.init_join_store(cap or self.cap,
                                        self.n_cols[side])
        out = {}
        for k, v in local.items():
            g = jnp.broadcast_to(v[None], (self.n_shards,) + v.shape)
            out[k] = jax.device_put(g, self.store_sharding())
        return out

    def put_store(self, host: Mapping[str, np.ndarray]):
        """Host planes [n_shards, cap, ...] -> device, key-sharded."""
        return {k: jax.device_put(jnp.asarray(v), self.store_sharding())
                for k, v in host.items()}

    def _build_probe_insert(self, nm: int, bcap: int, match_cap: int):
        mesh, key_axis = self.mesh, self.key_axis
        n_shards = self.n_shards
        store_spec = self._store_spec

        def owned_mask(bcode):
            shard = jax.lax.axis_index(key_axis)
            return (bcode % n_shards) == shard

        def probe_insert_local(mine, other, batch, n, within, cutoff):
            m = {k: v[0] for k, v in mine.items()}
            o = {k: v[0] for k, v in other.items()}
            owned = owned_mask(batch[0])
            packed = lattice._join_probe(o, batch, n, within, cutoff,
                                         bcap, match_cap, nm,
                                         owned=owned)
            new = lattice._join_insert(m, batch, n, bcap, nm,
                                       owned=owned)
            return {k: v[None] for k, v in new.items()}, packed

        # match buffers concatenate along the COLUMN axis: global
        # [rows, n_shards * match_cap], per-shard headers at column
        # s * match_cap
        return jax.jit(shard_map(
            probe_insert_local, mesh=mesh,
            in_specs=(store_spec, store_spec, P(), P(), P(), P()),
            out_specs=(store_spec, P(None, key_axis)),
            check_vma=False))

    def _build_probe_only(self, nm: int, bcap: int, match_cap: int):
        mesh, key_axis = self.mesh, self.key_axis
        n_shards = self.n_shards
        store_spec = self._store_spec

        def probe_only_local(other, batch, n, within, cutoff):
            o = {k: v[0] for k, v in other.items()}
            shard = jax.lax.axis_index(key_axis)
            owned = (batch[0] % n_shards) == shard
            return lattice._join_probe(o, batch, n, within, cutoff,
                                       bcap, match_cap, nm,
                                       owned=owned)

        return jax.jit(shard_map(
            probe_only_local, mesh=mesh,
            in_specs=(store_spec, P(), P(), P(), P()),
            out_specs=P(None, key_axis), check_vma=False))

    def _build_evict(self, cap: int):
        mesh, key_axis = self.mesh, self.key_axis
        store_spec = self._store_spec

        def evict_local(left, right, cutoff, delta):
            def _core(code, ts):
                alive = (code < lattice.JOIN_SENT_CODE) & (ts >= cutoff)
                code2 = jnp.where(alive, code, lattice.JOIN_SENT_CODE)
                ts2 = jnp.where(alive, ts - delta, 0)
                idx = jnp.arange(cap, dtype=jnp.int32)
                return jax.lax.sort((code2, ts2, idx), num_keys=2) + (
                    jnp.sum(alive.astype(jnp.int32)),)

            outs = []
            ns = []
            for st in (left, right):
                scode, sts, order, n = _core(st["code"][0], st["ts"][0])
                outs.append({"code": scode[None], "ts": sts[None],
                             "flags": st["flags"][0][order][None],
                             "cols": st["cols"][0][:, order][None]})
                ns.append(n)
            return outs[0], outs[1], jnp.stack(ns)[None]

        return jax.jit(shard_map(
            evict_local, mesh=mesh,
            in_specs=(store_spec, store_spec, P(), P()),
            out_specs=(store_spec, store_spec, P(key_axis)),
            check_vma=False))

    def _build_probe_insert_step(self, nm: int, inner: ShardedLattice,
                                 feed_plan, nulls_plan, filter_nulls,
                                 bcap: int, match_cap: int):
        mesh, key_axis = self.mesh, self.key_axis
        n_shards = self.n_shards
        store_spec = self._store_spec
        spec_tree = {k: inner.state_spec(k)
                     for k in lattice.init_state(inner.spec)}
        local_step = inner._local_step
        Kl = inner.local_spec.n_keys
        n_data, data_axis = inner.n_data, inner.data_axis
        inner_key = inner.key_axis
        mc = match_cap

        def join_step_local(mine, other, batch, n, within, cutoff,
                            inner_state, wm_rel, ts_off):
            m = {k: v[0] for k, v in mine.items()}
            o = {k: v[0] for k, v in other.items()}
            owned = ((batch[0] % n_shards)
                     == jax.lax.axis_index(key_axis))
            total, kid, jts, valid, cols = lattice._join_match_feed(
                o, batch, n, within, cutoff, bcap, mc,
                feed_plan, nulls_plan, filter_nulls, owned=owned)
            # ICI concat point: the per-shard match segments gather
            # into one [n_shards * match_cap] feed, replicated along
            # the key axis so every key shard sees every match and the
            # ownership scatter below re-routes by AGGREGATE key
            # (join key and group key need not shard alike)
            kid = jax.lax.all_gather(kid, key_axis, tiled=True)
            jts = jax.lax.all_gather(jts, key_axis, tiled=True)
            valid = jax.lax.all_gather(valid, key_axis, tiled=True)
            cols = {k: jax.lax.all_gather(v, key_axis, tiled=True)
                    for k, v in cols.items()}
            midx = jnp.arange(n_shards * mc, dtype=jnp.int32)
            if n_data > 1:
                dmine = ((midx % n_data)
                         == jax.lax.axis_index(data_axis))
            else:
                dmine = jnp.ones_like(midx, dtype=jnp.bool_)
            off = (jax.lax.axis_index(inner_key) * Kl
                   if inner_key else 0)
            kid_l = kid - off
            ok = valid & dmine & (kid_l >= 0) & (kid_l < Kl)
            loc = {k: v[0] for k, v in inner_state.items()}
            new_inner = local_step(loc, wm_rel, kid_l, jts + ts_off,
                                   ok, cols,
                                   slot_valid=valid & dmine)
            new_mine = lattice._join_insert(m, batch, n, bcap, nm,
                                            owned=owned)
            return ({k: v[None] for k, v in new_mine.items()},
                    {k: v[None] for k, v in new_inner.items()},
                    total[None])

        return jax.jit(shard_map(
            join_step_local, mesh=mesh,
            in_specs=(store_spec, store_spec, P(), P(), P(), P(),
                      spec_tree, P(), P()),
            out_specs=(store_spec, spec_tree, P(key_axis)),
            check_vma=False))

    def probe_insert(self, side: str, mine, other, batch, n, within,
                     cutoff, match_cap: int | None = None):
        mc = self.match_cap if match_cap is None else match_cap
        key = (side, batch.shape[1], mc)
        fn = self._probe_kerns.get(key)
        if fn is None:
            fn = self._probe_kerns[key] = self._build_probe_insert(
                self.n_cols[side], batch.shape[1], mc)
        return fn(mine, other, batch, n, within, cutoff)

    def probe_only(self, side: str, other, batch, n, within, cutoff,
                   match_cap: int):
        key = (side, batch.shape[1], match_cap)
        fn = self._probe_only_kerns.get(key)
        if fn is None:
            fn = self._probe_only_kerns[key] = self._build_probe_only(
                self.n_cols[side], batch.shape[1], match_cap)
        return fn(other, batch, n, within, cutoff)

    def probe_insert_step(self, side: str, inner: ShardedLattice,
                          mine, other, batch, n, within, cutoff,
                          inner_state, wm_rel, ts_off, *,
                          feed_plan, nulls_plan, filter_nulls,
                          match_cap: int | None = None):
        """Fused probe + insert + downstream-aggregate scatter, one
        dispatch; returns (mine', inner_state', per-shard totals
        i32[n_shards]). `inner` is the query's ShardedLattice (same
        mesh); the fused kernel is cached per (side, inner, shapes)."""
        mc = self.match_cap if match_cap is None else match_cap
        key = (side, inner, batch.shape[1], mc, feed_plan,
               nulls_plan, filter_nulls)
        fn = self._fused_kerns.get(key)
        if fn is None:
            fn = self._fused_kerns[key] = self._build_probe_insert_step(
                self.n_cols[side], inner, feed_plan, nulls_plan,
                filter_nulls, batch.shape[1], mc)
        return fn(mine, other, batch, n, within, cutoff, inner_state,
                  wm_rel, ts_off)

    def evict(self, left, right, cutoff, delta):
        cap = left["code"].shape[1]
        fn = self._evict_kerns.get(cap)
        if fn is None:
            fn = self._evict_kerns[cap] = self._build_evict(cap)
        return fn(left, right, cutoff, delta)

    def unpack_matches(self, packed: np.ndarray, side: str):
        """Flatten the shard-concatenated match buffer into host arrays
        in shard order: (total, kid, jts_rel, my_flags, other_flags,
        my_cols, other_cols) — the sharded twin of
        lattice.unpack_join_matches. `total` sums the per-shard headers;
        truncation per shard is visible as total > len(kid)."""
        nm = self.n_cols[side]
        match_cap = packed.shape[1] // self.n_shards
        parts = []
        total = 0
        for s in range(self.n_shards):
            seg = packed[:, s * match_cap:(s + 1) * match_cap]
            t, kid, jts, mf, of, mc, oc = lattice.unpack_join_matches(
                seg, nm)
            total += t
            parts.append((kid, jts, mf, of, mc, oc))
        return (total,
                np.concatenate([p[0] for p in parts]),
                np.concatenate([p[1] for p in parts]),
                np.concatenate([p[2] for p in parts]),
                np.concatenate([p[3] for p in parts]),
                np.concatenate([p[4] for p in parts], axis=1),
                np.concatenate([p[5] for p in parts], axis=1))


# ---- key-sharded session arena ----------------------------------------------
#
# Session chain merge is KEY-LOCAL (a session never spans keys), so the
# arena shards exactly like the join stores: each key shard keeps its
# own (code, t0)-sorted arena slice for the codes with
# ``code % n_shards == shard``, the packed batch / segment feed is
# replicated along the key axis, and an ownership mask does the routing
# — unowned records have their valid bit cleared (record mode) or their
# segment code rewritten to the sentinel (segment mode), which the
# single-chip kernels already treat as "drop" (their scatters are all
# mode="drop" at dest=cap). Zero collectives anywhere: step, merge,
# extract and remap are all embarrassingly per-shard; the host keeps
# the global interval mirror plus a per-shard slot index so late-drop
# and close decisions still resolve with zero device syncs.


class ShardedSessionLattice:
    """The session arena of one query, key-sharded over a mesh axis.

    Capacities are PER SHARD. Kernels wrap the lru-cached single-chip
    session factories under shard_map, built lazily and cached per
    shape so the executor's sticky capacity ladders reuse compiled
    shapes instead of retracing."""

    def __init__(self, mesh: Mesh, key_axis: str, spec, schema,
                 layout):
        self.mesh = mesh
        self.key_axis = key_axis
        self.n_shards = mesh.shape[key_axis]
        self.spec = spec
        self.schema = schema
        self.layout = layout
        self._plane_names = tuple(lattice.session_plane_np(spec, 1))
        self._arena_spec = {k: P(key_axis) for k in self._plane_names}
        self._step_kerns: dict = {}
        self._merge_kerns: dict = {}
        self._extract_kerns: dict = {}
        self._remap_kerns: dict = {}

    def arena_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, P(self.key_axis))

    def init_arena(self, cap: int) -> dict[str, jnp.ndarray]:
        """Per-shard empty arenas stacked on a leading shard axis and
        placed with the key-axis sharding."""
        local = lattice.session_plane_np(self.spec, cap)
        return {k: jax.device_put(
            jnp.broadcast_to(jnp.asarray(v)[None],
                             (self.n_shards,) + v.shape),
            self.arena_sharding()) for k, v in local.items()}

    def put_arena(self, host: Mapping[str, np.ndarray]):
        """Host planes [n_shards, cap, ...] -> device, key-sharded."""
        return {k: jax.device_put(jnp.asarray(v), self.arena_sharding())
                for k, v in host.items()}

    def grow_arena(self, arena, new_cap: int):
        """Copy every shard's slice into a fresh wider arena (identity
        fill past the old capacity), like lattice.grow_session_arena."""
        fresh = lattice.session_plane_np(self.spec, new_cap)
        out = {}
        for k, v in arena.items():
            g = jnp.broadcast_to(jnp.asarray(fresh[k])[None],
                                 (self.n_shards,) + fresh[k].shape)
            out[k] = jax.device_put(g.at[:, :v.shape[1]].set(v),
                                    self.arena_sharding())
        return out

    def _build_step(self, cap: int, bcap: int):
        base = lattice.session_step_kernel(self.spec, self.schema,
                                           self.layout, cap, bcap)
        mesh, key_axis = self.mesh, self.key_axis
        n_shards = self.n_shards
        aspec = self._arena_spec

        def session_step_local(arena, packed, gap, close_cut, delta):
            loc = {k: v[0] for k, v in arena.items()}
            owned = ((packed[0] % n_shards)
                     == jax.lax.axis_index(key_axis))
            # ownership routing: clear the valid bit (flags bit 0) of
            # records other shards own — the kernel maps invalid
            # records to the sentinel code and drops their scatters
            routed = packed.at[2].set(
                jnp.where(owned, packed[2], packed[2] & ~1))
            new = base(loc, routed, gap, close_cut, delta)
            return {k: v[None] for k, v in new.items()}

        return jax.jit(shard_map(
            session_step_local, mesh=mesh,
            in_specs=(aspec, P(), P(), P(), P()),
            out_specs=aspec, check_vma=False))

    def _build_merge(self, cap: int, scap: int, seg_keys: tuple):
        base = lattice.session_merge_kernel(self.spec, cap, scap)
        mesh, key_axis = self.mesh, self.key_axis
        n_shards = self.n_shards
        aspec = self._arena_spec
        seg_spec = {k: P() for k in seg_keys}

        def session_merge_local(arena, seg, gap, close_cut, delta):
            loc = {k: v[0] for k, v in arena.items()}
            owned = ((seg["code"] % n_shards)
                     == jax.lax.axis_index(key_axis))
            s2 = dict(seg)
            s2["code"] = jnp.where(
                owned & (seg["code"] < lattice.SESSION_SENT_CODE),
                seg["code"], lattice.SESSION_SENT_CODE)
            new = base(loc, s2, gap, close_cut, delta)
            return {k: v[None] for k, v in new.items()}

        return jax.jit(shard_map(
            session_merge_local, mesh=mesh,
            in_specs=(aspec, seg_spec, P(), P(), P()),
            out_specs=aspec, check_vma=False))

    def _build_extract(self, cap: int, pcap: int):
        base = lattice.session_extract_kernel(self.spec, cap, pcap)
        mesh, key_axis = self.mesh, self.key_axis
        aspec = self._arena_spec

        def session_extract_local(arena, slots):
            loc = {k: v[0] for k, v in arena.items()}
            return base(loc, slots[0])[None]

        return jax.jit(shard_map(
            session_extract_local, mesh=mesh,
            in_specs=(aspec, P(key_axis)),
            out_specs=P(key_axis), check_vma=False))

    def _build_remap(self, cap: int, lcap: int):
        base = lattice.session_remap_kernel(cap, lcap)
        mesh = self.mesh
        aspec = self._arena_spec

        def session_remap_local(arena, lut):
            loc = {k: v[0] for k, v in arena.items()}
            new = base(loc, lut)
            return {k: v[None] for k, v in new.items()}

        return jax.jit(shard_map(
            session_remap_local, mesh=mesh,
            in_specs=(aspec, P()),
            out_specs=aspec, check_vma=False))

    def step(self, arena, packed, gap, close_cut, delta):
        """Record-mode micro-batch: arena' — one dispatch, no fetch."""
        cap, bcap = arena["code"].shape[1], packed.shape[1]
        fn = self._step_kerns.get((cap, bcap))
        if fn is None:
            fn = self._step_kerns[(cap, bcap)] = self._build_step(
                cap, bcap)
        return fn(arena, packed, gap, close_cut, delta)

    def merge(self, arena, seg, gap, close_cut, delta):
        """Segment-mode micro-batch: arena' — one dispatch, no fetch."""
        cap = arena["code"].shape[1]
        scap = seg["code"].shape[0]
        seg_keys = tuple(sorted(seg))
        fn = self._merge_kerns.get((cap, scap, seg_keys))
        if fn is None:
            fn = self._merge_kerns[(cap, scap, seg_keys)] = \
                self._build_merge(cap, scap, seg_keys)
        return fn(arena, seg, gap, close_cut, delta)

    def extract(self, arena, slots):
        """Finalized rows for per-shard slot lists [n_shards, pcap]
        (-1 pads) -> packed [n_shards, 1 + n_aggs, pcap]."""
        cap, pcap = arena["code"].shape[1], slots.shape[1]
        fn = self._extract_kerns.get((cap, pcap))
        if fn is None:
            fn = self._extract_kerns[(cap, pcap)] = self._build_extract(
                cap, pcap)
        return fn(arena, slots)

    def remap(self, arena, lut):
        """Rewrite arena codes through a replicated LUT (compaction).
        The LUT must be residue-class preserving (new % n_shards ==
        old % n_shards) so entries never change owner shard."""
        cap, lcap = arena["code"].shape[1], lut.shape[0]
        fn = self._remap_kerns.get((cap, lcap))
        if fn is None:
            fn = self._remap_kerns[(cap, lcap)] = self._build_remap(
                cap, lcap)
        return fn(arena, lut)
