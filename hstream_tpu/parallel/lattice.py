"""Multi-chip sharding of the window-state lattice.

The reference is single-process for compute — its only cross-host axes are
storage replication and round-robin consumer dispatch (SURVEY §2.3;
hstream/src/HStream/Server/Handler.hs:896-922). The TPU-native design
scales the aggregation hot path itself over a 2-D device mesh:

  * ``data`` axis — records of each micro-batch are sharded across chips;
    every chip scatters its shard into a **partial lattice**. Because all
    accumulator planes are commutative monoids (lattice.plane_merge_kinds),
    partials merge exactly at drain points.
  * ``key`` axis — the key dimension of every plane is sharded, bounding
    per-chip HBM. Records are broadcast along ``key`` (the batch in_spec
    only names the data axis) and each chip masks the scatter to the key
    range it owns — no all-to-all in the hot path; the scatter itself does
    the routing.

State arrays carry a leading device axis of length ``D`` (the data-axis
size): a keyed plane is ``[D, K, W, ...]`` sharded
``P(data, key)``. The hot step runs under ``jax.shard_map`` with **zero
collectives**; merges (psum / pmin / pmax over ``data``, all riding ICI)
happen only when the host drains state — window close, changelog pull,
view peek — amortized over the window length.

This mirrors the scaling-book recipe: pick a mesh, annotate shardings, let
the compiled collectives ride ICI. DCN never sees lattice traffic; it is
reserved for the log-store replication plane (hstream_tpu.store).
"""

from __future__ import annotations

import functools
from typing import Callable, Mapping, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from hstream_tpu.engine import lattice
from hstream_tpu.engine.lattice import (
    EMPTY_START,
    LatticeSpec,
    build_step_fn,
    compile_agg_inputs,
    finalize_column,
    init_value,
    plane_merge_kinds,
)

_MERGE = {
    "sum": jax.lax.psum,
    "min": jax.lax.pmin,
    "max": jax.lax.pmax,
}


def _keyed(name: str) -> bool:
    return name != "slot_start"


class ShardedLattice:
    """The lattice of one query, sharded over a (data, key) mesh.

    Drop-in provider of the CompiledLattice callables with identical
    signatures (state first, host scalars as np types), so the host
    executor drives single-chip and multi-chip lattices the same way.
    ``n_keys`` of ``spec`` is the GLOBAL key capacity; it must divide by
    the key-axis size.
    """

    def __init__(self, spec: LatticeSpec, schema, filter_expr,
                 max_out: int, mesh: Mesh, layout,
                 data_axis: str = "data", key_axis: str = "key"):
        from hstream_tpu.engine.expr import compile_device

        self.layout = layout
        self.mesh = mesh
        self.data_axis = data_axis
        self.key_axis = key_axis if key_axis in mesh.axis_names else None
        self.n_data = mesh.shape[data_axis]
        self.n_key = mesh.shape[self.key_axis] if self.key_axis else 1
        if spec.n_keys % self.n_key != 0:
            raise ValueError(
                f"global key capacity {spec.n_keys} not divisible by "
                f"key-axis size {self.n_key}")
        self.spec = spec
        self.local_spec = LatticeSpec(
            n_keys=spec.n_keys // self.n_key, window=spec.window,
            aggs=spec.aggs, hll=spec.hll, qcfg=spec.qcfg,
            track_touched=spec.track_touched)
        self.max_out = max_out

        agg_inputs, self.null_keys = compile_agg_inputs(spec, schema)
        filter_fn = (compile_device(filter_expr, schema)
                     if filter_expr is not None else None)
        self._local_step = build_step_fn(self.local_spec, agg_inputs,
                                         filter_fn)
        self._merge_kinds = plane_merge_kinds(spec)
        bad = sorted(k for k, v in self._merge_kinds.items()
                     if v not in _MERGE)
        if bad:
            raise ValueError(
                f"plane(s) {bad} have no elementwise merge (TOPK): "
                "sharded execution is not supported for this query")
        self._state_specs = None  # built lazily from init_state's tree
        self._build()

    # ---- sharding specs ----------------------------------------------------

    def state_spec(self, name: str) -> P:
        if _keyed(name):
            return P(self.data_axis, self.key_axis)
        return P(self.data_axis)

    def state_sharding(self, name: str) -> NamedSharding:
        return NamedSharding(self.mesh, self.state_spec(name))

    def init_state(self) -> dict[str, jnp.ndarray]:
        """Global sharded state: local init replicated along ``data`` (all
        init values are merge identities, so D partial copies are exact)."""
        local = lattice.init_state(self.spec)  # global K, host-side
        out = {}
        for name, v in local.items():
            g = jnp.broadcast_to(v[None], (self.n_data,) + v.shape)
            out[name] = jax.device_put(g, self.state_sharding(name))
        return out

    def _specs_of(self, state_tree: Mapping[str, jnp.ndarray]):
        return {k: self.state_spec(k) for k in state_tree}

    # ---- compiled callables ------------------------------------------------

    def _build(self) -> None:
        mesh = self.mesh
        data_axis, key_axis = self.data_axis, self.key_axis
        Kl = self.local_spec.n_keys
        merge = self._merge_kinds
        spec_tree = {k: self.state_spec(k)
                     for k in lattice.init_state(self.spec)}
        local_spec = self.local_spec

        def key_offset():
            if key_axis is None:
                return 0
            return jax.lax.axis_index(key_axis) * Kl

        layout, null_keys = self.layout, self.null_keys

        def step_local(state, watermark, packed):
            local = {k: v[0] for k, v in state.items()}
            key_ids, ts, valid, cols = lattice.unpack_batch_device(
                packed, layout, null_keys)
            kid = key_ids - key_offset()
            ok = valid & (kid >= 0) & (kid < Kl)
            # slot_valid = the pre-key-ownership mask: slot_start is
            # key-independent, so every key shard must update it from ALL
            # valid records for the replicated out-spec to hold.
            new = self._local_step(local, watermark, kid, ts, ok, cols,
                                   slot_valid=valid)
            return {k: v[None] for k, v in new.items()}

        # packed batch [rows, B]: rows replicated, records sharded on data
        self.step = jax.jit(jax.shard_map(
            step_local, mesh=mesh,
            in_specs=(spec_tree, P(), P(None, data_axis)),
            out_specs=spec_tree, check_vma=False))

        def merged_col(state, slot):
            """One slot column, merged over the data axis -> {plane: [Kl]}"""
            col = {}
            for k, v in state.items():
                if k in ("slot_start", "touched"):
                    continue
                col[k] = _MERGE[merge[k]](v[0, :, slot], data_axis)
            return col

        def extract_local(state, slot):
            col = merged_col(state, slot)
            outs = finalize_column(local_spec, col)
            ws = jax.lax.pmax(state["slot_start"][0, slot], data_axis)
            return lattice.pack_extract_rows(local_spec, col["count"],
                                             ws, outs)

        # packed [2+n_aggs, K] — key axis concatenated over shards
        self.extract_slot = jax.jit(jax.shard_map(
            extract_local, mesh=mesh,
            in_specs=(spec_tree, P()),
            out_specs=P(None, key_axis), check_vma=False))

        def reset_local(state, slot):
            out = dict(state)
            for i, agg in enumerate(local_spec.aggs):
                if agg.kind == lattice.AggKind.COUNT_ALL:
                    continue  # aliases `count`, reset below
                name = lattice._plane_name(i, agg)
                out[name] = state[name].at[:, :, slot].set(init_value(agg))
                if agg.kind == lattice.AggKind.AVG:
                    out[name + "_n"] = state[name + "_n"].at[
                        :, :, slot].set(0)
            out["count"] = state["count"].at[:, :, slot].set(0)
            out["touched"] = state["touched"].at[:, :, slot].set(False)
            out["slot_start"] = state["slot_start"].at[:, slot].set(
                EMPTY_START)
            return out

        self.reset_slot = jax.jit(jax.shard_map(
            reset_local, mesh=mesh,
            in_specs=(spec_tree, P()),
            out_specs=spec_tree, check_vma=False))

        # ---- fused multi-slot close (one dispatch per close cycle) ----
        # Same contract as lattice.build_extract_reset_slots, with the
        # monoid merge riding ICI (psum/pmin/pmax over `data`) BEFORE
        # the single host fetch: slots i32[P] (entries < 0 pad), packed
        # out [P, 2+rows, K] with the key axis concatenated over shards
        # so kid indices in the buffer are GLOBAL key ids.

        def _extract_slots_local(state, slots):
            valid = slots >= 0
            safe = jnp.where(valid, slots, 0)

            def one(slot):
                col = merged_col(state, slot)
                outs = finalize_column(local_spec, col)
                ws = jax.lax.pmax(state["slot_start"][0, slot], data_axis)
                return lattice.pack_extract_rows(local_spec,
                                                 col["count"], ws, outs)

            packed = jax.vmap(one)(safe)
            return jnp.where(valid[:, None, None], packed, 0)

        def _reset_slots_local(state, slots):
            rs = jnp.where(slots >= 0, slots, local_spec.n_slots)
            out = dict(state)
            for i, agg in enumerate(local_spec.aggs):
                if agg.kind == lattice.AggKind.COUNT_ALL:
                    continue  # aliases `count`, reset below
                name = lattice._plane_name(i, agg)
                out[name] = state[name].at[:, :, rs].set(
                    init_value(agg), mode="drop")
                if agg.kind == lattice.AggKind.AVG:
                    out[name + "_n"] = state[name + "_n"].at[
                        :, :, rs].set(0, mode="drop")
            out["count"] = state["count"].at[:, :, rs].set(0, mode="drop")
            out["touched"] = state["touched"].at[:, :, rs].set(
                False, mode="drop")
            out["slot_start"] = state["slot_start"].at[:, rs].set(
                EMPTY_START, mode="drop")
            return out

        def extract_reset_local(state, slots):
            packed = _extract_slots_local(state, slots)
            return _reset_slots_local(state, slots), packed

        self.extract_reset_slots = jax.jit(jax.shard_map(
            extract_reset_local, mesh=mesh,
            in_specs=(spec_tree, P()),
            out_specs=(spec_tree, P(None, None, key_axis)),
            check_vma=False))

        self.extract_slots = jax.jit(jax.shard_map(
            _extract_slots_local, mesh=mesh,
            in_specs=(spec_tree, P()),
            out_specs=P(None, None, key_axis), check_vma=False))

        self.reset_slots = jax.jit(jax.shard_map(
            _reset_slots_local, mesh=mesh,
            in_specs=(spec_tree, P()),
            out_specs=spec_tree, check_vma=False))

        max_out = self.max_out

        def touched_local(state):
            # changelog across shards: merge the full lattice over `data`
            # (the one drain that pays a whole-lattice collective), then
            # enumerate per key-shard
            mask = jax.lax.pmax(state["touched"][0].astype(jnp.int32),
                                data_axis).astype(jnp.bool_)
            n = jnp.sum(mask.astype(jnp.int32))
            kidx, sidx = jnp.nonzero(mask, size=max_out, fill_value=0)
            col = {}
            for k, v in state.items():
                if k in ("slot_start", "touched"):
                    continue
                m = _MERGE[merge[k]](v[0], data_axis)
                col[k] = m[kidx, sidx]
            outs = finalize_column(local_spec, col)
            ws_merged = jax.lax.pmax(state["slot_start"][0], data_axis)
            valid = jnp.arange(max_out) < n
            out_state = dict(state)
            out_state["touched"] = jnp.zeros_like(state["touched"])
            kid_global = kidx + key_offset()
            packed = lattice.pack_touched_rows(
                local_spec, n, kid_global,
                jnp.where(valid, ws_merged[sidx], 0), outs, max_out)
            return out_state, packed[None]

        # packed per-key-shard buffers stacked on a leading axis
        self.extract_touched = jax.jit(jax.shard_map(
            touched_local, mesh=mesh,
            in_specs=(spec_tree,),
            out_specs=(spec_tree, P(key_axis)), check_vma=False))

    # ---- host-side helpers -------------------------------------------------

    def drain_touched(self, state):
        """Run extract_touched and flatten the per-key-shard results into
        (state', [(kid_global, win_start_rel, {name: value})...]) — one
        host fetch for the whole changelog."""
        state, packed = self.extract_touched(state)
        packed = np.asarray(packed)
        rows = []
        for s in range(self.n_key):
            n, kidx, ws, outs = lattice.unpack_touched_rows(
                self.local_spec, packed[s])
            for i in range(n):
                rows.append((int(kidx[i]), int(ws[i]),
                             {k: float(v[i]) for k, v in outs.items()}))
        return state, rows
