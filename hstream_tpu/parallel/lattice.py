"""Multi-chip sharding of the window-state lattice.

The reference is single-process for compute — its only cross-host axes are
storage replication and round-robin consumer dispatch (SURVEY §2.3;
hstream/src/HStream/Server/Handler.hs:896-922). The TPU-native design
scales the aggregation hot path itself over a 2-D device mesh:

  * ``data`` axis — records of each micro-batch are sharded across chips;
    every chip scatters its shard into a **partial lattice**. Because all
    accumulator planes are commutative monoids (lattice.plane_merge_kinds),
    partials merge exactly at drain points.
  * ``key`` axis — the key dimension of every plane is sharded, bounding
    per-chip HBM. Records are broadcast along ``key`` (the batch in_spec
    only names the data axis) and each chip masks the scatter to the key
    range it owns — no all-to-all in the hot path; the scatter itself does
    the routing.

State arrays carry a leading device axis of length ``D`` (the data-axis
size): a keyed plane is ``[D, K, W, ...]`` sharded
``P(data, key)``. The hot step runs under ``jax.shard_map`` with **zero
collectives**; merges (psum / pmin / pmax over ``data``, all riding ICI)
happen only when the host drains state — window close, changelog pull,
view peek — amortized over the window length.

This mirrors the scaling-book recipe: pick a mesh, annotate shardings, let
the compiled collectives ride ICI. DCN never sees lattice traffic; it is
reserved for the log-store replication plane (hstream_tpu.store).

The shard_map hygiene here (collectives only inside mesh bodies, no
host callbacks/fetches in them, axis names spelled consistently) is
checked by the tools/analyze shardmap pass — the CI jax build lacks
shard_map entirely, so these mistakes would otherwise surface only on
real mesh hardware.
"""

from __future__ import annotations

import functools
from typing import Callable, Mapping, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from hstream_tpu.engine import lattice
from hstream_tpu.engine.lattice import (
    EMPTY_START,
    LatticeSpec,
    build_step_fn,
    compile_agg_inputs,
    finalize_column,
    init_value,
    plane_merge_kinds,
)

_MERGE = {
    "sum": jax.lax.psum,
    "min": jax.lax.pmin,
    "max": jax.lax.pmax,
}


def _keyed(name: str) -> bool:
    return name != "slot_start"


class ShardedLattice:
    """The lattice of one query, sharded over a (data, key) mesh.

    Drop-in provider of the CompiledLattice callables with identical
    signatures (state first, host scalars as np types), so the host
    executor drives single-chip and multi-chip lattices the same way.
    ``n_keys`` of ``spec`` is the GLOBAL key capacity; it must divide by
    the key-axis size.
    """

    def __init__(self, spec: LatticeSpec, schema, filter_expr,
                 max_out: int, mesh: Mesh, layout,
                 data_axis: str = "data", key_axis: str = "key"):
        from hstream_tpu.engine.expr import compile_device

        self.layout = layout
        self.mesh = mesh
        self.data_axis = data_axis
        self.key_axis = key_axis if key_axis in mesh.axis_names else None
        self.n_data = mesh.shape[data_axis]
        self.n_key = mesh.shape[self.key_axis] if self.key_axis else 1
        if spec.n_keys % self.n_key != 0:
            raise ValueError(
                f"global key capacity {spec.n_keys} not divisible by "
                f"key-axis size {self.n_key}")
        self.spec = spec
        self.local_spec = LatticeSpec(
            n_keys=spec.n_keys // self.n_key, window=spec.window,
            aggs=spec.aggs, hll=spec.hll, qcfg=spec.qcfg,
            track_touched=spec.track_touched)
        self.max_out = max_out

        agg_inputs, self.null_keys = compile_agg_inputs(spec, schema)
        filter_fn = (compile_device(filter_expr, schema)
                     if filter_expr is not None else None)
        self._local_step = build_step_fn(self.local_spec, agg_inputs,
                                         filter_fn)
        self._merge_kinds = plane_merge_kinds(spec)
        bad = sorted(k for k, v in self._merge_kinds.items()
                     if v not in _MERGE)
        if bad:
            raise ValueError(
                f"plane(s) {bad} have no elementwise merge (TOPK): "
                "sharded execution is not supported for this query")
        self._state_specs = None  # built lazily from init_state's tree
        self._build()

    # ---- sharding specs ----------------------------------------------------

    def state_spec(self, name: str) -> P:
        if _keyed(name):
            return P(self.data_axis, self.key_axis)
        return P(self.data_axis)

    def state_sharding(self, name: str) -> NamedSharding:
        return NamedSharding(self.mesh, self.state_spec(name))

    def init_state(self) -> dict[str, jnp.ndarray]:
        """Global sharded state: local init replicated along ``data`` (all
        init values are merge identities, so D partial copies are exact)."""
        local = lattice.init_state(self.spec)  # global K, host-side
        out = {}
        for name, v in local.items():
            g = jnp.broadcast_to(v[None], (self.n_data,) + v.shape)
            out[name] = jax.device_put(g, self.state_sharding(name))
        return out

    def _specs_of(self, state_tree: Mapping[str, jnp.ndarray]):
        return {k: self.state_spec(k) for k in state_tree}

    # ---- compiled callables ------------------------------------------------

    def _build(self) -> None:
        mesh = self.mesh
        data_axis, key_axis = self.data_axis, self.key_axis
        Kl = self.local_spec.n_keys
        merge = self._merge_kinds
        spec_tree = {k: self.state_spec(k)
                     for k in lattice.init_state(self.spec)}
        local_spec = self.local_spec

        def key_offset():
            if key_axis is None:
                return 0
            return jax.lax.axis_index(key_axis) * Kl

        layout, null_keys = self.layout, self.null_keys

        def step_local(state, watermark, packed):
            local = {k: v[0] for k, v in state.items()}
            key_ids, ts, valid, cols = lattice.unpack_batch_device(
                packed, layout, null_keys)
            kid = key_ids - key_offset()
            ok = valid & (kid >= 0) & (kid < Kl)
            # slot_valid = the pre-key-ownership mask: slot_start is
            # key-independent, so every key shard must update it from ALL
            # valid records for the replicated out-spec to hold.
            new = self._local_step(local, watermark, kid, ts, ok, cols,
                                   slot_valid=valid)
            return {k: v[None] for k, v in new.items()}

        # packed batch [rows, B]: rows replicated, records sharded on data
        self.step = jax.jit(jax.shard_map(
            step_local, mesh=mesh,
            in_specs=(spec_tree, P(), P(None, data_axis)),
            out_specs=spec_tree, check_vma=False))

        def merged_col(state, slot):
            """One slot column, merged over the data axis -> {plane: [Kl]}"""
            col = {}
            for k, v in state.items():
                if k in ("slot_start", "touched"):
                    continue
                col[k] = _MERGE[merge[k]](v[0, :, slot], data_axis)
            return col

        def extract_local(state, slot):
            col = merged_col(state, slot)
            outs = finalize_column(local_spec, col)
            ws = jax.lax.pmax(state["slot_start"][0, slot], data_axis)
            return lattice.pack_extract_rows(local_spec, col["count"],
                                             ws, outs)

        # packed [2+n_aggs, K] — key axis concatenated over shards
        self.extract_slot = jax.jit(jax.shard_map(
            extract_local, mesh=mesh,
            in_specs=(spec_tree, P()),
            out_specs=P(None, key_axis), check_vma=False))

        def reset_local(state, slot):
            out = dict(state)
            for i, agg in enumerate(local_spec.aggs):
                if agg.kind == lattice.AggKind.COUNT_ALL:
                    continue  # aliases `count`, reset below
                name = lattice._plane_name(i, agg)
                out[name] = state[name].at[:, :, slot].set(init_value(agg))
                if agg.kind == lattice.AggKind.AVG:
                    out[name + "_n"] = state[name + "_n"].at[
                        :, :, slot].set(0)
            out["count"] = state["count"].at[:, :, slot].set(0)
            out["touched"] = state["touched"].at[:, :, slot].set(False)
            out["slot_start"] = state["slot_start"].at[:, slot].set(
                EMPTY_START)
            return out

        self.reset_slot = jax.jit(jax.shard_map(
            reset_local, mesh=mesh,
            in_specs=(spec_tree, P()),
            out_specs=spec_tree, check_vma=False))

        # ---- fused multi-slot close (one dispatch per close cycle) ----
        # Same contract as lattice.build_extract_reset_slots, with the
        # monoid merge riding ICI (psum/pmin/pmax over `data`) BEFORE
        # the single host fetch: slots i32[P] (entries < 0 pad), packed
        # out [P, 2+rows, K] with the key axis concatenated over shards
        # so kid indices in the buffer are GLOBAL key ids.

        def _extract_slots_local(state, slots):
            valid = slots >= 0
            safe = jnp.where(valid, slots, 0)

            def one(slot):
                col = merged_col(state, slot)
                outs = finalize_column(local_spec, col)
                ws = jax.lax.pmax(state["slot_start"][0, slot], data_axis)
                return lattice.pack_extract_rows(local_spec,
                                                 col["count"], ws, outs)

            packed = jax.vmap(one)(safe)
            return jnp.where(valid[:, None, None], packed, 0)

        def _reset_slots_local(state, slots):
            rs = jnp.where(slots >= 0, slots, local_spec.n_slots)
            out = dict(state)
            for i, agg in enumerate(local_spec.aggs):
                if agg.kind == lattice.AggKind.COUNT_ALL:
                    continue  # aliases `count`, reset below
                name = lattice._plane_name(i, agg)
                out[name] = state[name].at[:, :, rs].set(
                    init_value(agg), mode="drop")
                if agg.kind == lattice.AggKind.AVG:
                    out[name + "_n"] = state[name + "_n"].at[
                        :, :, rs].set(0, mode="drop")
            out["count"] = state["count"].at[:, :, rs].set(0, mode="drop")
            out["touched"] = state["touched"].at[:, :, rs].set(
                False, mode="drop")
            out["slot_start"] = state["slot_start"].at[:, rs].set(
                EMPTY_START, mode="drop")
            return out

        def extract_reset_local(state, slots):
            packed = _extract_slots_local(state, slots)
            return _reset_slots_local(state, slots), packed

        self.extract_reset_slots = jax.jit(jax.shard_map(
            extract_reset_local, mesh=mesh,
            in_specs=(spec_tree, P()),
            out_specs=(spec_tree, P(None, None, key_axis)),
            check_vma=False))

        self.extract_slots = jax.jit(jax.shard_map(
            _extract_slots_local, mesh=mesh,
            in_specs=(spec_tree, P()),
            out_specs=P(None, None, key_axis), check_vma=False))

        self.reset_slots = jax.jit(jax.shard_map(
            _reset_slots_local, mesh=mesh,
            in_specs=(spec_tree, P()),
            out_specs=spec_tree, check_vma=False))

        max_out = self.max_out

        def touched_local(state):
            # changelog across shards: merge the full lattice over `data`
            # (the one drain that pays a whole-lattice collective), then
            # enumerate per key-shard
            mask = jax.lax.pmax(state["touched"][0].astype(jnp.int32),
                                data_axis).astype(jnp.bool_)
            n = jnp.sum(mask.astype(jnp.int32))
            kidx, sidx = jnp.nonzero(mask, size=max_out, fill_value=0)
            col = {}
            for k, v in state.items():
                if k in ("slot_start", "touched"):
                    continue
                m = _MERGE[merge[k]](v[0], data_axis)
                col[k] = m[kidx, sidx]
            outs = finalize_column(local_spec, col)
            ws_merged = jax.lax.pmax(state["slot_start"][0], data_axis)
            valid = jnp.arange(max_out) < n
            out_state = dict(state)
            out_state["touched"] = jnp.zeros_like(state["touched"])
            kid_global = kidx + key_offset()
            packed = lattice.pack_touched_rows(
                local_spec, n, kid_global,
                jnp.where(valid, ws_merged[sidx], 0), outs, max_out)
            return out_state, packed[None]

        # packed per-key-shard buffers stacked on a leading axis
        self.extract_touched = jax.jit(jax.shard_map(
            touched_local, mesh=mesh,
            in_specs=(spec_tree,),
            out_specs=(spec_tree, P(key_axis)), check_vma=False))

# ---- key-sharded interval join ----------------------------------------------
#
# The shard_map mirror of engine.lattice's interval-join kernels: each
# key shard owns the join-key codes with ``code % n_shards == shard``,
# holds its own slice of both side stores, probes/inserts only the
# batch records it owns (the batch is replicated along the key axis —
# the ownership mask does the routing, like the aggregation lattice),
# and the per-shard match buffers CONCATENATE over ICI into one
# [rows, n_shards * match_cap] buffer before the single host fetch.
# Per-shard headers sit at column s * match_cap.


class ShardedJoinLattice:
    """Both sides of one interval join, key-sharded over a mesh axis.

    Capacities are PER SHARD. Drop-in twin of the single-chip kernels:
    ``probe_insert(mine, other, batch, n, within, cutoff)`` returns
    (mine', packed [rows, n_shards * match_cap]); ``evict(left, right,
    cutoff, delta)`` compacts both sides per shard and returns the
    per-shard live counts [n_shards, 2]."""

    def __init__(self, mesh: Mesh, key_axis: str, cap: int, bcap: int,
                 match_cap: int, n_cols_l: int, n_cols_r: int):
        self.mesh = mesh
        self.key_axis = key_axis
        self.n_shards = mesh.shape[key_axis]
        self.cap = cap
        self.bcap = bcap
        self.match_cap = match_cap
        self.n_cols = {"l": n_cols_l, "r": n_cols_r}
        self._build()

    def init_store(self, side: str) -> dict[str, jnp.ndarray]:
        """Per-shard empty stores stacked on a leading shard axis and
        placed with the key-axis sharding."""
        local = lattice.init_join_store(self.cap, self.n_cols[side])
        out = {}
        for k, v in local.items():
            g = jnp.broadcast_to(v[None], (self.n_shards,) + v.shape)
            out[k] = jax.device_put(g, NamedSharding(
                self.mesh, P(self.key_axis)))
        return out

    def _build(self) -> None:
        mesh, key_axis = self.mesh, self.key_axis
        n_shards = self.n_shards
        bcap, match_cap = self.bcap, self.match_cap
        store_spec = {k: P(key_axis) for k in ("code", "ts", "flags",
                                               "cols")}

        def owned_mask(bcode):
            shard = jax.lax.axis_index(key_axis)
            return (bcode % n_shards) == shard

        def probe_insert_local(mine, other, batch, n, within, cutoff,
                               nm, no):
            m = {k: v[0] for k, v in mine.items()}
            o = {k: v[0] for k, v in other.items()}
            owned = owned_mask(batch[0])
            packed = lattice._join_probe(o, batch, n, within, cutoff,
                                         bcap, match_cap, nm,
                                         owned=owned)
            new = lattice._join_insert(m, batch, n, bcap, nm,
                                       owned=owned)
            return {k: v[None] for k, v in new.items()}, packed

        def mk_probe(nm, no):
            def f(mine, other, batch, n, within, cutoff):
                return probe_insert_local(mine, other, batch, n,
                                          within, cutoff, nm, no)

            return jax.jit(jax.shard_map(
                f, mesh=mesh,
                in_specs=(store_spec, store_spec, P(), P(), P(), P()),
                out_specs=(store_spec, P(key_axis)), check_vma=False))

        self.probe_insert_l = mk_probe(self.n_cols["l"],
                                       self.n_cols["r"])
        self.probe_insert_r = mk_probe(self.n_cols["r"],
                                       self.n_cols["l"])

        cap = self.cap

        def evict_local(left, right, cutoff, delta):
            def _core(code, ts):
                alive = (code < lattice.JOIN_SENT_CODE) & (ts >= cutoff)
                code2 = jnp.where(alive, code, lattice.JOIN_SENT_CODE)
                ts2 = jnp.where(alive, ts - delta, 0)
                idx = jnp.arange(cap, dtype=jnp.int32)
                return jax.lax.sort((code2, ts2, idx), num_keys=2) + (
                    jnp.sum(alive.astype(jnp.int32)),)

            outs = []
            ns = []
            for st in (left, right):
                scode, sts, order, n = _core(st["code"][0], st["ts"][0])
                outs.append({"code": scode[None], "ts": sts[None],
                             "flags": st["flags"][0][order][None],
                             "cols": st["cols"][0][:, order][None]})
                ns.append(n)
            return outs[0], outs[1], jnp.stack(ns)[None]

        self.evict = jax.jit(jax.shard_map(
            evict_local, mesh=mesh,
            in_specs=(store_spec, store_spec, P(), P()),
            out_specs=(store_spec, store_spec, P(key_axis)),
            check_vma=False))

    def probe_insert(self, side: str, mine, other, batch, n, within,
                     cutoff):
        fn = (self.probe_insert_l if side == "l"
              else self.probe_insert_r)
        return fn(mine, other, batch, n, within, cutoff)

    def unpack_matches(self, packed: np.ndarray, side: str):
        """Flatten the shard-concatenated match buffer into host arrays
        in shard order: (total, kid, jts_rel, my_flags, other_flags,
        my_cols, other_cols) — the sharded twin of
        lattice.unpack_join_matches. `total` sums the per-shard headers;
        truncation per shard is visible as total > len(kid)."""
        nm = self.n_cols[side]
        parts = []
        total = 0
        for s in range(self.n_shards):
            seg = packed[:, s * self.match_cap:(s + 1) * self.match_cap]
            t, kid, jts, mf, of, mc, oc = lattice.unpack_join_matches(
                seg, nm)
            total += t
            parts.append((kid, jts, mf, of, mc, oc))
        return (total,
                np.concatenate([p[0] for p in parts]),
                np.concatenate([p[1] for p in parts]),
                np.concatenate([p[2] for p in parts]),
                np.concatenate([p[3] for p in parts]),
                np.concatenate([p[4] for p in parts], axis=1),
                np.concatenate([p[5] for p in parts], axis=1))
