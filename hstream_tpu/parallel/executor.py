"""Host executor for mesh-sharded queries.

Same host-side semantics as engine.QueryExecutor (watermark, window
bookkeeping, key dictionary, emission); only the device callables differ —
they come from a ShardedLattice, so every process() scatters a sharded
batch into per-chip partial lattices and drains merge over the mesh.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from hstream_tpu.engine import lattice as se_lattice
from hstream_tpu.engine.executor import QueryExecutor, StagedBatch
from hstream_tpu.engine.plan import AggregateNode
from hstream_tpu.engine.types import Schema
from hstream_tpu.parallel.lattice import ShardedLattice


class ShardedQueryExecutor(QueryExecutor):
    """QueryExecutor whose lattice lives sharded over a device mesh.

    ``initial_keys`` is the fixed GLOBAL key capacity (must divide by the
    key-axis size). Key growth re-shards through the host — rare and
    logged; size capacity generously for production queries.
    """

    # the sharded drain path fetches synchronously (one transfer of
    # the per-shard stack); the deferral flag would be a silent no-op
    supports_deferred_changes = False

    def __init__(self, node: AggregateNode, schema: Schema, *, mesh,
                 data_axis: str = "data", key_axis: str = "key",
                 emit_changes: bool = True, initial_keys: int = 1024,
                 batch_capacity: int = 4096):
        self._mesh = mesh
        self._data_axis = data_axis
        self._key_axis = key_axis
        # device dispatches that ran under shard_map (step + drain);
        # the query task mirrors deltas into the sharded_dispatches
        # stat family
        self.sharded_dispatches = 0
        super().__init__(node, schema, emit_changes=emit_changes,
                         initial_keys=initial_keys,
                         batch_capacity=batch_capacity)

    def _compile(self) -> None:
        from hstream_tpu.engine.expr import columns_of

        self._layout = tuple(
            (name, se_lattice.layout_tag(self.schema.type_of(name)))
            for name in self._needed_cols)
        sharded = ShardedLattice(
            self.spec, self.schema, self._filter_expr,
            self.batch_capacity * self.spec.windows_per_record,
            self._mesh, self._layout, data_axis=self._data_axis,
            key_axis=self._key_axis)
        self._sharded = sharded
        self._step = sharded.step
        self._extract_slot = self._count_close_kernel(sharded.extract_slot)
        self._reset_slot = self._count_close_kernel(sharded.reset_slot)
        self._extract_reset_slots = self._count_close_kernel(
            sharded.extract_reset_slots)
        self._extract_slots = sharded.extract_slots  # peek: read path
        self._reset_slots = self._count_close_kernel(sharded.reset_slots)
        self._extract_touched = sharded.extract_touched
        self._null_specs = [
            (key, sorted(columns_of(agg.input)))
            for key, agg in zip(sharded.null_keys, self.spec.aggs)
            if key is not None
        ]
        # Replace single-chip state from the base __init__ with sharded
        # state (keyed planes gain a leading data-shard axis); the grow
        # path installs its own padded arrays instead.
        if not getattr(self, "_defer_state_init", False):
            cur = getattr(self, "state", None)
            if cur is None or cur["count"].ndim == 2:
                self.state = sharded.init_state()

    def _grow_keys(self) -> None:
        # gather → pad global key axis (axis 1 of keyed planes) → re-shard
        import jax

        new_k = self.spec.n_keys * 2
        kinds = se_lattice.plane_merge_kinds(self.spec)
        extra = new_k - self.spec.n_keys
        # key growth re-shards through the host: one fetch per plane is
        # unavoidable (mixed dtypes/ranks cannot stack).
        # analyze: ok dispatch-sync — rare re-shard path by design
        host = {k: np.asarray(v) for k, v in self.state.items()}
        grown = {}
        for k, v in host.items():
            if k == "slot_start":
                grown[k] = v
                continue
            pad = [(0, 0), (0, extra)] + [(0, 0)] * (v.ndim - 2)
            fill = (np.inf if kinds.get(k) == "min"
                    else -np.inf if kinds.get(k) == "max" and
                    v.dtype == np.float32 else 0)
            grown[k] = np.pad(v, pad, constant_values=fill)
        self.spec = se_lattice.LatticeSpec(
            n_keys=new_k, window=self.spec.window, aggs=self.spec.aggs,
            hll=self.spec.hll, qcfg=self.spec.qcfg,
            track_touched=self.spec.track_touched)
        self._defer_state_init = True
        try:
            self._compile()
        finally:
            self._defer_state_init = False
        self.state = {
            k: jax.device_put(v, self._sharded.state_sharding(k))
            for k, v in grown.items()
        }

    def stage_columnar(self, key_ids, ts_ms, cols, nulls=None,
                       upload: bool = True) -> StagedBatch | None:
        # Sharded execution keeps the v1 packed transport (the batch is
        # distributed by shard_map, not the link codec), so staging
        # degrades to a host-held batch; process_staged routes combo=None
        # through the synchronous sharded path. IngestPipeline therefore
        # still works, just without encode/step overlap.
        key_ids = np.asarray(key_ids, dtype=np.int32)
        if len(key_ids) == 0:
            return None
        ts = np.asarray(ts_ms, dtype=np.int64)
        return StagedBatch(
            n=len(key_ids), cap=0, combo=None, bases=None, words=None,
            epoch=0, ts_min=int(ts.min()), ts_max=int(ts.max()),
            key_ids=key_ids, ts_ms=ts, cols=cols, nulls=nulls)

    # contract: dispatches<=1 fetches<=0
    def _run_step(self, cap, n, key_ids, ts_rel, cols, valid,
                  null_streams, wm_rel) -> None:
        # The sharded path keeps the v1 packed transport: the batch is
        # split across the data axis by shard_map, so the wire format is
        # the intra-host one (device_put with a sharding), not the
        # bit-packed link codec.
        null_masks = [null_streams.get(nk) for nk, _ in self._null_specs]
        packed = se_lattice.pack_batch_host(
            cap, n, key_ids,
            # both callers narrow ts_rel only after their own span check
            # analyze: ok overflow-narrowing — caller-guarded narrow
            np.asarray(ts_rel, dtype=np.int32), valid,
            cols, null_masks, self._layout)
        self.state = self._step(self.state, wm_rel, packed)
        self.sharded_dispatches += 1

    # contract: dispatches<=1 fetches<=1
    def _drain_changes(self):
        """Columnar sharded changelog drain: ONE host fetch of the
        per-key-shard packed buffers, then the same batched decode the
        single-chip path uses (kid rows already carry GLOBAL key ids).
        A lone shard's batch stays a ColumnarEmit."""
        from hstream_tpu.common.columnar import extend_rows

        self.state, packed = self._extract_touched(self.state)
        self.sharded_dispatches += 1
        packed = np.asarray(packed)        # [n_key_shards, rows, max_out]
        out = None
        for s in range(self._sharded.n_key):
            out = extend_rows(out, self._decode_changes(packed[s],
                                                        self.epoch))
        return out if out is not None else []
