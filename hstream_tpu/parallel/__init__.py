"""Multi-chip scaling: mesh-sharded lattices and executors.

See hstream_tpu.parallel.lattice for the sharding design (data-parallel
partial lattices + key-sharded planes over a 2-D mesh, monoid merges at
drain points riding ICI).
"""

from hstream_tpu.parallel.lattice import ShardedLattice
from hstream_tpu.parallel.executor import ShardedQueryExecutor


def make_mesh(n_data: int | None = None, n_key: int = 1,
              devices=None):
    """A (data, key) mesh over the available devices (row-major)."""
    import jax
    import numpy as np

    devices = devices if devices is not None else jax.devices()
    if n_data is None:
        n_data = len(devices) // n_key
    arr = np.asarray(devices[:n_data * n_key]).reshape(n_data, n_key)
    from jax.sharding import Mesh

    return Mesh(arr, ("data", "key"))


__all__ = ["ShardedLattice", "ShardedQueryExecutor", "make_mesh"]
