"""hstream-tpu: a TPU-native streaming database framework.

Capabilities mirror the HStreamDB reference (Yu-zh/hstream): persistent
pub/sub streams over a log store, SQL continuous queries with windowed
aggregation, materialized views with pull queries, subscriptions, and
source/sink connectors — but the continuous-query hot path executes as
XLA-compiled micro-batch kernels over device-resident state lattices
instead of a per-record interpreted processor DAG.

Layer map (bottom up), mirroring the reference's capability boundaries
(see SURVEY.md §1):

  store/       durable log store (C++ core + in-memory test backend)
  common/      record codec, id generation, logging, errors
  engine/      logical plans + the jitted TPU window-aggregation executor
  parallel/    device-mesh sharding of engine state (dp over records,
               kp over keys) using shard_map + XLA collectives
  sql/         SQL lexer/parser/AST -> validated plan -> engine plan
  server/      gRPC HStreamApi service, subscriptions, metadata persistence
  connectors/  hstore source/sink, MySQL / ClickHouse sinks
  client/      SQL REPL and client actions
  stats/       per-stream counters and time-series rates
"""

__version__ = "0.1.0"
