"""Columnar record batches: the host<->device data format.

JSON records (dicts decoded from HStreamRecord payloads) are staged into
fixed-capacity columnar batches. Numeric fields become float32/int32
columns; strings are dictionary-encoded to int32 ids against a per-field
host dictionary (device code only ever compares ids). Batch capacity is
rounded up to a power of two so jit specializes on a handful of shapes.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Iterable, Mapping, Sequence

import numpy as np


class ColumnType(enum.Enum):
    FLOAT = "float"    # float32 on device
    INT = "int"        # int32 on device
    BOOL = "bool"
    STRING = "string"  # dictionary-encoded int32 ids


_NP_DTYPE = {
    ColumnType.FLOAT: np.float32,
    ColumnType.INT: np.int32,
    ColumnType.BOOL: np.bool_,
    ColumnType.STRING: np.int32,
}


@dataclass(frozen=True)
class Schema:
    """Ordered field name -> type mapping for one stream."""

    fields: tuple[tuple[str, ColumnType], ...]

    @staticmethod
    def of(**kw: ColumnType) -> "Schema":
        return Schema(tuple(kw.items()))

    def names(self) -> list[str]:
        return [n for n, _ in self.fields]

    def type_of(self, name: str) -> ColumnType:
        for n, t in self.fields:
            if n == name:
                return t
        raise KeyError(name)

    def has(self, name: str) -> bool:
        return any(n == name for n, _ in self.fields)


class StringDictionary:
    """Per-field host dictionary: string value <-> dense int32 id."""

    def __init__(self) -> None:
        self._to_id: dict[str, int] = {}
        self._values: list[str] = []

    def encode(self, value: str) -> int:
        i = self._to_id.get(value)
        if i is None:
            i = len(self._values)
            self._to_id[value] = i
            self._values.append(value)
        return i

    def lookup(self, value: str) -> int:
        """Encode without inserting; -1 when unseen (for literal compares)."""
        return self._to_id.get(value, -1)

    def decode(self, idx: int) -> str:
        return self._values[idx]

    def __len__(self) -> int:
        return len(self._values)


def canon_key(key: tuple) -> tuple:
    """Canonicalize a group-key tuple: float values round-trip through
    float32 so JSON-producer rows (python floats) and columnar batches
    (f32 columns) agree on group identity — 20.1 and f32(20.1) must be
    ONE group, not two."""
    if any(isinstance(v, float) for v in key):
        return tuple(float(np.float32(v)) if isinstance(v, float) else v
                     for v in key)
    return key


def round_up_pow2(n: int, lo: int = 256) -> int:
    cap = lo
    while cap < n:
        cap *= 2
    return cap


@dataclass
class HostBatch:
    """A columnar batch on host, padded to `capacity` rows.

    `ts_ms` carries absolute epoch milliseconds (int64, host only); the
    executor converts to device-relative int32 before the jitted step.
    """

    schema: Schema
    capacity: int
    n: int
    ts_ms: np.ndarray                     # int64 [capacity]
    valid: np.ndarray                     # bool  [capacity]
    cols: dict[str, np.ndarray]           # per field, [capacity]
    nulls: dict[str, np.ndarray]          # per field, bool [capacity], True=missing

    @staticmethod
    def from_rows(schema: Schema, rows: Sequence[Mapping[str, Any]],
                  ts_ms: Sequence[int],
                  dicts: Mapping[str, StringDictionary],
                  capacity: int | None = None) -> "HostBatch":
        n = len(rows)
        cap = capacity or round_up_pow2(n)
        valid = np.zeros(cap, dtype=np.bool_)
        valid[:n] = True
        ts = np.zeros(cap, dtype=np.int64)
        ts[:n] = np.asarray(ts_ms, dtype=np.int64)
        cols: dict[str, np.ndarray] = {}
        nulls: dict[str, np.ndarray] = {}
        for name, ctype in schema.fields:
            arr = np.zeros(cap, dtype=_NP_DTYPE[ctype])
            null = np.zeros(cap, dtype=np.bool_)
            if ctype == ColumnType.STRING:
                d = dicts[name]
                for i, row in enumerate(rows):
                    v = row.get(name)
                    if v is None:
                        arr[i] = -1
                        null[i] = True
                    else:
                        arr[i] = d.encode(str(v))
            else:
                for i, row in enumerate(rows):
                    v = row.get(name)
                    if v is None or not isinstance(v, (int, float, bool)):
                        null[i] = True
                    else:
                        arr[i] = v
            cols[name] = arr
            nulls[name] = null
        return HostBatch(schema=schema, capacity=cap, n=n, ts_ms=ts,
                         valid=valid, cols=cols, nulls=nulls)
