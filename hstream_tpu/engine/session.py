"""Session-window aggregation.

Reference semantics (SessionWindowedStream.hs:84-118): a record at ts
belongs to session [ts, ts]; sessions of the same key merge when their
gap-extended intervals overlap (ts within `gap` of the session edge);
a session closes when the watermark passes end + gap + grace.

Merge-on-overlap is inherently sequential per key, so the design follows
SURVEY §7: per-batch segmentation is vectorized (lexsort by (key, ts) +
gap-break detection + reduceat segment reduction), then the few resulting
segment aggregates merge into per-key session state on the host. All
accumulators are monoids, so segment/session merges are exact. Device
offload of the segmentation is a later optimization — per-batch work is
O(B log B) numpy, and segment counts are tiny compared to record counts.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Mapping, Sequence

import numpy as np

from hstream_tpu.common.errors import SQLCodegenError
from hstream_tpu.engine.executor import QueryExecutor
from hstream_tpu.engine.expr import eval_host
from hstream_tpu.engine.plan import AggKind, AggregateNode, AggSpec
from hstream_tpu.engine.sketches import HLLConfig, QuantileConfig
from hstream_tpu.engine.types import Schema, canon_key
from hstream_tpu.engine.window import SessionWindow


# ---- numpy sketch helpers (host-side finalize) -----------------------------

def hll_update_np(values: np.ndarray, cfg: HLLConfig):
    """(register idx, rank) per value — numpy mirror of
    sketches.hll_update_indices (same hash, same estimates merge)."""
    v = np.ascontiguousarray(values, dtype=np.float32)
    v = np.where(v == 0.0, np.float32(0.0), v)
    h = v.view(np.uint32).copy()
    h ^= h >> 16
    h = (h * np.uint32(0x85EBCA6B)) & np.uint32(0xFFFFFFFF)
    h ^= h >> 13
    h = (h * np.uint32(0xC2B2AE35)) & np.uint32(0xFFFFFFFF)
    h ^= h >> 16
    p = cfg.precision
    reg = (h >> (32 - p)).astype(np.int64)
    w = (h << p) & np.uint32(0xFFFFFFFF)
    # count leading zeros of remaining bits
    rank = np.zeros(len(v), dtype=np.int64)
    x = w.copy()
    for shift in (16, 8, 4, 2, 1):
        empty = (x >> (32 - shift)) == 0
        rank += np.where(empty, shift, 0)
        x = np.where(empty, (x << shift) & np.uint32(0xFFFFFFFF), x)
    rank = np.where(w == 0, 32, rank)
    rank = np.minimum(rank + 1, 32 - p + 1).astype(np.int8)
    return reg, rank


def hll_estimate_np(registers: np.ndarray, cfg: HLLConfig) -> np.ndarray:
    """HLL estimate over the last axis: accepts one register set [m] or
    a batch [..., m] (batched session closes finalize in one call)."""
    m = cfg.m
    if m == 16:
        alpha = 0.673
    elif m == 32:
        alpha = 0.697
    elif m == 64:
        alpha = 0.709
    else:
        alpha = 0.7213 / (1 + 1.079 / m)
    regs = np.asarray(registers).astype(np.float64)
    raw = alpha * m * m / np.sum(np.exp2(-regs), axis=-1)
    zeros = np.sum(np.asarray(registers) == 0, axis=-1)
    lin = m * np.log(m / np.maximum(zeros, 1))
    return np.where((raw <= 2.5 * m) & (zeros > 0), lin, raw)


def quantile_bin_np(values: np.ndarray, cfg: QuantileConfig) -> np.ndarray:
    v = np.maximum(values.astype(np.float64), 0.0)
    safe = np.maximum(v, cfg.min_value)
    b = np.floor(np.log(safe / cfg.min_value) / cfg.gamma_log).astype(
        np.int64) + 1
    b = np.clip(b, 1, cfg.n_bins - 1)
    return np.where(v < cfg.min_value, 0, b)


def quantile_estimate_np(hist: np.ndarray, q: float,
                         cfg: QuantileConfig) -> np.ndarray:
    """Quantile estimate over the last axis: one histogram [n_bins] or
    a batch [..., n_bins]. argmax(cdf >= target) is searchsorted-left
    with a batch axis."""
    cdf = np.cumsum(hist, axis=-1)
    total = cdf[..., -1]
    target = q * total
    idx = np.argmax(cdf >= target[..., None], axis=-1)
    idx = np.minimum(idx, cfg.n_bins - 1)
    log_lo = (idx - 1.0) * cfg.gamma_log
    est = cfg.min_value * np.exp(log_lo + 0.5 * cfg.gamma_log)
    return np.where((idx == 0) | (total == 0), 0.0, est)


# ---- session state ---------------------------------------------------------

@dataclass
class _Session:
    start: int
    end: int                      # last record ts
    accs: dict[str, Any] = field(default_factory=dict)


def _acc_init(agg: AggSpec, hll: HLLConfig, qcfg: QuantileConfig):
    if agg.kind in (AggKind.COUNT_ALL, AggKind.COUNT):
        return 0
    if agg.kind in (AggKind.SUM,):
        return 0.0
    if agg.kind == AggKind.AVG:
        return (0.0, 0)
    if agg.kind == AggKind.MIN:
        return math.inf
    if agg.kind == AggKind.MAX:
        return -math.inf
    if agg.kind == AggKind.APPROX_COUNT_DISTINCT:
        return np.zeros(hll.m, dtype=np.int8)
    if agg.kind == AggKind.APPROX_QUANTILE:
        return np.zeros(qcfg.n_bins, dtype=np.int64)
    if agg.kind in (AggKind.TOPK, AggKind.TOPK_DISTINCT):
        return []  # descending value list, trimmed to k
    raise SQLCodegenError(f"session agg {agg.kind} unsupported")


def _acc_merge(agg: AggSpec, a, b):
    if agg.kind in (AggKind.COUNT_ALL, AggKind.COUNT, AggKind.SUM):
        return a + b
    if agg.kind == AggKind.AVG:
        return (a[0] + b[0], a[1] + b[1])
    if agg.kind == AggKind.MIN:
        return min(a, b)
    if agg.kind == AggKind.MAX:
        return max(a, b)
    if agg.kind == AggKind.APPROX_COUNT_DISTINCT:
        return np.maximum(a, b)
    if agg.kind == AggKind.APPROX_QUANTILE:
        return a + b
    if agg.kind == AggKind.TOPK:
        from hstream_tpu.engine.lattice import agg_width

        return sorted(a + b, reverse=True)[: agg_width(agg)]
    if agg.kind == AggKind.TOPK_DISTINCT:
        from hstream_tpu.engine.lattice import agg_width

        return sorted(set(a) | set(b), reverse=True)[: agg_width(agg)]
    raise SQLCodegenError(f"session agg {agg.kind} unsupported")


class SessionExecutor:
    """Windowed-by-session grouped aggregation (host merge engine).

    API-compatible with QueryExecutor: process(rows, ts_ms) -> emitted
    rows; emitted rows carry winStart/winEnd = [session start,
    session end + gap) like the reference's session serde."""

    def __init__(self, node: AggregateNode, schema: Schema, *,
                 emit_changes: bool = False,
                 hll: HLLConfig = HLLConfig(),
                 qcfg: QuantileConfig = QuantileConfig()):
        if not isinstance(node.window, SessionWindow):
            raise SQLCodegenError("SessionExecutor needs a SessionWindow")
        self.node = node
        self.schema = schema
        self.window: SessionWindow = node.window
        self.emit_changes = emit_changes
        self.hll = hll
        self.qcfg = qcfg
        self.group_cols = [g.name for g in node.group_keys]
        self.aggs = list(node.aggs)
        self.watermark: int = -1
        # key tuple -> list[_Session], kept sorted by start
        self.sessions: dict[tuple, list[_Session]] = {}
        self._filter = QueryExecutor._extract_filter(self)  # same chain walk
        # batch key-encoding caches (rebuildable; not snapshot state)
        self._code_of: dict[tuple, int] = {}   # canon key -> code
        self._code_rev: list[tuple] = []       # code -> canon key
        self._raw_memo: dict[Any, int] = {}    # raw value(s) -> code
        self._input_cache: dict = {}           # per-batch input columns

    # QueryExecutor._extract_filter reads self.node only.

    def _agg_input(self, agg: AggSpec, row: Mapping[str, Any]):
        if agg.input is None:
            return 1
        try:
            v = eval_host(agg.input, row)
        except (TypeError, KeyError):
            return None
        # non-numeric values are NULL, the same rule the vectorized
        # path's _agg_input_cols applies — lateness must not change
        # whether a malformed record is skipped or crashes the query
        if not isinstance(v, (int, float)):
            return None
        if isinstance(v, float) and not math.isfinite(v):
            return None
        return v

    def _acc_update(self, agg: AggSpec, acc, v):
        if agg.kind == AggKind.COUNT_ALL:
            return acc + 1
        if v is None:
            return acc
        if agg.kind == AggKind.COUNT:
            return acc + 1
        if agg.kind == AggKind.SUM:
            return acc + float(v)
        if agg.kind == AggKind.AVG:
            return (acc[0] + float(v), acc[1] + 1)
        if agg.kind == AggKind.MIN:
            return min(acc, float(v))
        if agg.kind == AggKind.MAX:
            return max(acc, float(v))
        if agg.kind == AggKind.APPROX_COUNT_DISTINCT:
            reg, rank = hll_update_np(np.asarray([float(v)]), self.hll)
            acc = acc.copy()
            acc[reg[0]] = max(acc[reg[0]], rank[0])
            return acc
        if agg.kind == AggKind.APPROX_QUANTILE:
            b = int(quantile_bin_np(np.asarray([float(v)]), self.qcfg)[0])
            acc = acc.copy()
            acc[b] += 1
            return acc
        if agg.kind in (AggKind.TOPK, AggKind.TOPK_DISTINCT):
            return _acc_merge(agg, acc, [float(v)])
        raise SQLCodegenError(f"session agg {agg.kind} unsupported")

    # ---- vectorized batch path ---------------------------------------------
    #
    # SURVEY §7's session plan, realized: per-batch segmentation is
    # numpy (lexsort by (key, ts) + gap-break detection), per-SEGMENT
    # accumulators come from reduceat / scattered histogram updates, and
    # only the few segments (<= touched keys x batch span / gap) walk
    # the host merge. Merging a whole segment is exact: within a segment
    # consecutive records are <= gap apart, so sequential per-record
    # processing would land them all in one session chain, and every
    # accumulator is a commutative monoid. Segments that might interact
    # with the late-record policy (any record at ts + gap + grace <= the
    # pre-batch watermark) take the per-record fallback, which preserves
    # the reference's record-at-a-time drop-vs-merge decisions
    # (SessionWindowedStream.hs:84-118).

    def process(self, rows: Sequence[Mapping[str, Any]],
                ts_ms: Sequence[int]) -> list[dict[str, Any]]:
        if not rows:
            return []
        gap = self.window.gap_ms
        grace = self.window.grace_ms
        touched: set[tuple] = set()
        ts_all = np.asarray(ts_ms, np.int64)
        new_wm = int(ts_all.max())
        ts = ts_all
        if self._filter is not None:
            keep = np.fromiter((self._row_passes(r) for r in rows),
                               np.bool_, len(rows))
            if not keep.all():
                idx = np.nonzero(keep)[0]
                rows = [rows[i] for i in idx.tolist()]
                ts = ts[idx]
        n = len(rows)
        if n:
            codes, key_rev = self._key_codes(rows)
            order = np.lexsort((ts, codes))
            ks = codes[order]
            tss = ts[order]
            brk = np.empty(n, np.bool_)
            brk[0] = True
            brk[1:] = (ks[1:] != ks[:-1]) | ((tss[1:] - tss[:-1]) > gap)
            starts = np.nonzero(brk)[0]
            ends = np.append(starts[1:], n)
            seg_t0 = tss[starts]
            seg_t1 = tss[ends - 1]
            nseg = len(starts)
            wm = self.watermark
            # any record possibly subject to the late policy -> per-row
            slow = (seg_t0 + gap + grace <= wm if wm >= 0
                    else np.zeros(nseg, np.bool_))
            seg_of_row = np.cumsum(brk) - 1
            accs_cols = self._segment_accs(rows, order, starts, ends,
                                           seg_of_row)
            seg_keys = ks[starts]
            for j in range(nseg):
                key = key_rev[int(seg_keys[j])]
                if slow[j]:
                    for i in order[starts[j]:ends[j]].tolist():
                        if self._ingest_row(rows[i], int(ts[i])):
                            touched.add(key)
                    continue
                accs = {a.out_name: accs_cols[a.out_name][j]
                        for a in self.aggs}
                self._merge_segment(key, int(seg_t0[j]), int(seg_t1[j]),
                                    accs)
                touched.add(key)
        if new_wm > self.watermark:
            self.watermark = new_wm

        out: list[dict[str, Any]] = []
        if self.emit_changes:
            for key in touched:
                for s in self.sessions.get(key, []):
                    r = self._emit_row(key, s)
                    if r is not None:
                        out.append(r)
        out.extend(self.close_due_sessions())
        return out

    def _row_passes(self, row: Mapping[str, Any]) -> bool:
        try:
            return bool(eval_host(self._filter, row))
        except (TypeError, KeyError):
            return False

    # key-encoding cache bound: codes only matter WITHIN one batch, so
    # the caches are safe to drop wholesale; bounding them keeps a
    # months-long high-cardinality query (session per request_id) from
    # growing without limit after its sessions closed
    _KEY_CACHE_MAX = 1 << 18

    def _key_codes(self, rows) -> tuple[np.ndarray, list]:
        """Dense int codes per row's group key. Codes persist across
        batches (encoding cache only — not part of snapshot state);
        raw-value memoization keeps the per-row cost to one dict hit."""
        if len(self._code_of) > self._KEY_CACHE_MAX:
            self._code_of = {}
            self._code_rev = []
            self._raw_memo = {}
        out = np.empty(len(rows), np.int64)
        rev = self._code_rev
        if len(self.group_cols) == 1:
            c = self.group_cols[0]
            memo = self._raw_memo
            for i, r in enumerate(rows):
                v = r.get(c)
                code = memo.get(v)
                if code is None:
                    k = canon_key((v,))
                    code = self._code_of.get(k)
                    if code is None:
                        code = len(rev)
                        self._code_of[k] = code
                        rev.append(k)
                    memo[v] = code
                out[i] = code
        else:
            cols = self.group_cols
            memo = self._raw_memo
            for i, r in enumerate(rows):
                raw = tuple(r.get(c) for c in cols)
                code = memo.get(raw)
                if code is None:
                    k = canon_key(raw)
                    code = self._code_of.get(k)
                    if code is None:
                        code = len(rev)
                        self._code_of[k] = code
                        rev.append(k)
                    memo[raw] = code
                out[i] = code
        return out, rev

    def _agg_input_cols(self, a: AggSpec, rows,
                        n: int) -> tuple[np.ndarray, np.ndarray]:
        """(values f64[n], valid bool[n]) for one aggregate's input.
        Invalid = missing / None / non-numeric / non-finite (the same
        records _agg_input returns None for)."""
        from hstream_tpu.engine.expr import Col

        if a.input is None:  # _agg_input's constant-1 case
            return np.ones(n, np.float64), np.ones(n, np.bool_)
        # one extraction per distinct input column/expr per batch (p50 +
        # p99 over the same column share it)
        ck = (("col", a.input.name) if isinstance(a.input, Col)
              else ("expr", id(a.input)))
        hit = self._input_cache.get(ck)
        if hit is not None:
            return hit
        if isinstance(a.input, Col):
            name = a.input.name
            raw = [r.get(name) for r in rows]
        else:
            raw = []
            for r in rows:
                try:
                    raw.append(eval_host(a.input, r))
                except (TypeError, KeyError):
                    raw.append(None)
        # one NULL rule for both engines: only int/float values count
        # (matching _agg_input's isinstance check on the per-record slow
        # path). A bare float64 asarray would silently coerce NUMERIC
        # STRINGS here while the slow path NULLs them — the same record
        # would then aggregate differently depending on lateness. The
        # dtype probe keeps the all-numeric common case vectorized: any
        # string/None/mixed value forces a non-numeric dtype and takes
        # the per-element rule.
        try:
            arr = np.asarray(raw)
        except (TypeError, ValueError):  # ragged sequences etc.
            arr = None
        if arr is not None and arr.dtype.kind in "fiub":
            vals = arr.astype(np.float64)
        else:
            vals = np.array(
                [float(v) if isinstance(v, (int, float)) else np.nan
                 for v in raw], np.float64)
        res = (vals, np.isfinite(vals))
        self._input_cache[ck] = res
        return res

    def _segment_accs(self, rows, order, starts, ends,
                      seg_of_row) -> dict[str, Any]:
        """Per-segment accumulators (same formats _acc_init/_acc_merge
        use), one vectorized reduction per aggregate."""
        nseg = len(starts)
        out: dict[str, Any] = {}
        seg_len = None
        self._input_cache: dict = {}
        for a in self.aggs:
            if a.kind == AggKind.COUNT_ALL:
                if seg_len is None:
                    seg_len = (ends - starts).astype(np.int64)
                out[a.out_name] = seg_len.tolist()
                continue
            vals, valid = self._agg_input_cols(a, rows, len(order))
            vs = vals[order]
            ok = valid[order]
            if a.kind == AggKind.COUNT:
                out[a.out_name] = np.add.reduceat(
                    ok.astype(np.int64), starts).tolist()
            elif a.kind == AggKind.SUM:
                out[a.out_name] = np.add.reduceat(
                    np.where(ok, vs, 0.0), starts).tolist()
            elif a.kind == AggKind.AVG:
                s = np.add.reduceat(np.where(ok, vs, 0.0), starts)
                c = np.add.reduceat(ok.astype(np.int64), starts)
                out[a.out_name] = list(zip(s.tolist(), c.tolist()))
            elif a.kind == AggKind.MIN:
                out[a.out_name] = np.minimum.reduceat(
                    np.where(ok, vs, np.inf), starts).tolist()
            elif a.kind == AggKind.MAX:
                out[a.out_name] = np.maximum.reduceat(
                    np.where(ok, vs, -np.inf), starts).tolist()
            elif a.kind == AggKind.APPROX_QUANTILE:
                hist = np.zeros((nseg, self.qcfg.n_bins), np.int64)
                b = quantile_bin_np(np.where(ok, vs, self.qcfg.min_value),
                                    self.qcfg)
                np.add.at(hist, (seg_of_row[ok], b[ok]), 1)
                out[a.out_name] = hist
            elif a.kind == AggKind.APPROX_COUNT_DISTINCT:
                regs = np.zeros((nseg, self.hll.m), np.int8)
                reg, rank = hll_update_np(
                    np.where(ok, vs, 0.0).astype(np.float32), self.hll)
                np.maximum.at(regs, (seg_of_row[ok], reg[ok]), rank[ok])
                out[a.out_name] = regs
            elif a.kind in (AggKind.TOPK, AggKind.TOPK_DISTINCT):
                from hstream_tpu.engine.lattice import agg_width

                k = agg_width(a)
                lst = []
                for j in range(nseg):
                    sv = vs[starts[j]:ends[j]][ok[starts[j]:ends[j]]]
                    if a.kind == AggKind.TOPK_DISTINCT:
                        sv = np.unique(sv)
                    sv = np.sort(sv)[::-1][:k]
                    lst.append([float(x) for x in sv])
                out[a.out_name] = lst
            else:
                raise SQLCodegenError(
                    f"session agg {a.kind} unsupported")
        return out

    def _merge_segment(self, key: tuple, t0: int, t1: int,
                       accs: dict[str, Any]) -> None:
        gap = self.window.gap_ms
        sess_list = self.sessions.setdefault(key, [])
        overl = [s for s in sess_list
                 if s.start - gap <= t1 and t0 <= s.end + gap]
        if not overl:
            # copy array accs: segment rows are views into batch-wide
            # reduction buffers and must not pin them in session state
            own = {k: (v.copy() if isinstance(v, np.ndarray) else v)
                   for k, v in accs.items()}
            sess_list.append(_Session(start=t0, end=t1, accs=own))
            sess_list.sort(key=lambda s: s.start)
            return
        m = overl[0]
        for s in overl[1:]:
            m.start = min(m.start, s.start)
            m.end = max(m.end, s.end)
            for a in self.aggs:
                m.accs[a.out_name] = _acc_merge(
                    a, m.accs[a.out_name], s.accs[a.out_name])
            sess_list.remove(s)
        m.start = min(m.start, t0)
        m.end = max(m.end, t1)
        for a in self.aggs:
            m.accs[a.out_name] = _acc_merge(
                a, m.accs[a.out_name], accs[a.out_name])

    def _ingest_row(self, row: Mapping[str, Any], ts: int) -> bool:
        """Exact per-record path (late-policy segments): returns True
        when the record landed in a session, False when dropped."""
        gap = self.window.gap_ms
        grace = self.window.grace_ms
        key = canon_key(tuple(row.get(c) for c in self.group_cols))
        sess_list = self.sessions.setdefault(key, [])
        overl = [s for s in sess_list
                 if s.start - gap <= ts <= s.end + gap]
        # Late-record policy (reference merge-on-overlap,
        # SessionWindowedStream.hs:84-118): drop only when the record
        # is past grace AND cannot merge into any still-open session.
        if (not overl and self.watermark >= 0
                and ts + gap + grace <= self.watermark):
            return False
        if overl:
            merged = overl[0]
            for s in overl[1:]:
                merged.end = max(merged.end, s.end)
                merged.start = min(merged.start, s.start)
                for a in self.aggs:
                    merged.accs[a.out_name] = _acc_merge(
                        a, merged.accs[a.out_name], s.accs[a.out_name])
                sess_list.remove(s)
            merged.start = min(merged.start, ts)
            merged.end = max(merged.end, ts)
            target = merged
        else:
            target = _Session(start=ts, end=ts, accs={
                a.out_name: _acc_init(a, self.hll, self.qcfg)
                for a in self.aggs})
            sess_list.append(target)
            sess_list.sort(key=lambda s: s.start)
        for a in self.aggs:
            target.accs[a.out_name] = self._acc_update(
                a, target.accs[a.out_name],
                self._agg_input(a, row))
        return True

    def close_due_sessions(self) -> list[dict[str, Any]]:
        # A session may only close once no acceptable future record can
        # still merge into it. Acceptable records have ts > wm-gap-grace
        # (the in-grace gate) and merge into s when ts <= s.end + gap, so
        # the session is safe to close when wm >= end + 2*gap + grace.
        # The reference never eagerly deletes session state
        # (SessionWindowedStream.hs:84-118); closing one gap-width later
        # preserves its merge-on-overlap semantics while still emitting.
        gap, grace = self.window.gap_ms, self.window.grace_ms
        pairs: list[tuple[tuple, _Session]] = []
        for key, sess_list in list(self.sessions.items()):
            due = [s for s in sess_list
                   if s.end + 2 * gap + grace <= self.watermark]
            for s in due:
                if not self.emit_changes:
                    pairs.append((key, s))
                sess_list.remove(s)
            if not sess_list:
                del self.sessions[key]
        return self._emit_rows_batch(pairs)

    def _emit_rows_batch(self, pairs: list) -> list[dict[str, Any]]:
        """Emit many sessions at once: sketch finalization (quantile
        cdf + DDSketch bin edge, HLL estimate) runs vectorized over the
        whole close set instead of ~10 numpy calls per row."""
        if not pairs:
            return []
        vec: dict[str, np.ndarray] = {}
        for a in self.aggs:
            if a.kind == AggKind.APPROX_QUANTILE:
                hist = np.stack([s.accs[a.out_name] for _, s in pairs])
                vec[a.out_name] = quantile_estimate_np(
                    hist, a.quantile or 0.5, self.qcfg)
            elif a.kind == AggKind.APPROX_COUNT_DISTINCT:
                regs = np.stack([s.accs[a.out_name] for _, s in pairs])
                vec[a.out_name] = np.rint(
                    hll_estimate_np(regs, self.hll)).astype(np.int64)
        rows = []
        for i, (key, s) in enumerate(pairs):
            overrides = {
                name: (float(v[i]) if v.dtype.kind == "f" else int(v[i]))
                for name, v in vec.items()}
            r = self._emit_row(key, s, overrides or None)
            if r is not None:
                rows.append(r)
        return rows

    def _finalize(self, agg: AggSpec, acc):
        if agg.kind == AggKind.AVG:
            return acc[0] / max(acc[1], 1)
        if agg.kind == AggKind.MIN:
            return 0.0 if acc == math.inf else acc
        if agg.kind == AggKind.MAX:
            return 0.0 if acc == -math.inf else acc
        if agg.kind == AggKind.APPROX_COUNT_DISTINCT:
            return int(np.rint(hll_estimate_np(acc, self.hll)))
        if agg.kind == AggKind.APPROX_QUANTILE:
            return float(quantile_estimate_np(acc, agg.quantile or 0.5,
                                              self.qcfg))
        if agg.kind in (AggKind.TOPK, AggKind.TOPK_DISTINCT):
            return list(acc)
        return acc

    def _emit_row(self, key: tuple, s: _Session,
                  overrides: dict[str, Any] | None = None
                  ) -> dict[str, Any] | None:
        """One emitted row. `overrides` carries pre-finalized aggregate
        values (the batched sketch finalization) so the close path and
        this path share the HAVING/projection/window-stamp tail."""
        row = dict(zip(self.group_cols, key))
        for a in self.aggs:
            if overrides is not None and a.out_name in overrides:
                row[a.out_name] = overrides[a.out_name]
            else:
                row[a.out_name] = self._finalize(a, s.accs[a.out_name])
        row["winStart"] = s.start
        row["winEnd"] = s.end + self.window.gap_ms
        if self.node.having is not None:
            try:
                if not eval_host(self.node.having, row):
                    return None
            except (TypeError, KeyError):
                return None
        if self.node.post_projections:
            proj = {}
            for name, expr in self.node.post_projections:
                proj[name] = eval_host(expr, row)
            for meta in ("winStart", "winEnd"):
                proj[meta] = row[meta]
            return proj
        return row

    def peek(self) -> list[dict[str, Any]]:
        rows = []
        for key, sess_list in self.sessions.items():
            for s in sess_list:
                r = self._emit_row(key, s)
                if r is not None:
                    rows.append(r)
        return rows
