"""Session-window aggregation.

Reference semantics (SessionWindowedStream.hs:84-118): a record at ts
belongs to session [ts, ts]; sessions of the same key merge when their
gap-extended intervals overlap (ts within `gap` of the session edge);
a session closes when the watermark passes end + gap + grace.

Merge-on-overlap LOOKS inherently sequential per key, but session merge
is an associative monoid fold over ts-ordered segments (Dataflow-model
session semantics), so the hot path now runs as lattice kernels
(engine.lattice "session lattice kernels"): open sessions live in a
device-resident arena sorted by (key code, t0), and each micro-batch is
ONE fused dispatch — sort (arena ∪ batch) by (code, ts) with a stable
`lax.sort`, segmented-scan the chain boundaries (gap > timeout ⇒ new
session), scatter each chain into a compacted arena slot with monoid
acc merges. The step fetches nothing; closed sessions come back through
the pow2-padded extract path (one dispatch + one fetch per close cycle)
and emit as a ColumnarEmit end-to-end.

The HOST path below is retained in full as the equivalence reference
(`use_device_sessions=False`): per-batch segmentation vectorized in
numpy, per-segment accumulators via reduceat, segment merges into
per-key Python session state. The device path keeps an exact host-side
interval MIRROR (code, t0, t1 — no accumulators) of the arena, updated
with the numpy twin of the kernel's sort+scan: the mirror decides
late-record drops (the order-dependent part of the reference
semantics), close cycles, capacity, and slot indices with zero device
syncs. The executor degrades per-executor to the host path — PR 8
style, counted in `device_fallbacks` — on kernel failure, on
pathological overlap chains (one session swallowing more than
`chain_merge_limit` open sessions in a batch), and never activates for
host-only aggregate configs (TOPK lists, EMIT CHANGES sessions).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Mapping, Sequence

import numpy as np

from hstream_tpu.common.columnar import ColumnarEmit, extend_rows
from hstream_tpu.common.errors import SQLCodegenError
from hstream_tpu.common.faultinject import FAULTS
from hstream_tpu.common.logger import get_logger
from hstream_tpu.common.tracing import kernel_family
from hstream_tpu.engine.executor import _READ_NONCE, QueryExecutor
from hstream_tpu.engine.expr import (
    columns_of,
    compile_device,
    encode_strings,
    eval_host,
    eval_host_vec,
)
from hstream_tpu.engine.plan import AggKind, AggregateNode, AggSpec
from hstream_tpu.engine.sketches import HLLConfig, QuantileConfig
from hstream_tpu.engine.types import (
    ColumnType,
    HostBatch,
    Schema,
    StringDictionary,
    canon_key,
    round_up_pow2,
)
from hstream_tpu.engine.window import SessionWindow

log = get_logger("session")

# sentinel return of the device ingest helpers: the executor degraded
# mid-plan (state already pulled back to host); the caller reruns the
# batch through the host path
_DEGRADED = object()


# ---- numpy sketch helpers (host-side finalize) -----------------------------

def hll_update_np(values: np.ndarray, cfg: HLLConfig):
    """(register idx, rank) per value — numpy mirror of
    sketches.hll_update_indices (same hash, same estimates merge)."""
    v = np.ascontiguousarray(values, dtype=np.float32)
    v = np.where(v == 0.0, np.float32(0.0), v)
    h = v.view(np.uint32).copy()
    h ^= h >> 16
    h = (h * np.uint32(0x85EBCA6B)) & np.uint32(0xFFFFFFFF)
    h ^= h >> 13
    h = (h * np.uint32(0xC2B2AE35)) & np.uint32(0xFFFFFFFF)
    h ^= h >> 16
    p = cfg.precision
    reg = (h >> (32 - p)).astype(np.int64)
    w = (h << p) & np.uint32(0xFFFFFFFF)
    # count leading zeros of remaining bits
    rank = np.zeros(len(v), dtype=np.int64)
    x = w.copy()
    for shift in (16, 8, 4, 2, 1):
        empty = (x >> (32 - shift)) == 0
        rank += np.where(empty, shift, 0)
        x = np.where(empty, (x << shift) & np.uint32(0xFFFFFFFF), x)
    rank = np.where(w == 0, 32, rank)
    rank = np.minimum(rank + 1, 32 - p + 1).astype(np.int8)
    return reg, rank


def hll_estimate_np(registers: np.ndarray, cfg: HLLConfig) -> np.ndarray:
    """HLL estimate over the last axis: accepts one register set [m] or
    a batch [..., m] (batched session closes finalize in one call)."""
    m = cfg.m
    if m == 16:
        alpha = 0.673
    elif m == 32:
        alpha = 0.697
    elif m == 64:
        alpha = 0.709
    else:
        alpha = 0.7213 / (1 + 1.079 / m)
    regs = np.asarray(registers).astype(np.float64)
    raw = alpha * m * m / np.sum(np.exp2(-regs), axis=-1)
    zeros = np.sum(np.asarray(registers) == 0, axis=-1)
    lin = m * np.log(m / np.maximum(zeros, 1))
    return np.where((raw <= 2.5 * m) & (zeros > 0), lin, raw)


def quantile_bin_np(values: np.ndarray, cfg: QuantileConfig) -> np.ndarray:
    v = np.maximum(values.astype(np.float64), 0.0)
    safe = np.maximum(v, cfg.min_value)
    b = np.floor(np.log(safe / cfg.min_value) / cfg.gamma_log).astype(
        np.int64) + 1
    b = np.clip(b, 1, cfg.n_bins - 1)
    return np.where(v < cfg.min_value, 0, b)


def quantile_estimate_np(hist: np.ndarray, q: float,
                         cfg: QuantileConfig) -> np.ndarray:
    """Quantile estimate over the last axis: one histogram [n_bins] or
    a batch [..., n_bins]. argmax(cdf >= target) is searchsorted-left
    with a batch axis."""
    cdf = np.cumsum(hist, axis=-1)
    total = cdf[..., -1]
    target = q * total
    idx = np.argmax(cdf >= target[..., None], axis=-1)
    idx = np.minimum(idx, cfg.n_bins - 1)
    log_lo = (idx - 1.0) * cfg.gamma_log
    est = cfg.min_value * np.exp(log_lo + 0.5 * cfg.gamma_log)
    return np.where((idx == 0) | (total == 0), 0.0, est)


# ---- interval chain merge (numpy twin of the device kernel) -----------------

def merge_chains_np(code: np.ndarray, t0: np.ndarray, t1: np.ndarray,
                    gap: int, n_first: int = 0
                    ) -> tuple[np.ndarray, np.ndarray, np.ndarray, int]:
    """Chain-merge intervals by gap-overlap: sort by (code, t0, t1),
    break a chain at a code change or where t0 exceeds the running max
    end + gap — the exact fixpoint of sequential merge-on-overlap
    (interval clustering is confluent: merging only grows intervals).
    This is the numpy twin of lattice._session_chain_slots, so the
    returned chains are, in order, exactly the device arena's slots.

    Returns (code, t0, t1) per chain plus the max number of the FIRST
    `n_first` input entries (the open-session mirror) landing in one
    chain — the pathological-overlap-chain detector."""
    n = len(code)
    if n == 0:
        e = np.empty(0, np.int64)
        return e, e.copy(), e.copy(), 0
    order = np.lexsort((t1, t0, code))
    c = code[order].astype(np.int64)
    a = t0[order].astype(np.int64)
    b = t1[order].astype(np.int64)
    newrun = np.empty(n, np.bool_)
    newrun[0] = True
    newrun[1:] = c[1:] != c[:-1]
    # segmented running max of end via one accumulate: offset each code
    # run into its own disjoint value band (span bounded by the int32
    # relative-time guard, codes < 2^22, so the product fits int64)
    base = int(b.min())
    span = int(b.max()) - base + int(gap) + 2
    runmax = np.maximum.accumulate(c * span + (b - base)) - c * span + base
    prev = np.empty(n, np.int64)
    prev[0] = base - gap - 1
    prev[1:] = runmax[:-1]
    brk = newrun | (a > prev + gap)
    starts = np.nonzero(brk)[0]
    mcode = c[starts]
    mt0 = a[starts]
    mt1 = np.maximum.reduceat(b, starts)
    fanin = 0
    if n_first:
        cid = np.cumsum(brk) - 1
        first = cid[order < n_first]
        if len(first):
            fanin = int(np.bincount(first).max())
    return mcode, mt0, mt1, fanin


# ---- session state ---------------------------------------------------------

@dataclass
class _Session:
    start: int
    end: int                      # last record ts
    accs: dict[str, Any] = field(default_factory=dict)


def _acc_init(agg: AggSpec, hll: HLLConfig, qcfg: QuantileConfig):
    if agg.kind in (AggKind.COUNT_ALL, AggKind.COUNT):
        return 0
    if agg.kind in (AggKind.SUM,):
        return 0.0
    if agg.kind == AggKind.AVG:
        return (0.0, 0)
    if agg.kind == AggKind.MIN:
        return math.inf
    if agg.kind == AggKind.MAX:
        return -math.inf
    if agg.kind == AggKind.APPROX_COUNT_DISTINCT:
        return np.zeros(hll.m, dtype=np.int8)
    if agg.kind == AggKind.APPROX_QUANTILE:
        return np.zeros(qcfg.n_bins, dtype=np.int64)
    if agg.kind in (AggKind.TOPK, AggKind.TOPK_DISTINCT):
        return []  # descending value list, trimmed to k
    raise SQLCodegenError(f"session agg {agg.kind} unsupported")


def _acc_merge(agg: AggSpec, a, b):
    if agg.kind in (AggKind.COUNT_ALL, AggKind.COUNT, AggKind.SUM):
        return a + b
    if agg.kind == AggKind.AVG:
        return (a[0] + b[0], a[1] + b[1])
    if agg.kind == AggKind.MIN:
        return min(a, b)
    if agg.kind == AggKind.MAX:
        return max(a, b)
    if agg.kind == AggKind.APPROX_COUNT_DISTINCT:
        return np.maximum(a, b)
    if agg.kind == AggKind.APPROX_QUANTILE:
        return a + b
    if agg.kind == AggKind.TOPK:
        from hstream_tpu.engine.lattice import agg_width

        return sorted(a + b, reverse=True)[: agg_width(agg)]
    if agg.kind == AggKind.TOPK_DISTINCT:
        from hstream_tpu.engine.lattice import agg_width

        return sorted(set(a) | set(b), reverse=True)[: agg_width(agg)]
    raise SQLCodegenError(f"session agg {agg.kind} unsupported")


class SessionExecutor:
    """Windowed-by-session grouped aggregation.

    API-compatible with QueryExecutor: process(rows, ts_ms) -> emitted
    rows; emitted rows carry winStart/winEnd = [session start,
    session end + gap) like the reference's session serde. The hot path
    runs on device (module docstring); the host merge engine below is
    the retained equivalence reference and degrade target."""

    # tasks.py columnar feed capability: process_columnar takes
    # (ts, named numpy columns, nulls) — the join's _plain_columns shape
    supports_columnar_sessions = True

    # aggregate kinds the device arena carries; TOPK value lists stay
    # host-only (no fixed-width monoid plane worth it for sessions)
    _DEVICE_AGG_KINDS = frozenset({
        AggKind.COUNT_ALL, AggKind.COUNT, AggKind.SUM, AggKind.AVG,
        AggKind.MIN, AggKind.MAX, AggKind.APPROX_COUNT_DISTINCT,
        AggKind.APPROX_QUANTILE,
    })

    REBASE_THRESHOLD = 1 << 30  # re-anchor epoch past this relative ms

    def __init__(self, node: AggregateNode, schema: Schema, *,
                 emit_changes: bool = False,
                 hll: HLLConfig = HLLConfig(),
                 qcfg: QuantileConfig = QuantileConfig(),
                 mesh=None, data_axis: str = "data",
                 key_axis: str = "key"):
        if not isinstance(node.window, SessionWindow):
            raise SQLCodegenError("SessionExecutor needs a SessionWindow")
        self.node = node
        self.schema = schema
        self.window: SessionWindow = node.window
        self.emit_changes = emit_changes
        self.hll = hll
        self.qcfg = qcfg
        self.group_cols = [g.name for g in node.group_keys]
        self.aggs = list(node.aggs)
        self.watermark: int = -1
        # key tuple -> list[_Session], kept sorted by start
        self.sessions: dict[tuple, list[_Session]] = {}
        self._filter = QueryExecutor._extract_filter(self)  # same chain walk
        # batch key-encoding caches (rebuildable; not snapshot state) —
        # in device mode the codes ARE the arena's sort keys, so the
        # cache bound compacts (order-preserving remap kernel) instead
        # of clearing
        self._code_of: dict[tuple, int] = {}   # canon key -> code
        self._code_rev: list[tuple] = []       # code -> canon key
        self._raw_memo: dict[Any, int] = {}    # raw value(s) -> code
        self._input_cache: dict = {}           # per-batch input columns
        # device session path (engine.lattice session kernels);
        # use_device_sessions=False pins the host reference engine
        self.use_device_sessions = True
        self._dev: dict | None = None
        self._device_refusal: str | None = None   # host-only config
        # a mesh whose key axis has >1 devices key-shards the session
        # arena (ShardedSessionLattice: chain merge is key-local, so
        # every device op stays embarrassingly per-shard); single-device
        # meshes keep the single-chip kernels
        self.mesh = mesh
        self.data_axis = data_axis
        self.key_axis = key_axis
        self.sharded_dispatches = 0
        # None = auto (backend-dependent); "record" | "segment" force a
        # kernel mode — see _plan_device
        self.device_session_mode: str | None = None
        # Deferred close decode (device mode): closing sessions keeps
        # the packed extract as a device value; drain_closed() fetches
        # every pending cycle in ONE stacked transfer per buffer shape
        # (the PR 5 deferred-close idiom — on a tunneled link each
        # fetch is a full round trip)
        self.defer_close_decode = False
        self._pending_closes: list[tuple] = []
        # one batch chain may merge at most this many OPEN sessions;
        # deeper chains are the pathological case the mirror detects
        # and routes to the host reference path (degrade, not die)
        self.chain_merge_limit = 32
        # device activations/dispatches that failed and degraded this
        # executor to the host path; the query task mirrors deltas into
        # the device_path_fallbacks counter
        self.device_fallbacks = 0
        self.epoch: int | None = None   # device relative-time anchor
        self._closed_wm: int = -1       # wm of the last close cycle
        # ingest-path dispatch accounting: the session device contract
        # is ONE step dispatch and ZERO fetches per micro-batch, plus
        # one extract dispatch + one fetch per close cycle — bench and
        # tests assert on these
        self.session_stats = {
            "batches": 0, "step_dispatches": 0, "close_cycles": 0,
            "close_dispatches": 0, "close_fetches": 0,
            "peek_dispatches": 0, "remap_dispatches": 0, "grows": 0,
        }
        # observability plane (ISSUE 13): per-family dispatch observer,
        # late-record drop count (both engines decide lateness on the
        # host mirror), and H2D/D2H byte totals — all host values the
        # owning task mirrors into /metrics
        self.dispatch_observer = None   # callable (family, seconds)
        self.late_drops = 0
        self.transfer_stats = {"h2d_bytes": 0, "d2h_bytes": 0}
        self.dicts: dict[str, StringDictionary] = {
            name: StringDictionary() for name, t in schema.fields
            if t == ColumnType.STRING
        }
        self._code_cols_cache: tuple[int, list[np.ndarray]] = (-1, [])
        # read-plane versioning (ISSUE 20): bumped at every mutation
        # entry point (ingest, close, engine migration) so equal
        # read_version() tuples guarantee identical peek() results.
        # Plain int — lock-free readers at worst miss spuriously.
        self.read_epoch = 0
        self._read_nonce = next(_READ_NONCE)

    # QueryExecutor._extract_filter reads self.node only.

    def _agg_input(self, agg: AggSpec, row: Mapping[str, Any]):
        if agg.input is None:
            return 1
        try:
            v = eval_host(agg.input, row)
        except (TypeError, KeyError):
            return None
        # non-numeric values are NULL, the same rule the vectorized
        # path's _agg_input_cols applies — lateness must not change
        # whether a malformed record is skipped or crashes the query
        if not isinstance(v, (int, float)):
            return None
        if isinstance(v, float) and not math.isfinite(v):
            return None
        return v

    def _acc_update(self, agg: AggSpec, acc, v):
        if agg.kind == AggKind.COUNT_ALL:
            return acc + 1
        if v is None:
            return acc
        if agg.kind == AggKind.COUNT:
            return acc + 1
        if agg.kind == AggKind.SUM:
            return acc + float(v)
        if agg.kind == AggKind.AVG:
            return (acc[0] + float(v), acc[1] + 1)
        if agg.kind == AggKind.MIN:
            return min(acc, float(v))
        if agg.kind == AggKind.MAX:
            return max(acc, float(v))
        if agg.kind == AggKind.APPROX_COUNT_DISTINCT:
            reg, rank = hll_update_np(np.asarray([float(v)]), self.hll)
            acc = acc.copy()
            acc[reg[0]] = max(acc[reg[0]], rank[0])
            return acc
        if agg.kind == AggKind.APPROX_QUANTILE:
            b = int(quantile_bin_np(np.asarray([float(v)]), self.qcfg)[0])
            acc = acc.copy()
            acc[b] += 1
            return acc
        if agg.kind in (AggKind.TOPK, AggKind.TOPK_DISTINCT):
            return _acc_merge(agg, acc, [float(v)])
        raise SQLCodegenError(f"session agg {agg.kind} unsupported")

    # ---- vectorized batch path ---------------------------------------------
    #
    # SURVEY §7's session plan, realized: per-batch segmentation is
    # numpy (lexsort by (key, ts) + gap-break detection), per-SEGMENT
    # accumulators come from reduceat / scattered histogram updates, and
    # only the few segments (<= touched keys x batch span / gap) walk
    # the host merge. Merging a whole segment is exact: within a segment
    # consecutive records are <= gap apart, so sequential per-record
    # processing would land them all in one session chain, and every
    # accumulator is a commutative monoid. Segments that might interact
    # with the late-record policy (any record at ts + gap + grace <= the
    # pre-batch watermark) take the per-record fallback, which preserves
    # the reference's record-at-a-time drop-vs-merge decisions
    # (SessionWindowedStream.hs:84-118).

    def process(self, rows: Sequence[Mapping[str, Any]],
                ts_ms: Sequence[int]) -> list[dict[str, Any]]:
        if not rows:
            return []
        self.read_epoch += 1
        if self._device_ready():
            out = self._process_rows_device(rows, ts_ms)
            if out is not _DEGRADED:
                return out
            # degraded mid-plan: device state was pulled back into
            # self.sessions untouched by this batch — fall through to
            # the host engine below
        gap = self.window.gap_ms
        grace = self.window.grace_ms
        touched: set[tuple] = set()
        ts_all = np.asarray(ts_ms, np.int64)
        new_wm = int(ts_all.max())
        ts = ts_all
        if self._filter is not None:
            keep = np.fromiter((self._row_passes(r) for r in rows),
                               np.bool_, len(rows))
            if not keep.all():
                idx = np.nonzero(keep)[0]
                rows = [rows[i] for i in idx.tolist()]
                ts = ts[idx]
        n = len(rows)
        if n:
            codes, key_rev = self._key_codes(rows)
            order = np.lexsort((ts, codes))
            ks = codes[order]
            tss = ts[order]
            brk = np.empty(n, np.bool_)
            brk[0] = True
            brk[1:] = (ks[1:] != ks[:-1]) | ((tss[1:] - tss[:-1]) > gap)
            starts = np.nonzero(brk)[0]
            ends = np.append(starts[1:], n)
            seg_t0 = tss[starts]
            seg_t1 = tss[ends - 1]
            nseg = len(starts)
            wm = self.watermark
            # any record possibly subject to the late policy -> per-row
            slow = (seg_t0 + gap + grace <= wm if wm >= 0
                    else np.zeros(nseg, np.bool_))
            seg_of_row = np.cumsum(brk) - 1
            accs_cols = self._segment_accs(rows, order, starts, ends,
                                           seg_of_row)
            seg_keys = ks[starts]
            for j in range(nseg):
                key = key_rev[int(seg_keys[j])]
                if slow[j]:
                    for i in order[starts[j]:ends[j]].tolist():
                        if self._ingest_row(rows[i], int(ts[i])):
                            touched.add(key)
                    continue
                accs = {a.out_name: accs_cols[a.out_name][j]
                        for a in self.aggs}
                self._merge_segment(key, int(seg_t0[j]), int(seg_t1[j]),
                                    accs)
                touched.add(key)
        if new_wm > self.watermark:
            self.watermark = new_wm

        out = None
        if self.emit_changes:
            pairs = [(key, s) for key in touched
                     for s in self.sessions.get(key, [])]
            out = extend_rows(out, self._emit_cols_batch(pairs))
        # a lone columnar batch (changes or closes) stays columnar all
        # the way to the caller (extend_rows, the PR 5 drain threading)
        out = extend_rows(out, self.close_due_sessions())
        return out if out is not None else []

    def _row_passes(self, row: Mapping[str, Any]) -> bool:
        try:
            return bool(eval_host(self._filter, row))
        except (TypeError, KeyError):
            return False

    # key-encoding cache bound: codes only matter WITHIN one batch, so
    # the caches are safe to drop wholesale; bounding them keeps a
    # months-long high-cardinality query (session per request_id) from
    # growing without limit after its sessions closed
    _KEY_CACHE_MAX = 1 << 18

    def _bound_key_cache(self) -> None:
        """Cache-bound enforcement: host mode drops the caches wholesale
        (codes only matter within one batch there); device mode must
        keep codes of keys with LIVE arena sessions stable, so it
        compacts through the order-preserving remap kernel instead."""
        if len(self._code_of) <= self._KEY_CACHE_MAX:
            return
        if self._dev is not None:
            self._compact_codes_device()
        else:
            self._code_of = {}
            self._code_rev = []
            self._raw_memo = {}
            self._code_cols_cache = (-1, [])

    def _key_codes(self, rows) -> tuple[np.ndarray, list]:
        """Dense int codes per row's group key. Codes persist across
        batches (encoding cache only — not part of snapshot state);
        raw-value memoization keeps the per-row cost to one dict hit."""
        self._bound_key_cache()
        out = np.empty(len(rows), np.int64)
        rev = self._code_rev
        if len(self.group_cols) == 1:
            c = self.group_cols[0]
            memo = self._raw_memo
            for i, r in enumerate(rows):
                v = r.get(c)
                code = memo.get(v)
                if code is None:
                    k = canon_key((v,))
                    code = self._code_of.get(k)
                    if code is None:
                        code = len(rev)
                        self._code_of[k] = code
                        rev.append(k)
                    memo[v] = code
                out[i] = code
        else:
            cols = self.group_cols
            memo = self._raw_memo
            for i, r in enumerate(rows):
                raw = tuple(r.get(c) for c in cols)
                code = memo.get(raw)
                if code is None:
                    k = canon_key(raw)
                    code = self._code_of.get(k)
                    if code is None:
                        code = len(rev)
                        self._code_of[k] = code
                        rev.append(k)
                    memo[raw] = code
                out[i] = code
        return out, rev

    def _agg_input_cols(self, a: AggSpec, rows,
                        n: int) -> tuple[np.ndarray, np.ndarray]:
        """(values f64[n], valid bool[n]) for one aggregate's input.
        Invalid = missing / None / non-numeric / non-finite (the same
        records _agg_input returns None for)."""
        from hstream_tpu.engine.expr import Col

        if a.input is None:  # _agg_input's constant-1 case
            return np.ones(n, np.float64), np.ones(n, np.bool_)
        # one extraction per distinct input column/expr per batch (p50 +
        # p99 over the same column share it)
        ck = (("col", a.input.name) if isinstance(a.input, Col)
              else ("expr", id(a.input)))
        hit = self._input_cache.get(ck)
        if hit is not None:
            return hit
        if isinstance(a.input, Col):
            name = a.input.name
            raw = [r.get(name) for r in rows]
        else:
            raw = []
            for r in rows:
                try:
                    raw.append(eval_host(a.input, r))
                except (TypeError, KeyError):
                    raw.append(None)
        # one NULL rule for both engines: only int/float values count
        # (matching _agg_input's isinstance check on the per-record slow
        # path). A bare float64 asarray would silently coerce NUMERIC
        # STRINGS here while the slow path NULLs them — the same record
        # would then aggregate differently depending on lateness. The
        # dtype probe keeps the all-numeric common case vectorized: any
        # string/None/mixed value forces a non-numeric dtype and takes
        # the per-element rule.
        try:
            arr = np.asarray(raw)
        except (TypeError, ValueError):  # ragged sequences etc.
            arr = None
        if arr is not None and arr.dtype.kind in "fiub":
            vals = arr.astype(np.float64)
        else:
            vals = np.array(
                [float(v) if isinstance(v, (int, float)) else np.nan
                 for v in raw], np.float64)
        res = (vals, np.isfinite(vals))
        self._input_cache[ck] = res
        return res

    def _segment_accs(self, rows, order, starts, ends,
                      seg_of_row) -> dict[str, Any]:
        """Per-segment accumulators (same formats _acc_init/_acc_merge
        use), one vectorized reduction per aggregate."""
        nseg = len(starts)
        out: dict[str, Any] = {}
        seg_len = None
        self._input_cache: dict = {}
        for a in self.aggs:
            if a.kind == AggKind.COUNT_ALL:
                if seg_len is None:
                    seg_len = (ends - starts).astype(np.int64)
                out[a.out_name] = seg_len.tolist()
                continue
            vals, valid = self._agg_input_cols(a, rows, len(order))
            vs = vals[order]
            ok = valid[order]
            if a.kind == AggKind.COUNT:
                out[a.out_name] = np.add.reduceat(
                    ok.astype(np.int64), starts).tolist()
            elif a.kind == AggKind.SUM:
                out[a.out_name] = np.add.reduceat(
                    np.where(ok, vs, 0.0), starts).tolist()
            elif a.kind == AggKind.AVG:
                s = np.add.reduceat(np.where(ok, vs, 0.0), starts)
                c = np.add.reduceat(ok.astype(np.int64), starts)
                out[a.out_name] = list(zip(s.tolist(), c.tolist()))
            elif a.kind == AggKind.MIN:
                out[a.out_name] = np.minimum.reduceat(
                    np.where(ok, vs, np.inf), starts).tolist()
            elif a.kind == AggKind.MAX:
                out[a.out_name] = np.maximum.reduceat(
                    np.where(ok, vs, -np.inf), starts).tolist()
            elif a.kind == AggKind.APPROX_QUANTILE:
                hist = np.zeros((nseg, self.qcfg.n_bins), np.int64)
                b = quantile_bin_np(np.where(ok, vs, self.qcfg.min_value),
                                    self.qcfg)
                np.add.at(hist, (seg_of_row[ok], b[ok]), 1)
                out[a.out_name] = hist
            elif a.kind == AggKind.APPROX_COUNT_DISTINCT:
                regs = np.zeros((nseg, self.hll.m), np.int8)
                reg, rank = hll_update_np(
                    np.where(ok, vs, 0.0).astype(np.float32), self.hll)
                np.maximum.at(regs, (seg_of_row[ok], reg[ok]), rank[ok])
                out[a.out_name] = regs
            elif a.kind in (AggKind.TOPK, AggKind.TOPK_DISTINCT):
                from hstream_tpu.engine.lattice import agg_width

                k = agg_width(a)
                lst = []
                for j in range(nseg):
                    sv = vs[starts[j]:ends[j]][ok[starts[j]:ends[j]]]
                    if a.kind == AggKind.TOPK_DISTINCT:
                        sv = np.unique(sv)
                    sv = np.sort(sv)[::-1][:k]
                    lst.append([float(x) for x in sv])
                out[a.out_name] = lst
            else:
                raise SQLCodegenError(
                    f"session agg {a.kind} unsupported")
        return out

    def _merge_segment(self, key: tuple, t0: int, t1: int,
                       accs: dict[str, Any]) -> None:
        gap = self.window.gap_ms
        sess_list = self.sessions.setdefault(key, [])
        overl = [s for s in sess_list
                 if s.start - gap <= t1 and t0 <= s.end + gap]
        if not overl:
            # copy array accs: segment rows are views into batch-wide
            # reduction buffers and must not pin them in session state
            own = {k: (v.copy() if isinstance(v, np.ndarray) else v)
                   for k, v in accs.items()}
            sess_list.append(_Session(start=t0, end=t1, accs=own))
            sess_list.sort(key=lambda s: s.start)
            return
        m = overl[0]
        for s in overl[1:]:
            m.start = min(m.start, s.start)
            m.end = max(m.end, s.end)
            for a in self.aggs:
                m.accs[a.out_name] = _acc_merge(
                    a, m.accs[a.out_name], s.accs[a.out_name])
            sess_list.remove(s)
        m.start = min(m.start, t0)
        m.end = max(m.end, t1)
        for a in self.aggs:
            m.accs[a.out_name] = _acc_merge(
                a, m.accs[a.out_name], accs[a.out_name])

    def _ingest_row(self, row: Mapping[str, Any], ts: int) -> bool:
        """Exact per-record path (late-policy segments): returns True
        when the record landed in a session, False when dropped."""
        gap = self.window.gap_ms
        grace = self.window.grace_ms
        key = canon_key(tuple(row.get(c) for c in self.group_cols))
        sess_list = self.sessions.setdefault(key, [])
        overl = [s for s in sess_list
                 if s.start - gap <= ts <= s.end + gap]
        # Late-record policy (reference merge-on-overlap,
        # SessionWindowedStream.hs:84-118): drop only when the record
        # is past grace AND cannot merge into any still-open session.
        if (not overl and self.watermark >= 0
                and ts + gap + grace <= self.watermark):
            self.late_drops += 1
            return False
        if overl:
            merged = overl[0]
            for s in overl[1:]:
                merged.end = max(merged.end, s.end)
                merged.start = min(merged.start, s.start)
                for a in self.aggs:
                    merged.accs[a.out_name] = _acc_merge(
                        a, merged.accs[a.out_name], s.accs[a.out_name])
                sess_list.remove(s)
            merged.start = min(merged.start, ts)
            merged.end = max(merged.end, ts)
            target = merged
        else:
            target = _Session(start=ts, end=ts, accs={
                a.out_name: _acc_init(a, self.hll, self.qcfg)
                for a in self.aggs})
            sess_list.append(target)
            sess_list.sort(key=lambda s: s.start)
        for a in self.aggs:
            target.accs[a.out_name] = self._acc_update(
                a, target.accs[a.out_name],
                self._agg_input(a, row))
        return True

    def close_due_sessions(self) -> list[dict[str, Any]]:
        # A session may only close once no acceptable future record can
        # still merge into it. Acceptable records have ts > wm-gap-grace
        # (the in-grace gate) and merge into s when ts <= s.end + gap, so
        # the session is safe to close when wm >= end + 2*gap + grace.
        # The reference never eagerly deletes session state
        # (SessionWindowedStream.hs:84-118); closing one gap-width later
        # preserves its merge-on-overlap semantics while still emitting.
        self.read_epoch += 1
        if self._dev is not None:
            return self._close_due_device()
        gap, grace = self.window.gap_ms, self.window.grace_ms
        pairs: list[tuple[tuple, _Session]] = []
        for key, sess_list in list(self.sessions.items()):
            due = [s for s in sess_list
                   if s.end + 2 * gap + grace <= self.watermark]
            for s in due:
                if not self.emit_changes:
                    pairs.append((key, s))
                sess_list.remove(s)
            if not sess_list:
                del self.sessions[key]
        return self._emit_cols_batch(pairs)

    def _emit_cols_batch(self, pairs: list
                         ) -> "ColumnarEmit | list[dict[str, Any]]":
        """Columnar emission of many host sessions at once: every
        aggregate finalizes as one vectorized column (sketch estimates
        batched over the whole set), HAVING/projections evaluate
        columnwise, and the result stays a ColumnarEmit until the wire —
        sessions were the last emitter materializing per-row dicts.
        The per-row reference is _emit_row (equivalence tests and the
        host-only-op fallback)."""
        if not pairs:
            return []
        n = len(pairs)
        cols: dict[str, Any] = {}
        for gi, name in enumerate(self.group_cols):
            arr = np.empty(n, object)
            arr[:] = [key[gi] for key, _ in pairs]
            cols[name] = arr
        for a in self.aggs:
            accs = [s.accs[a.out_name] for _, s in pairs]
            if a.kind in (AggKind.COUNT_ALL, AggKind.COUNT):
                cols[a.out_name] = np.asarray(accs, np.int64)
            elif a.kind == AggKind.SUM:
                cols[a.out_name] = np.asarray(accs, np.float64)
            elif a.kind == AggKind.AVG:
                s_ = np.asarray([x[0] for x in accs], np.float64)
                c_ = np.asarray([x[1] for x in accs], np.int64)
                cols[a.out_name] = s_ / np.maximum(c_, 1)
            elif a.kind == AggKind.MIN:
                v = np.asarray(accs, np.float64)
                cols[a.out_name] = np.where(v == np.inf, 0.0, v)
            elif a.kind == AggKind.MAX:
                v = np.asarray(accs, np.float64)
                cols[a.out_name] = np.where(v == -np.inf, 0.0, v)
            elif a.kind == AggKind.APPROX_COUNT_DISTINCT:
                regs = np.stack(accs)
                cols[a.out_name] = np.rint(
                    hll_estimate_np(regs, self.hll)).astype(np.int64)
            elif a.kind == AggKind.APPROX_QUANTILE:
                hist = np.stack(accs)
                cols[a.out_name] = quantile_estimate_np(
                    hist, a.quantile or 0.5, self.qcfg).astype(np.float64)
            elif a.kind in (AggKind.TOPK, AggKind.TOPK_DISTINCT):
                arr = np.empty(n, object)
                arr[:] = [list(acc) for acc in accs]
                cols[a.out_name] = arr
            else:
                raise SQLCodegenError(f"session agg {a.kind} unsupported")
        cols["winStart"] = np.asarray([s.start for _, s in pairs],
                                      np.int64)
        cols["winEnd"] = np.asarray(
            [s.end + self.window.gap_ms for _, s in pairs], np.int64)
        return self._postprocess_session_cols(cols, n)

    def _postprocess_session_cols(self, cols: dict[str, Any], n: int
                                  ) -> "ColumnarEmit | list[dict[str, Any]]":
        """HAVING + SELECT projections over a columnar session batch;
        any host-only op (or NULL-driven eval error) falls back to the
        per-row path whose drop semantics match _emit_row exactly."""
        if self.node.having is not None:
            try:
                keep = np.broadcast_to(
                    np.asarray(eval_host_vec(self.node.having, cols),
                               np.bool_), (n,))
            except Exception:  # noqa: BLE001 — host-only op / NULLs
                return self._postprocess_session_rows(
                    ColumnarEmit(cols, n))
            if not keep.all():
                cols = {k: np.asarray(v)[keep] for k, v in cols.items()}
                n = int(keep.sum())
                if n == 0:
                    return []
        if self.node.post_projections:
            try:
                projected: dict[str, Any] = {}
                for name, expr in self.node.post_projections:
                    v = eval_host_vec(expr, cols)
                    projected[name] = np.broadcast_to(
                        np.asarray(v), (n,)) if np.ndim(v) == 0 \
                        else np.asarray(v)
                for meta in ("winStart", "winEnd"):
                    projected[meta] = cols[meta]
                cols = projected
            except Exception:  # noqa: BLE001
                return self._postprocess_session_rows(
                    ColumnarEmit(cols, n))
        return ColumnarEmit(cols, n)

    def _postprocess_session_rows(self, rows) -> list[dict[str, Any]]:
        """Per-row HAVING/projection fallback — the same drop rules as
        _emit_row (a HAVING eval error drops the row; projection errors
        propagate, as they always did)."""
        out = []
        for row in rows:
            if self.node.having is not None:
                try:
                    if not eval_host(self.node.having, row):
                        continue
                except (TypeError, KeyError):
                    continue
            if self.node.post_projections:
                proj = {}
                for name, expr in self.node.post_projections:
                    proj[name] = eval_host(expr, row)
                for meta in ("winStart", "winEnd"):
                    proj[meta] = row[meta]
                out.append(proj)
            else:
                out.append(row)
        return out

    def _finalize(self, agg: AggSpec, acc):
        if agg.kind == AggKind.AVG:
            return acc[0] / max(acc[1], 1)
        if agg.kind == AggKind.MIN:
            return 0.0 if acc == math.inf else acc
        if agg.kind == AggKind.MAX:
            return 0.0 if acc == -math.inf else acc
        if agg.kind == AggKind.APPROX_COUNT_DISTINCT:
            return int(np.rint(hll_estimate_np(acc, self.hll)))
        if agg.kind == AggKind.APPROX_QUANTILE:
            return float(quantile_estimate_np(acc, agg.quantile or 0.5,
                                              self.qcfg))
        if agg.kind in (AggKind.TOPK, AggKind.TOPK_DISTINCT):
            return list(acc)
        return acc

    def _emit_row(self, key: tuple, s: _Session,
                  overrides: dict[str, Any] | None = None
                  ) -> dict[str, Any] | None:
        """One emitted row. `overrides` carries pre-finalized aggregate
        values (the batched sketch finalization) so the close path and
        this path share the HAVING/projection/window-stamp tail."""
        row = dict(zip(self.group_cols, key))
        for a in self.aggs:
            if overrides is not None and a.out_name in overrides:
                row[a.out_name] = overrides[a.out_name]
            else:
                row[a.out_name] = self._finalize(a, s.accs[a.out_name])
        row["winStart"] = s.start
        row["winEnd"] = s.end + self.window.gap_ms
        if self.node.having is not None:
            try:
                if not eval_host(self.node.having, row):
                    return None
            except (TypeError, KeyError):
                return None
        if self.node.post_projections:
            proj = {}
            for name, expr in self.node.post_projections:
                proj[name] = eval_host(expr, row)
            for meta in ("winStart", "winEnd"):
                proj[meta] = row[meta]
            return proj
        return row

    def peek(self) -> list[dict[str, Any]]:
        """Open-session rows (pull queries / view peeks), columnar on
        both engines: ONE read-only extract dispatch + ONE fetch covers
        every open session on device; the host engine finalizes every
        session as one vectorized column batch."""
        if self._dev is not None:
            return self._peek_device()
        pairs = [(key, s) for key, sess_list in self.sessions.items()
                 for s in sess_list]
        return self._emit_cols_batch(pairs)

    # contract: dispatches<=0 fetches<=0
    def read_version(self) -> tuple:
        """Exact version of the peek-visible session set (the read
        cache's validity key — ISSUE 20): equal tuples guarantee peek()
        would return the same rows. Host ints only, lock-free safe."""
        return ("sess", self._read_nonce, self.read_epoch,
                self.session_stats["close_cycles"], self.watermark)

    # contract: dispatches<=0 fetches<=0
    def live_min_win_end(self) -> int | None:
        """Smallest winEnd any open session could emit (session winEnd
        is end + gap), or None when no session is open — read off the
        host dict or the device interval mirror, never the arena
        (ISSUE 20: closed-only readers skip peek() entirely)."""
        gap = self.window.gap_ms
        if self._dev is not None:
            dev = self._dev
            live = dev["mir_live"]
            if not live.any():
                return None
            return int(dev["mir_t1"][live].min()) + gap
        ends = [s.end for sess_list in self.sessions.values()
                for s in sess_list]
        if not ends:
            return None
        return min(ends) + gap

    # ---- device session path (engine.lattice session kernels) --------------
    #
    # Open sessions live in a device arena sorted by (code, t0); each
    # micro-batch is ONE fused sort + segmented-scan merge dispatch and
    # ZERO fetches; close cycles and peeks are one pow2-padded extract
    # dispatch + one fetch each. The host keeps an exact interval
    # mirror (merge_chains_np — the numpy twin of the kernel's scan)
    # that decides late-record drops, close sets, capacity, and slot
    # indices with no device sync. The host engine above is the
    # equivalence reference and the degrade target (PR 8 pattern).

    def _device_ready(self) -> bool:
        if self._dev is not None:
            return True
        if not self.use_device_sessions \
                or self._device_refusal is not None:
            return False
        plan = self._plan_device()
        if plan is None:
            return False  # host-only config: a refusal, not a failure
        try:
            if FAULTS.active:  # chaos: provoke an activation failure
                FAULTS.point("device.session.activate")
            self._activate_device(plan)
            return True
        except Exception as e:  # noqa: BLE001 — an activation failure
            # (kernel build, migration, device OOM, injected fault)
            # degrades to the retained host reference path instead of
            # killing the query; results are identical, only slower
            log.warning(
                "device session activation failed (%s: %s); staying on "
                "the host reference path", type(e).__name__, e)
            self._dev = None
            self.use_device_sessions = False
            self.device_fallbacks += 1
            return False

    def _plan_device(self) -> dict | None:
        """Static plan for the device path, or None (with the refusal
        recorded) for host-only configs: EMIT CHANGES sessions (emit
        per touched key, a per-batch extract the host path serves
        better), TOPK list aggregates, and — in record mode — aggregate
        inputs the device expression compiler cannot express.

        Mode selection: "record" packs raw records and runs the fully
        fused sort+scan+scatter step — the wire-frugal shape for real
        accelerators where per-record scatters are cheap and H2D bytes
        are not. "segment" pre-reduces rows into per-segment plane
        contributions on the host (the reference path's vectorized
        reduceat/add.at) and merges arenas on device — the shape for
        the CPU backend, where XLA per-record scatters lose to numpy's
        vectorized reduction. `device_session_mode` overrides."""
        import jax

        from hstream_tpu.engine import lattice

        if self.emit_changes:
            self._device_refusal = "EMIT CHANGES sessions emit per " \
                "touched key; host path retained"
            return None
        if 2 * self.window.gap_ms + self.window.grace_ms >= (1 << 30):
            # the close rule (t1 + 2*gap + grace) must fit the int32
            # relative-time budget alongside the span bound
            self._device_refusal = "gap/grace span exceeds the device " \
                "relative-time range; host path retained"
            return None
        for a in self.aggs:
            if a.kind not in self._DEVICE_AGG_KINDS:
                self._device_refusal = \
                    f"aggregate {a.kind.value} is host-only"
                return None
        mode = self.device_session_mode or (
            "segment" if jax.default_backend() == "cpu" else "record")
        try:
            encoded = []
            for a in self.aggs:
                if a.input is not None:
                    a = AggSpec(kind=a.kind, out_name=a.out_name,
                                input=encode_strings(a.input, self.schema,
                                                     self.dicts),
                                quantile=a.quantile, k=a.k)
                encoded.append(a)
            needed: set[str] = set()
            for a in encoded:
                if a.input is not None:
                    needed |= columns_of(a.input)
                    if mode == "record":
                        compile_device(a.input, self.schema)  # may raise
            layout = tuple(
                (name, lattice.layout_tag(self.schema.type_of(name)))
                for name in sorted(needed))
        except Exception as e:  # noqa: BLE001 — host-only expression
            self._device_refusal = f"device compile refused: {e}"
            return None
        spec = lattice.SessionSpec(aggs=tuple(encoded), hll=self.hll,
                                   qcfg=self.qcfg)
        null_refs = [sorted(columns_of(a.input)) for a in encoded
                     if a.input is not None]
        return {"spec": spec, "layout": layout, "null_refs": null_refs,
                "mode": mode}

    def _activate_device(self, plan: dict) -> None:
        """Migrate the host session state into a fresh device arena
        (sorted by (code, t0)) and build the interval mirror. The host
        dict is cleared only after every plane uploaded — a failure
        partway leaves the reference path intact to fall back on."""
        import jax

        from hstream_tpu.engine import lattice

        spec = plan["spec"]
        entries: list[tuple[int, _Session]] = []
        for key, sess_list in self.sessions.items():
            code = self._code_of.get(key)
            if code is None:
                code = len(self._code_rev)
                self._code_of[key] = code
                self._code_rev.append(key)
            for s in sess_list:
                entries.append((code, s))
        n = len(entries)
        cap = round_up_pow2(2 * max(n, 1), lo=256)
        mir_code = np.empty(n, np.int64)
        mir_t0 = np.empty(n, np.int64)
        mir_t1 = np.empty(n, np.int64)
        for i, (code, s) in enumerate(entries):
            mir_code[i] = code
            mir_t0[i] = s.start
            mir_t1[i] = s.end
        order = np.lexsort((mir_t1, mir_t0, mir_code))
        mir_code, mir_t0, mir_t1 = (mir_code[order], mir_t0[order],
                                    mir_t1[order])
        epoch = int(mir_t0.min()) if n else None
        ssl = None
        if (self.mesh is not None
                and self.key_axis in self.mesh.axis_names
                and self.mesh.shape[self.key_axis] > 1):
            from hstream_tpu.parallel.lattice import \
                ShardedSessionLattice
            ssl = ShardedSessionLattice(self.mesh, self.key_axis, spec,
                                        self.schema, plan["layout"])
        arena_np = lattice.session_plane_np(spec, cap)
        if ssl is not None:
            # per-shard planes: each key shard holds its residue class
            # (code % n_shards) in mirror order. The per-shard cap keeps
            # the single-chip formula — memory spent on skew tolerance.
            arena_np = {k: np.broadcast_to(
                v[None], (ssl.n_shards,) + v.shape).copy()
                for k, v in arena_np.items()}
            cls = (mir_code % ssl.n_shards).astype(np.int64)
            sl = np.empty(n, np.int64)
            for s in range(ssl.n_shards):
                m = cls == s
                sl[m] = np.arange(int(m.sum()))

        def dst(j):
            return (cls[j], sl[j]) if ssl is not None else j

        if n:
            if ssl is not None:
                arena_np["code"][cls, sl] = mir_code.astype(np.int32)
                arena_np["t0"][cls, sl] = (mir_t0 - epoch).astype(
                    np.int32)
                arena_np["t1"][cls, sl] = (mir_t1 - epoch).astype(
                    np.int32)
            else:
                arena_np["code"][:n] = mir_code.astype(np.int32)
                arena_np["t0"][:n] = (mir_t0 - epoch).astype(np.int32)
                arena_np["t1"][:n] = (mir_t1 - epoch).astype(np.int32)
            for name, a in zip(lattice.session_plane_names(spec),
                               spec.aggs):
                for j, (_code, s) in enumerate(
                        (entries[o] for o in order.tolist())):
                    acc = s.accs[a.out_name]
                    if a.kind == AggKind.AVG:
                        arena_np[name][dst(j)] = np.float32(acc[0])
                        arena_np[name + "_n"][dst(j)] = acc[1]
                    elif a.kind == AggKind.APPROX_COUNT_DISTINCT:
                        arena_np[name][dst(j)] = acc
                    elif a.kind == AggKind.APPROX_QUANTILE:
                        if int(np.max(acc, initial=0)) >= (1 << 31):
                            raise SQLCodegenError(
                                "session histogram count exceeds int32 "
                                "at device activation")
                        arena_np[name][dst(j)] = acc.astype(np.int32)
                    else:
                        arena_np[name][dst(j)] = np.float32(acc) \
                            if arena_np[name].dtype == np.float32 else acc
        self._dev = {
            "spec": spec,
            "layout": plan["layout"],
            "null_refs": plan["null_refs"],
            "mode": plan["mode"],
            "cap": cap,
            "ssl": ssl,
            "arena": (ssl.put_arena(arena_np) if ssl is not None else
                      {k: jax.device_put(v)
                       for k, v in arena_np.items()}),
            "mir_code": mir_code,
            "mir_t0": mir_t0,
            "mir_t1": mir_t1,
            "mir_live": np.ones(n, np.bool_),
            "bcaps": set(),
            "scaps": set(),
        }
        self.epoch = epoch
        self.sessions = {}
        self.read_epoch += 1

    def _degrade_to_host(self, reason: str) -> None:
        """Pull the device state back into the host session dict and pin
        this executor to the reference engine — identical results, only
        slower (counted in device_fallbacks, mirrored into the
        device_path_fallbacks counter by the query task)."""
        log.warning("device session path degrading to host: %s", reason)
        # deferred closes decode lazily through _code_rev; the host-mode
        # cache bound may rebuild that dictionary, so resolve their key
        # columns against the CURRENT one now (same rule as the
        # code-space compaction)
        self._pending_closes = [
            (codes, t0, t1, packed,
             keys if keys is not None else
             [arr[codes.astype(np.int64)]
              for arr in self._code_rev_columns()])
            for codes, t0, t1, packed, keys in self._pending_closes]
        self.sessions = self._host_sessions_view()
        self._dev = None
        self.use_device_sessions = False
        self.device_fallbacks += 1
        self.read_epoch += 1

    # contract: dispatches<=0 fetches<=1
    def _host_sessions_view(self) -> dict[tuple, list[_Session]]:
        """Host-format view of the device arena (snapshot serialization
        and the degrade path): ONE pytree fetch, then per-live-slot acc
        decode into the reference accumulator formats."""
        import jax

        dev = self._dev
        host = jax.device_get(dev["arena"])
        if dev.get("ssl") is not None:
            # gather every mirror row's value out of its shard's plane:
            # the flattened view indexes by mirror row, exactly like the
            # single-chip arena below
            cls, sl = self._shard_slots()
            host = {k: v[cls, sl] for k, v in host.items()}
        spec = dev["spec"]
        sessions: dict[tuple, list[_Session]] = {}
        from hstream_tpu.engine import lattice

        for slot in np.nonzero(dev["mir_live"])[0].tolist():
            key = self._code_rev[int(dev["mir_code"][slot])]
            accs: dict[str, Any] = {}
            for name, a in zip(lattice.session_plane_names(spec),
                               spec.aggs):
                v = host[name][slot]
                if a.kind in (AggKind.COUNT_ALL, AggKind.COUNT):
                    accs[a.out_name] = int(v)
                elif a.kind == AggKind.SUM:
                    accs[a.out_name] = float(v)
                elif a.kind == AggKind.AVG:
                    accs[a.out_name] = (float(v),
                                        int(host[name + "_n"][slot]))
                elif a.kind in (AggKind.MIN, AggKind.MAX):
                    accs[a.out_name] = float(v)
                elif a.kind == AggKind.APPROX_COUNT_DISTINCT:
                    accs[a.out_name] = np.asarray(v, np.int8).copy()
                elif a.kind == AggKind.APPROX_QUANTILE:
                    accs[a.out_name] = np.asarray(v, np.int64).copy()
            sessions.setdefault(key, []).append(_Session(
                start=int(dev["mir_t0"][slot]),
                end=int(dev["mir_t1"][slot]), accs=accs))
        return sessions

    def _shard_slots(self) -> tuple[np.ndarray, np.ndarray]:
        """(key shard, per-shard arena slot) of every mirror row: each
        shard's arena holds exactly its residue class's chains in mirror
        order, so a row's slot is its rank within its class. Dead rows
        (mir_live False) still occupy arena slots until the next step
        dispatch retires them, so the ranks run over ALL rows."""
        dev = self._dev
        ns = dev["ssl"].n_shards
        cls = (dev["mir_code"] % ns).astype(np.int64)
        slot = np.empty(len(cls), np.int64)
        for s in range(ns):
            m = cls == s
            slot[m] = np.arange(int(m.sum()))
        return cls, slot

    def _process_rows_device(self, rows, ts_ms):
        """Row-shaped ingest onto the device path: host filter eval,
        key-code encode, then either schema-typed columns (record mode)
        or per-aggregate value columns (segment mode)."""
        ts_all = np.asarray(ts_ms, np.int64)
        pre_max = int(ts_all.max())
        ts = ts_all
        if self._filter is not None:
            keepf = np.fromiter((self._row_passes(r) for r in rows),
                                np.bool_, len(rows))
            if not keepf.all():
                idx = np.nonzero(keepf)[0]
                rows = [rows[i] for i in idx.tolist()]
                ts = ts[idx]
        if not rows:
            return self._advance_and_close_device(pre_max)
        codes, _rev = self._key_codes(rows)
        if self._dev is None:  # the key-cache bound degraded mid-encode
            return _DEGRADED
        if self._dev["mode"] == "record":
            batch = HostBatch.from_rows(self.schema, rows, ts, self.dicts)
            feed = ("record", batch.cols, batch.nulls)
        else:
            self._input_cache = {}
            feed = ("segment", [
                None if a.input is None
                else self._agg_input_cols(a, rows, len(rows))
                for a in self.aggs])
        return self._process_device(codes.astype(np.int64), ts, feed,
                                    pre_max)

    def process_columnar(self, ts_ms, cols: Mapping[str, Any],
                         nulls: Mapping[str, np.ndarray] | None = None
                         ) -> list[dict[str, Any]]:
        """Columnar session ingest: int64 absolute-ms timestamps plus
        named numpy columns (object arrays for strings — the join's
        _plain_columns shape); a null-mask cell means the field is
        ABSENT from that record. On the device path the batch packs
        straight from the arrays (vectorized key encode, no row dicts);
        until the device path activates — or after a degrade — rows
        materialize once and take the row path, so semantics are
        identical."""
        n = len(ts_ms)
        if n == 0:
            return []
        self.read_epoch += 1
        if self._device_ready():
            out = self._process_columnar_device(
                np.asarray(ts_ms, np.int64), cols, nulls)
            if out is not _DEGRADED:
                return out
        return self.process(self._rows_from_cols(cols, nulls, n),
                            [int(t) for t in np.asarray(ts_ms)])

    @staticmethod
    def _rows_from_cols(cols, nulls, n: int) -> list[dict[str, Any]]:
        """Materialize columnar input into per-row dicts (pre-activation
        / post-degrade fallback); null-masked cells are ABSENT fields,
        the per-record decode shape."""
        names = list(cols)
        lists = [np.asarray(cols[c]).tolist() for c in names]
        rows = [dict(zip(names, vals)) for vals in zip(*lists)] \
            if names else [{} for _ in range(n)]
        if nulls:
            for cname, mask in nulls.items():
                if cname not in cols:
                    continue
                for row, isnull in zip(rows, np.asarray(mask).tolist()):
                    if isnull:
                        del row[cname]
        return rows

    def _process_columnar_device(self, ts, cols, nulls):
        """Columnar twin of _process_rows_device: vectorized host
        filter, memoized key encode, schema-typed device columns."""
        n = len(ts)
        pre_max = int(ts.max())
        kept = None
        if self._filter is not None:
            try:
                fv = eval_host_vec(self._filter, cols)
                keep = np.broadcast_to(np.asarray(fv, np.bool_),
                                       (n,)).copy()
            except Exception:  # noqa: BLE001 — host-only op in WHERE:
                # materialize rows once, run the row-shaped device path
                return self._process_rows_device(
                    self._rows_from_cols(cols, nulls, n),
                    [int(t) for t in ts])
            if nulls:
                # SQL NULL in a WHERE operand: predicate not-true
                for c in columns_of(self._filter):
                    nm = nulls.get(c)
                    if nm is not None:
                        keep &= ~np.asarray(nm, np.bool_)
            if not keep.all():
                kept = np.nonzero(keep)[0]
                ts = ts[kept]
                if len(ts) == 0:
                    return self._advance_and_close_device(pre_max)
        nk = n if kept is None else len(kept)
        codes = self._key_codes_cols(cols, nulls, kept, nk)
        if self._dev is None:  # the key-cache bound degraded mid-encode
            return _DEGRADED
        if self._dev["mode"] == "record":
            dcols, dnulls = self._typed_cols(cols, nulls, kept, nk)
            feed = ("record", dcols, dnulls)
        else:
            feed = ("segment", self._agg_vals_cols(cols, nulls, kept, nk))
        return self._process_device(codes, ts, feed, pre_max)

    def _agg_vals_cols(self, cols, nulls, kept, n: int):
        """(values f64[n], valid bool[n]) per aggregate straight from
        raw columnar input — the columnar twin of _agg_input_cols, same
        NULL rules (None / non-numeric / non-finite / null-masked cells
        do not contribute)."""
        from hstream_tpu.engine.expr import Col

        out: list[tuple[np.ndarray, np.ndarray] | None] = []
        cache: dict = {}
        rows_cache: list | None = None
        for a in self.aggs:
            if a.input is None:
                out.append(None)
                continue
            ck = (("col", a.input.name) if isinstance(a.input, Col)
                  else ("expr", id(a.input)))
            hit = cache.get(ck)
            if hit is None:
                if isinstance(a.input, Col):
                    raw = cols.get(a.input.name)
                    if raw is None:
                        vals = np.full(n, np.nan)
                    else:
                        arr = np.asarray(raw)
                        if kept is not None:
                            arr = arr[kept]
                        if arr.dtype.kind in "fiub":
                            vals = arr.astype(np.float64)
                        else:
                            vals = np.array(
                                [float(v) if isinstance(v, (int, float))
                                 else np.nan for v in arr.tolist()],
                                np.float64)
                else:
                    try:
                        v = eval_host_vec(a.input, cols)
                        vals = (np.full(n, float(v)) if np.ndim(v) == 0
                                else np.asarray(v, np.float64))
                        if kept is not None and len(vals) != n:
                            vals = vals[kept]
                    except Exception:  # noqa: BLE001 — host-only op:
                        # per-row eval over materialized dicts, once
                        if rows_cache is None:
                            rows_cache = self._rows_from_cols(
                                cols, nulls, len(np.asarray(
                                    next(iter(cols.values())))))
                            if kept is not None:
                                rows_cache = [rows_cache[i]
                                              for i in kept.tolist()]
                        vals = np.empty(n, np.float64)
                        for i, r in enumerate(rows_cache):
                            try:
                                v = eval_host(a.input, r)
                            except (TypeError, KeyError):
                                v = None
                            vals[i] = (float(v) if isinstance(
                                v, (int, float)) else np.nan)
                # null-masked referenced cells do not contribute
                if nulls:
                    for c in columns_of(a.input):
                        nm = nulls.get(c)
                        if nm is not None:
                            nm = np.asarray(nm, np.bool_)
                            vals = vals.copy()
                            vals[nm[kept] if kept is not None
                                 else nm] = np.nan
                hit = (vals, np.isfinite(vals))
                cache[ck] = hit
            out.append(hit)
        return out

    def _key_codes_cols(self, cols, nulls, kept, n: int) -> np.ndarray:
        """Dense key codes from columnar input. Numpy-typed columns
        factorize at C speed (np.unique per column, one dict hit per
        DISTINCT value/combination — the _columnar_key_ids discipline);
        object columns fall back to the memoized per-row loop.
        Null-masked group cells decode as None."""
        self._bound_key_cache()
        if not self.group_cols:  # global session: one key ()
            k = canon_key(())
            code = self._code_of.get(k)
            if code is None:
                code = len(self._code_rev)
                self._code_of[k] = code
                self._code_rev.append(k)
            return np.full(n, code, np.int64)
        col_vals: list[list] = []
        col_codes: list[np.ndarray] = []
        for cname in self.group_cols:
            arr = cols.get(cname)
            if arr is None:
                col_vals.append([None])
                col_codes.append(np.zeros(n, np.int64))
                continue
            a = np.asarray(arr)
            if kept is not None:
                a = a[kept]
            nm = nulls.get(cname) if nulls else None
            if nm is not None:
                nm = np.asarray(nm, np.bool_)
                if kept is not None:
                    nm = nm[kept]
                if not nm.any():
                    nm = None
            if a.dtype.kind == "O":
                return self._key_codes_cols_slow(cols, nulls, kept, n)
            uniq, inv = np.unique(a, return_inverse=True)
            vals = uniq.tolist()  # python scalars: canon/dict semantics
            codes = inv.astype(np.int64)
            if nm is not None:
                vals = [None] + vals
                codes = np.where(nm, 0, codes + 1)
            col_vals.append(vals)
            col_codes.append(codes)
        if len(col_vals) == 1:
            vals, codes = col_vals[0], col_codes[0]
            lut = np.empty(len(vals), np.int64)
            for p, v in enumerate(vals):
                lut[p] = self._code_for(canon_key((v,)))
            return lut[codes]
        radix = 1
        for vals in col_vals:
            radix *= max(len(vals), 1)
        if radix >= (1 << 62):  # mixed-radix would overflow int64
            return self._key_codes_cols_slow(cols, nulls, kept, n)
        combined = col_codes[0]
        for codes, vals in zip(col_codes[1:], col_vals[1:]):
            combined = combined * len(vals) + codes
        u, inv = np.unique(combined, return_inverse=True)
        lut = np.empty(len(u), np.int64)
        for j, cu in enumerate(u.tolist()):
            idxs = []
            for vals in reversed(col_vals[1:]):
                idxs.append(cu % len(vals))
                cu //= len(vals)
            idxs.append(cu)
            idxs.reverse()
            key = canon_key(tuple(col_vals[g][i]
                                  for g, i in enumerate(idxs)))
            lut[j] = self._code_for(key)
        return lut[inv]

    def _code_for(self, key: tuple) -> int:
        code = self._code_of.get(key)
        if code is None:
            code = len(self._code_rev)
            self._code_of[key] = code
            self._code_rev.append(key)
        return code

    def _key_codes_cols_slow(self, cols, nulls, kept, n: int
                             ) -> np.ndarray:
        """Object-column fallback: one memoized dict hit per row over
        raw value tuples (the _key_codes discipline)."""
        parts: list[list] = []
        for cname in self.group_cols:
            arr = cols.get(cname)
            if arr is None:
                parts.append([None] * n)
                continue
            a = np.asarray(arr)
            if kept is not None:
                a = a[kept]
            vals = a.tolist()
            nm = nulls.get(cname) if nulls else None
            if nm is not None:
                nm = np.asarray(nm, np.bool_)
                if kept is not None:
                    nm = nm[kept]
                if nm.any():
                    vals = [None if isnull else v
                            for v, isnull in zip(vals, nm.tolist())]
            parts.append(vals)
        memo = self._raw_memo
        out = np.empty(n, np.int64)
        rows_iter = zip(*parts) if len(parts) > 1 \
            else ((v,) for v in parts[0])
        for i, raw in enumerate(rows_iter):
            code = memo.get(raw)
            if code is None:
                code = self._code_for(canon_key(raw))
                memo[raw] = code
            out[i] = code
        return out

    def _typed_cols(self, cols, nulls, kept, n: int):
        """Schema-typed device columns + per-column null masks from raw
        columnar input — the same NULL rules as HostBatch.from_rows
        (None / non-scalar numeric cells are SQL NULL; strings stringify
        and dictionary-encode)."""
        dcols: dict[str, np.ndarray] = {}
        dnulls: dict[str, np.ndarray] = {}
        for name, _tag in self._dev["layout"]:
            want = self.schema.type_of(name)
            raw = cols.get(name)
            msk = np.zeros(n, np.bool_)
            nm = nulls.get(name) if nulls else None
            if nm is not None:
                nm = np.asarray(nm, np.bool_)
                msk |= nm[kept] if kept is not None else nm
            if raw is None:
                dcols[name] = np.zeros(
                    n, np.int32 if want == ColumnType.STRING
                    else np.float32)
                dnulls[name] = np.ones(n, np.bool_)
                continue
            a = np.asarray(raw)
            if kept is not None:
                a = a[kept]
            if want == ColumnType.STRING:
                enc = self.dicts[name].encode
                out = np.empty(n, np.int32)
                for i, v in enumerate(a.tolist()):
                    if v is None:
                        out[i] = -1
                        msk[i] = True
                    else:
                        out[i] = enc(str(v))
            else:
                dt = (np.bool_ if want == ColumnType.BOOL
                      else np.int32 if want == ColumnType.INT
                      else np.float32)
                if a.dtype.kind in "fiub":
                    out = a.astype(dt)
                else:
                    out = np.zeros(n, dt)
                    for i, v in enumerate(a.tolist()):
                        if v is None or not isinstance(
                                v, (int, float, bool)):
                            msk[i] = True
                        else:
                            out[i] = v
            dcols[name] = out
            if msk.any():
                dnulls[name] = msk
        return dcols, (dnulls or None)

    def _advance_and_close_device(self, pre_max: int):
        """Watermark advance + close cycle for a batch whose records all
        filtered out — the wm still moves (it is computed pre-filter)."""
        if pre_max > self.watermark:
            self.watermark = pre_max
        out = self._close_due_device()
        return out if out else []

    # contract: dispatches<=1 fetches<=0
    def _process_device(self, codes, ts, feed, pre_max):
        """One device micro-batch: mirror-side late walk + segmentation
        + chain merge (numpy), then ONE fused kernel dispatch and NO
        fetch — the session ingest contract. Closes ride
        _close_due_device (their own one-dispatch-one-fetch budget)."""
        from hstream_tpu.engine import lattice

        dev = self._dev
        gap = self.window.gap_ms
        grace = self.window.grace_ms
        n = len(codes)
        self.session_stats["batches"] += 1
        if n and self.watermark >= 0 \
                and int(ts.min()) + gap + grace <= self.watermark:
            keep = self._late_keep_mask(codes, ts)
            if not keep.all():
                self.late_drops += int(n - keep.sum())
                idx = np.nonzero(keep)[0]
                codes = codes[idx]
                ts = ts[idx]
                feed = self._subset_feed(feed, idx)
                n = len(codes)
        if n:
            # shared segmentation: per-key gap-chains of this batch —
            # ONE combined-key argsort (codes are < 2^22 and the span is
            # int32-bounded, so code*span+ts fits int64; ties are
            # commutative-merge-equal, so stability is not needed)
            tmin = int(ts.min())
            span = int(ts.max()) - tmin + 1
            order = np.argsort(codes * span + (ts - tmin))
            ks = codes[order]
            tss = ts[order]
            brk = np.empty(n, np.bool_)
            brk[0] = True
            brk[1:] = (ks[1:] != ks[:-1]) | ((tss[1:] - tss[:-1]) > gap)
            starts = np.nonzero(brk)[0]
            ends = np.append(starts[1:], n)
            seg_code = ks[starts]
            seg_t0 = tss[starts]
            seg_t1 = tss[ends - 1]
            live = dev["mir_live"]
            mcode, mt0, mt1, fanin = merge_chains_np(
                np.concatenate([dev["mir_code"][live], seg_code]),
                np.concatenate([dev["mir_t0"][live], seg_t0]),
                np.concatenate([dev["mir_t1"][live], seg_t1]),
                gap, n_first=int(live.sum()))
            if fanin > self.chain_merge_limit:
                self._degrade_to_host(
                    f"one session chain merged {fanin} open sessions "
                    f"(> chain_merge_limit {self.chain_merge_limit})")
                return _DEGRADED
            need = len(mcode)
            if dev.get("ssl") is not None:
                # per-shard cap: size to the fullest residue class
                need = int(np.bincount(
                    (mcode % dev["ssl"].n_shards).astype(np.int64),
                    minlength=dev["ssl"].n_shards).max())
            if need > dev["cap"]:
                self._grow_arena(need)
            if self.epoch is None:
                self.epoch = int(mt0.min())
            # close_cut is compared against PRE-shift arena times in the
            # kernel, so compute it in the OLD epoch before any rebase.
            # In range by construction: |closed_wm - epoch| < the span
            # bound below and 2*gap + grace < 2^30 (activation guard).
            close_cut = np.int32(-(1 << 30)) if self._closed_wm < 0 else \
                np.int32(self._closed_wm - 2 * gap - grace - self.epoch)
            delta = self._maybe_rebase_dev(int(mt1.max()), int(mt0.min()))
            if int(mt1.max()) - self.epoch >= self.REBASE_THRESHOLD:
                # the rebase could not reclaim range (an ancient session
                # pins the anchor): past this bound the kernels' scan
                # arithmetic and the t0 scatter identity stop covering
                # the values — the HOST engine has no such bound, so
                # degrade instead of dying (found by code review: a
                # pinned anchor + ~12 days of stream time desynced the
                # mirror and crash-looped the query)
                self._degrade_to_host(
                    "relative stream span reached the device range "
                    "(an old session is still open); host engine "
                    "continues without the int32 bound")
                return _DEGRADED
            try:
                if FAULTS.active:  # chaos: fail/delay a session step
                    FAULTS.point("device.session.dispatch")
                if dev["mode"] == "record":
                    dev["arena"] = self._dispatch_record_step(
                        codes, ts, feed, close_cut, delta)
                else:
                    dev["arena"] = self._dispatch_segment_merge(
                        feed, order, starts, ends, np.cumsum(brk) - 1,
                        seg_code, seg_t0, seg_t1, close_cut, delta)
            except Exception as e:  # noqa: BLE001 — dispatch failed
                # before any state mutation (functional update): the
                # host path continues from the unchanged arena
                self._degrade_to_host(
                    f"step dispatch failed "
                    f"({type(e).__name__}: {e})")
                return _DEGRADED
            self.session_stats["step_dispatches"] += 1
            dev["mir_code"] = mcode
            dev["mir_t0"] = mt0
            dev["mir_t1"] = mt1
            dev["mir_live"] = np.ones(len(mcode), np.bool_)
        return self._advance_and_close_device(pre_max)

    @staticmethod
    def _subset_feed(feed, idx):
        """Apply a keep-index to either feed shape (late-record drops)."""
        if feed[0] == "record":
            _tag, cols, nulls = feed
            return ("record",
                    {k: np.asarray(v)[idx] for k, v in cols.items()},
                    None if nulls is None else
                    {k: np.asarray(v)[idx] for k, v in nulls.items()})
        _tag, vv = feed
        return ("segment", [
            None if e is None else (e[0][idx], e[1][idx]) for e in vv])

    def _dispatch_record_step(self, codes, ts, feed, close_cut, delta):
        """Record-mode dispatch: pack raw records into one int32 wire
        buffer (compact — H2D bytes dominate on tunneled accelerators)
        and run the fully fused sort+scan+scatter step."""
        from hstream_tpu.engine import lattice

        dev = self._dev
        _tag, cols, nulls = feed
        n = len(codes)
        ts_rel = (ts - self.epoch).astype(np.int64)
        bcap = self._dev_bcap(n)
        null_masks = []
        for refs in dev["null_refs"]:
            m = np.zeros(n, np.bool_)
            if nulls:
                for c in refs:
                    nm = nulls.get(c)
                    if nm is not None:
                        m |= np.asarray(nm, np.bool_)[:n]
            null_masks.append(m if m.any() else None)
        packed = lattice.pack_batch_host(
            bcap, n, codes.astype(np.int32), ts_rel, None, cols,
            null_masks, dev["layout"])
        self.transfer_stats["h2d_bytes"] += int(
            getattr(packed, "nbytes", 0))
        ssl = dev.get("ssl")
        if ssl is not None:
            # the batch replicates along the key axis; the shard_map
            # wrapper clears the valid bit of records other shards own
            self.sharded_dispatches += 1
            with kernel_family("session", self.dispatch_observer,
                               ready=self._device_values):
                dev["arena"] = ssl.step(dev["arena"], packed,
                                        np.int32(self.window.gap_ms),
                                        close_cut, np.int32(delta))
            return dev["arena"]
        step = lattice.session_step_kernel(
            dev["spec"], self.schema, dev["layout"], dev["cap"], bcap)
        with kernel_family("session", self.dispatch_observer,
                           ready=self._device_values):
            dev["arena"] = step(dev["arena"], packed,
                                np.int32(self.window.gap_ms), close_cut,
                                np.int32(delta))
        return dev["arena"]

    def _dispatch_segment_merge(self, feed, order, starts, ends,
                                seg_of_row_sorted, seg_code, seg_t0,
                                seg_t1, close_cut, delta):
        """Segment-mode dispatch: reduce the batch's rows into
        per-segment plane contributions with the host path's vectorized
        machinery (reduceat / add.at — exact, segments are gap-chains)
        and merge the segment arena into the session arena on device."""
        from hstream_tpu.engine import lattice

        dev = self._dev
        _tag, vv = feed
        seg = self._segment_planes(vv, order, starts, ends,
                                   seg_of_row_sorted, seg_code,
                                   seg_t0 - self.epoch,
                                   seg_t1 - self.epoch)
        self.transfer_stats["h2d_bytes"] += sum(
            int(getattr(v, "nbytes", 0)) for v in seg.values())
        ssl = dev.get("ssl")
        if ssl is not None:
            # segments replicate along the key axis; the shard_map
            # wrapper rewrites unowned segment codes to the sentinel
            self.sharded_dispatches += 1
            with kernel_family("session", self.dispatch_observer,
                               ready=self._device_values):
                dev["arena"] = ssl.merge(dev["arena"], seg,
                                         np.int32(self.window.gap_ms),
                                         close_cut, np.int32(delta))
            return dev["arena"]
        kern = lattice.session_merge_kernel(dev["spec"], dev["cap"],
                                            len(seg["code"]))
        with kernel_family("session", self.dispatch_observer,
                           ready=self._device_values):
            dev["arena"] = kern(dev["arena"], seg,
                                np.int32(self.window.gap_ms), close_cut,
                                np.int32(delta))
        return dev["arena"]

    def _segment_planes(self, vv, order, starts, ends, seg_of_row,
                        seg_code, seg_t0_rel, seg_t1_rel
                        ) -> dict[str, np.ndarray]:
        """Per-segment arena-format planes (numpy, padded to a sticky
        pow2 segment capacity) — the same reductions as the host path's
        _segment_accs, emitted in device plane layout."""
        from hstream_tpu.engine import lattice

        dev = self._dev
        spec = dev["spec"]
        nseg = len(starts)
        scap = self._dev_scap(nseg)
        seg: dict[str, np.ndarray] = {
            "code": np.full(scap, lattice.SESSION_SENT_CODE, np.int32),
            "t0": np.zeros(scap, np.int32),
            "t1": np.zeros(scap, np.int32),
        }
        seg["code"][:nseg] = seg_code.astype(np.int32)
        seg["t0"][:nseg] = seg_t0_rel
        seg["t1"][:nseg] = seg_t1_rel
        seg_len = None
        sorted_cache: dict = {}
        for i, (name, a) in enumerate(zip(
                lattice.session_plane_names(spec), spec.aggs)):
            if name in seg:
                continue  # aliased plane (p50+p99 share the histogram)
            if a.kind == AggKind.COUNT_ALL:
                if seg_len is None:
                    seg_len = (ends - starts).astype(np.int64)
                plane = np.zeros(scap, np.int32)
                plane[:nseg] = seg_len
                seg[name] = plane
                continue
            vals, ok = vv[i]
            hit = sorted_cache.get(id(vals))
            if hit is None:
                hit = (vals[order], ok[order])
                sorted_cache[id(vals)] = hit
            vs, okv = hit
            if a.kind == AggKind.COUNT:
                plane = np.zeros(scap, np.int32)
                plane[:nseg] = np.add.reduceat(okv.astype(np.int64),
                                               starts)
            elif a.kind == AggKind.SUM:
                plane = np.zeros(scap, np.float32)
                plane[:nseg] = np.add.reduceat(np.where(okv, vs, 0.0),
                                               starts)
            elif a.kind == AggKind.AVG:
                plane = np.zeros(scap, np.float32)
                plane[:nseg] = np.add.reduceat(np.where(okv, vs, 0.0),
                                               starts)
                pn = np.zeros(scap, np.int32)
                pn[:nseg] = np.add.reduceat(okv.astype(np.int64), starts)
                seg[name + "_n"] = pn
            elif a.kind == AggKind.MIN:
                plane = np.full(scap, np.inf, np.float32)
                plane[:nseg] = np.minimum.reduceat(
                    np.where(okv, vs, np.inf), starts)
            elif a.kind == AggKind.MAX:
                plane = np.full(scap, -np.inf, np.float32)
                plane[:nseg] = np.maximum.reduceat(
                    np.where(okv, vs, -np.inf), starts)
            elif a.kind == AggKind.APPROX_COUNT_DISTINCT:
                plane = np.zeros((scap, self.hll.m), np.int8)
                reg, rank = hll_update_np(
                    np.where(okv, vs, 0.0).astype(np.float32), self.hll)
                np.maximum.at(plane, (seg_of_row[okv], reg[okv]),
                              rank[okv])
            elif a.kind == AggKind.APPROX_QUANTILE:
                nb = self.qcfg.n_bins
                b = quantile_bin_np(
                    np.where(okv, vs, self.qcfg.min_value), self.qcfg)
                # bincount over the flattened (segment, bin) space is
                # ~5x np.add.at for the same scattered histogram
                flat = seg_of_row[okv] * nb + b[okv]
                plane = np.bincount(
                    flat, minlength=scap * nb).astype(
                    np.int32).reshape(scap, nb)
            else:
                raise SQLCodegenError(
                    f"session agg {a.kind} unsupported")
            seg[name] = plane
        return seg

    def _dev_scap(self, nseg: int) -> int:
        # the shape-stability twin of _dev_bcap, floored lower —
        # segments are few
        return self._sticky_cap(self._dev["scaps"], nseg, 256)

    def _late_keep_mask(self, codes, ts) -> np.ndarray:
        """The order-dependent part of the reference semantics: walk the
        batch in (per-key) ts order over the INTERVAL mirror, dropping
        records that are past grace AND cannot merge into any session
        alive at their turn (SessionWindowedStream.hs:84-118). Interval
        state only — no accumulators — so this host walk costs a few
        list ops per record, and only on batches that actually carry
        possibly-late records."""
        gap = self.window.gap_ms
        grace = self.window.grace_ms
        wm = self.watermark
        n = len(codes)
        dev = self._dev
        batch_keys = set(codes.tolist())
        iv: dict[int, list[list[int]]] = {}
        for slot in np.nonzero(dev["mir_live"])[0].tolist():
            c = int(dev["mir_code"][slot])
            if c in batch_keys:
                iv.setdefault(c, []).append(
                    [int(dev["mir_t0"][slot]), int(dev["mir_t1"][slot])])
        keep = np.ones(n, np.bool_)
        order = np.lexsort((ts, codes))
        for p in order.tolist():
            c = int(codes[p])
            t = int(ts[p])
            lst = iv.setdefault(c, [])
            overl = [s for s in lst if s[0] - gap <= t <= s[1] + gap]
            if not overl:
                if t + gap + grace <= wm:
                    keep[p] = False
                    continue
                lst.append([t, t])
                continue
            m = overl[0]
            for s in overl[1:]:
                m[0] = min(m[0], s[0])
                m[1] = max(m[1], s[1])
                lst.remove(s)
            m[0] = min(m[0], t)
            m[1] = max(m[1], t)
        return keep

    def _maybe_rebase_dev(self, max_ts: int, anchor: int) -> int:
        """Re-anchor the device epoch when relative time nears int32
        range; the returned delta rides the next step dispatch (the
        kernel shifts arena times in the same fused pass)."""
        if max_ts - self.epoch < self.REBASE_THRESHOLD:
            return 0
        delta = anchor - self.epoch
        if delta <= 0:
            return 0
        self.epoch += delta
        return delta

    @staticmethod
    def _sticky_cap(caps: set, n: int, lo: int) -> int:
        """Sticky pow2 capacity (the _stage_cap discipline): each
        distinct cap is its own compiled kernel, so varying sizes
        converge on a handful of shapes; a size reuses the smallest
        already-chosen cap within 8x padding."""
        for c in sorted(caps):
            if n <= c <= 8 * max(n, 1):
                return c
        cap = round_up_pow2(n, lo=lo)
        caps.add(cap)
        return cap

    def _dev_bcap(self, n: int) -> int:
        return self._sticky_cap(self._dev["bcaps"], n, 4096)

    def _grow_arena(self, need: int) -> None:
        """Double the arena capacity (pow2) — rare; compiled shapes
        converge like grow_keys on the window lattice."""
        from hstream_tpu.engine import lattice

        dev = self._dev
        new_cap = round_up_pow2(need, lo=dev["cap"] * 2)
        if dev.get("ssl") is not None:
            dev["arena"] = dev["ssl"].grow_arena(dev["arena"], new_cap)
        else:
            dev["arena"] = lattice.grow_session_arena(
                dev["spec"], dev["arena"], new_cap)
        dev["cap"] = new_cap
        self.session_stats["grows"] += 1

    # contract: dispatches<=1 fetches<=0
    def _compact_codes_device(self) -> None:
        """Key-code compaction under the cache bound: keep only codes
        with live sessions, reassign dense codes in sorted order (the
        arena stays (code, t0)-sorted), remap the arena through the
        pow2-padded LUT kernel — one dispatch, no fetch. Dead codes map
        to the sentinel, so the remap doubles as eviction."""
        import jax

        from hstream_tpu.engine import lattice

        dev = self._dev
        live = dev["mir_live"]
        # pending deferred closes still decode by their PRE-remap codes
        # (the extracted device buffers are immutable): resolve their
        # key columns against the old dictionary now
        self._pending_closes = [
            (codes, t0, t1, packed,
             keys if keys is not None else
             [arr[codes.astype(np.int64)]
              for arr in self._code_rev_columns()])
            for codes, t0, t1, packed, keys in self._pending_closes]
        live_codes = np.unique(dev["mir_code"][live]).astype(np.int64)
        lcap = round_up_pow2(max(len(self._code_rev), 1), lo=256)
        lut = np.full(lcap, lattice.SESSION_SENT_CODE, np.int32)
        ssl = dev.get("ssl")
        if ssl is not None:
            # residue-class-preserving compaction (new % n_shards ==
            # old % n_shards): entries never change owner shard, and
            # within a shard the map is order-preserving, so every
            # per-shard arena stays (code, t0)-sorted without a sort
            ns = ssl.n_shards
            new_of = np.empty(len(live_codes), np.int64)
            for s in range(ns):
                m = (live_codes % ns) == s
                new_of[m] = np.arange(int(m.sum()),
                                      dtype=np.int64) * ns + s
        else:
            new_of = np.arange(len(live_codes), dtype=np.int64)
        lut[live_codes] = new_of.astype(np.int32)
        try:
            if ssl is not None:
                dev["arena"] = ssl.remap(dev["arena"],
                                         jax.device_put(lut))
                self.sharded_dispatches += 1
            else:
                kern = lattice.session_remap_kernel(dev["cap"], lcap)
                dev["arena"] = kern(dev["arena"], jax.device_put(lut))
        except Exception as e:  # noqa: BLE001 — arena unchanged
            # (functional update): the host engine continues with the
            # un-remapped caches; the device caller re-checks _dev
            self._degrade_to_host(
                f"code remap dispatch failed "
                f"({type(e).__name__}: {e})")
            return
        self.session_stats["remap_dispatches"] += 1
        new_code = np.full(len(dev["mir_code"]), -1, np.int64)
        pos = np.searchsorted(live_codes, dev["mir_code"][live])
        new_code[live] = new_of[pos]
        if ssl is not None:
            # dead mirror rows still occupy arena slots: keep their
            # shard residue (negative = poison, residue survives the
            # floor modulo) so per-shard slot ranks stay aligned
            new_code[~live] = dev["mir_code"][~live] % ns - ns
        dev["mir_code"] = new_code
        # sharded new codes are class-strided, so the reverse index may
        # carry holes (None); only live codes ever decode through it
        top = int(new_of.max()) + 1 if len(live_codes) else 0
        new_rev: list = [None] * top
        for c, nc in zip(live_codes.tolist(), new_of.tolist()):
            new_rev[nc] = self._code_rev[c]
        self._code_rev = new_rev
        self._code_of = {k: i for i, k in enumerate(new_rev)
                         if k is not None}
        self._raw_memo = {}
        self._code_cols_cache = (-1, [])

    # contract: dispatches<=1 fetches<=1
    def _close_due_device(self):
        """Close every session past end + 2*gap + grace: the mirror
        names the due slots, ONE pow2-padded extract dispatch finalizes
        them on device, ONE fetch brings the packed buffer down, and the
        decode is columnar (ColumnarEmit). With defer_close_decode the
        fetch is deferred: drain_closed() later stacks every pending
        cycle into one transfer per buffer shape. The arena retires the
        closed entries lazily on the next step dispatch (close_cut)."""
        dev = self._dev
        gap = self.window.gap_ms
        grace = self.window.grace_ms
        if self.watermark < 0:
            return []
        due = dev["mir_live"] & (dev["mir_t1"] + 2 * gap + grace
                                 <= self.watermark)
        idx = np.nonzero(due)[0]
        if len(idx) == 0:
            return []
        self.session_stats["close_cycles"] += 1
        # the mirror rows are snapshotted NOW: the mirror mutates on the
        # next step, the deferred decode must not see that
        codes = dev["mir_code"][idx].copy()
        t0 = dev["mir_t0"][idx].copy()
        t1 = dev["mir_t1"][idx].copy()
        self.session_stats["close_dispatches"] += 1
        try:
            packed_dev = self._dispatch_extract(idx)
        except Exception as e:  # noqa: BLE001 — nothing retired yet:
            # the host engine closes the same due set from the pulled-
            # back state (a FETCH failure later still propagates — by
            # then the buffers are the only copy of those rows)
            self._degrade_to_host(
                f"close extract dispatch failed "
                f"({type(e).__name__}: {e})")
            return self.close_due_sessions()
        dev["mir_live"][idx] = False
        self._closed_wm = max(self._closed_wm, self.watermark)
        if self.defer_close_decode:
            # keep the packed batch as a device value; no host sync
            self._pending_closes.append((codes, t0, t1, packed_dev,
                                         None))
            return []
        self.session_stats["close_fetches"] += 1
        packed_host = np.asarray(packed_dev)
        self.transfer_stats["d2h_bytes"] += packed_host.nbytes
        return self._decode_close(packed_host, codes, t0, t1)

    def _dispatch_extract(self, idx: np.ndarray):
        """One pow2-padded extract dispatch over the named arena slots;
        returns the packed device value (the caller fetches or defers)."""
        from hstream_tpu.engine import lattice

        dev = self._dev
        if FAULTS.active:  # chaos: fail/delay a session extract
            FAULTS.point("device.session.dispatch")
        ssl = dev.get("ssl")
        if ssl is not None:
            # per-shard slot lists [n_shards, pcap] (-1 pads), each
            # shard's in the order its rows appear in idx — the order
            # _flatten_sharded_extract reassembles by
            cls, slot = self._shard_slots()
            sel = cls[idx]
            per = np.bincount(sel, minlength=ssl.n_shards)
            pcap = round_up_pow2(max(int(per.max()), 1), lo=1)
            slots = np.full((ssl.n_shards, pcap), -1, np.int32)
            for s in range(ssl.n_shards):
                v = slot[idx[sel == s]]
                slots[s, :len(v)] = v
            self.sharded_dispatches += 1
            res = None

            def _ready():  # the extract result once the body ran
                return dev["arena"] if res is None else res

            with kernel_family("close", self.dispatch_observer,
                               ready=_ready):
                res = ssl.extract(dev["arena"], slots)
            return res
        slots = lattice.pad_slots(idx)
        kern = lattice.session_extract_kernel(dev["spec"], dev["cap"],
                                              len(slots))
        res = None

        def _ready():
            return dev["arena"] if res is None else res

        with kernel_family("close", self.dispatch_observer, ready=_ready):
            res = kern(dev["arena"], slots)
        return res

    # contract: dispatches<=0 fetches<=1
    def drain_closed(self) -> list[dict[str, Any]]:
        """Decode every deferred session close. Multiple pending close
        cycles fetch in ONE device->host transfer per buffer shape
        (stack_pow2) — fetch count, not bytes, dominates drain cost on
        real links. A fetch failure here propagates: the closed slots'
        mirror entries are already retired, so task death + supervised
        restart from snapshot is the correct recovery (the PR 8 drain
        rule)."""
        from hstream_tpu.engine import lattice

        if not self._pending_closes:
            return []
        out = None
        if len(self._pending_closes) == 1:
            codes, t0, t1, packed_dev, keys = self._pending_closes[0]
            self.session_stats["close_fetches"] += 1
            packed_host = np.asarray(packed_dev)
            self.transfer_stats["d2h_bytes"] += packed_host.nbytes
            out = self._decode_close(packed_host, codes, t0, t1, keys)
            self._pending_closes.clear()  # only after decode succeeded
            return out if out is not None else []
        by_shape: dict[tuple, list[tuple]] = {}
        for ent in self._pending_closes:
            by_shape.setdefault(tuple(ent[3].shape), []).append(ent)
        for group in by_shape.values():
            self.session_stats["close_fetches"] += 1
            stacked = np.asarray(lattice.stack_pow2(
                [p for _c, _a, _b, p, _k in group]))
            self.transfer_stats["d2h_bytes"] += stacked.nbytes
            for (codes, t0, t1, _, keys), packed in zip(group, stacked):
                out = extend_rows(
                    out, self._decode_close(packed, codes, t0, t1, keys))
        self._pending_closes.clear()
        return out if out is not None else []

    def has_pending_closes(self) -> bool:
        return bool(self._pending_closes)

    def flush_changes(self) -> list[dict[str, Any]]:
        """API parity with QueryExecutor's drain surface: sessions have
        no deferred changelog, so flushing delivers any deferred closes."""
        return self.drain_closed()

    # contract: dispatches<=0 fetches<=1
    def block_until_ready(self) -> None:
        if self._dev is not None:
            import jax

            jax.block_until_ready(self._dev["arena"])

    # ---- device cost plane (ISSUE 18) ----------------------------------

    # contract: dispatches<=0 fetches<=0
    def _device_values(self):
        """Late-bound handle for the device-time sampler: the arena dict
        after the dispatch under measurement replaced it."""
        dev = self._dev
        return dev["arena"] if dev is not None else ()

    # contract: dispatches<=0 fetches<=0
    def device_plane_bytes(self) -> dict[str, int]:
        """Exact live device bytes per arena plane (host-mode: empty —
        the numpy mirrors are not device-resident)."""
        from hstream_tpu.stats.devicecost import plane_bytes

        dev = self._dev
        if dev is None:
            return {}
        return plane_bytes(dev["arena"])

    @staticmethod
    def _flatten_sharded_extract(packed: np.ndarray,
                                 codes: np.ndarray) -> np.ndarray:
        """Sharded extract buffer [n_shards, 1 + n_aggs, pcap] -> the
        single-chip [1 + n_aggs, k] layout: row r of the close's codes
        snapshot sits at (its shard, its rank among the snapshot's rows
        of that shard) — the order _dispatch_extract built the per-shard
        slot lists in. Works on deferred buffers too: the codes snapshot
        predates any compaction, and the remap preserves residues."""
        ns = packed.shape[0]
        cls = (codes % ns).astype(np.int64)
        rank = np.empty(len(codes), np.int64)
        for s in range(ns):
            m = cls == s
            rank[m] = np.arange(int(m.sum()))
        return np.ascontiguousarray(packed[cls, :, rank].T)

    def _decode_close(self, packed: np.ndarray, codes, t0, t1,
                      keys=None):
        k = len(codes)
        if packed.ndim == 3:  # sharded extract: [n_shards, rows, pcap]
            packed = self._flatten_sharded_extract(packed, codes)
        if not np.array_equal(packed[0, :k], codes):
            raise AssertionError(
                "session mirror diverged from device arena codes")
        return self._decode_device_rows(packed, codes, t0, t1, keys)

    def _decode_device_rows(self, packed: np.ndarray, codes, t0, t1,
                            keys=None):
        """Fetched extract buffer -> ColumnarEmit: key decode is a
        cached reverse-index gather, agg values are already finalized on
        device (counts/HLL i32, floats f32-bitcast), window bounds come
        from the mirror snapshot taken at dispatch time."""
        n = len(codes)
        cols: dict[str, Any] = {}
        if keys is not None:  # resolved before a code-space compaction
            for name, arr in zip(self.group_cols, keys):
                cols[name] = arr
        else:
            for name, arr in zip(self.group_cols,
                                 self._code_rev_columns()):
                cols[name] = arr[codes.astype(np.int64)]
        row = 1
        for a in self.aggs:
            v = np.ascontiguousarray(packed[row, :n])
            if a.kind in (AggKind.COUNT_ALL, AggKind.COUNT,
                          AggKind.APPROX_COUNT_DISTINCT):
                cols[a.out_name] = v.astype(np.int64)
            else:
                cols[a.out_name] = v.view(np.float32).astype(np.float64)
            row += 1
        cols["winStart"] = t0.astype(np.int64)
        cols["winEnd"] = (t1 + self.window.gap_ms).astype(np.int64)
        return self._postprocess_session_cols(cols, n)

    def _code_rev_columns(self) -> list[np.ndarray]:
        """Per-group-column object arrays over the code dictionary for
        vectorized key decode; rebuilt only when codes changed."""
        version = len(self._code_rev)
        if self._code_cols_cache[0] != version:
            out = []
            for g in range(len(self.group_cols)):
                arr = np.empty(version, object)
                for i, key in enumerate(self._code_rev):
                    if key is not None:  # sharded-compaction hole
                        arr[i] = key[g]
                out.append(arr)
            self._code_cols_cache = (version, out)
        return self._code_cols_cache[1]

    # contract: dispatches<=1 fetches<=1
    def _peek_device(self):
        """Open-session rows without touching state: one read-only
        extract dispatch over every live slot + one fetch."""
        dev = self._dev
        idx = np.nonzero(dev["mir_live"])[0]
        if len(idx) == 0:
            return []
        self.session_stats["peek_dispatches"] += 1
        try:
            packed_dev = self._dispatch_extract(idx)
        except Exception as e:  # noqa: BLE001 — read-only: degrade and
            # peek the pulled-back host state instead
            self._degrade_to_host(
                f"peek extract dispatch failed "
                f"({type(e).__name__}: {e})")
            return self.peek()
        return self._decode_close(np.asarray(packed_dev),
                                  dev["mir_code"][idx].copy(),
                                  dev["mir_t0"][idx].copy(),
                                  dev["mir_t1"][idx].copy())
