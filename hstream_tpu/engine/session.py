"""Session-window aggregation.

Reference semantics (SessionWindowedStream.hs:84-118): a record at ts
belongs to session [ts, ts]; sessions of the same key merge when their
gap-extended intervals overlap (ts within `gap` of the session edge);
a session closes when the watermark passes end + gap + grace.

Merge-on-overlap is inherently sequential per key, so the design follows
SURVEY §7: per-batch segmentation is vectorized (lexsort by (key, ts) +
gap-break detection + reduceat segment reduction), then the few resulting
segment aggregates merge into per-key session state on the host. All
accumulators are monoids, so segment/session merges are exact. Device
offload of the segmentation is a later optimization — per-batch work is
O(B log B) numpy, and segment counts are tiny compared to record counts.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Mapping, Sequence

import numpy as np

from hstream_tpu.common.errors import SQLCodegenError
from hstream_tpu.engine.executor import QueryExecutor
from hstream_tpu.engine.expr import eval_host
from hstream_tpu.engine.plan import AggKind, AggregateNode, AggSpec
from hstream_tpu.engine.sketches import HLLConfig, QuantileConfig
from hstream_tpu.engine.types import Schema, canon_key
from hstream_tpu.engine.window import SessionWindow


# ---- numpy sketch helpers (host-side finalize) -----------------------------

def hll_update_np(values: np.ndarray, cfg: HLLConfig):
    """(register idx, rank) per value — numpy mirror of
    sketches.hll_update_indices (same hash, same estimates merge)."""
    v = np.ascontiguousarray(values, dtype=np.float32)
    v = np.where(v == 0.0, np.float32(0.0), v)
    h = v.view(np.uint32).copy()
    h ^= h >> 16
    h = (h * np.uint32(0x85EBCA6B)) & np.uint32(0xFFFFFFFF)
    h ^= h >> 13
    h = (h * np.uint32(0xC2B2AE35)) & np.uint32(0xFFFFFFFF)
    h ^= h >> 16
    p = cfg.precision
    reg = (h >> (32 - p)).astype(np.int64)
    w = (h << p) & np.uint32(0xFFFFFFFF)
    # count leading zeros of remaining bits
    rank = np.zeros(len(v), dtype=np.int64)
    x = w.copy()
    for shift in (16, 8, 4, 2, 1):
        empty = (x >> (32 - shift)) == 0
        rank += np.where(empty, shift, 0)
        x = np.where(empty, (x << shift) & np.uint32(0xFFFFFFFF), x)
    rank = np.where(w == 0, 32, rank)
    rank = np.minimum(rank + 1, 32 - p + 1).astype(np.int8)
    return reg, rank


def hll_estimate_np(registers: np.ndarray, cfg: HLLConfig) -> float:
    m = cfg.m
    if m == 16:
        alpha = 0.673
    elif m == 32:
        alpha = 0.697
    elif m == 64:
        alpha = 0.709
    else:
        alpha = 0.7213 / (1 + 1.079 / m)
    regs = registers.astype(np.float64)
    raw = alpha * m * m / np.sum(np.exp2(-regs))
    zeros = int(np.sum(registers == 0))
    if raw <= 2.5 * m and zeros > 0:
        return m * math.log(m / zeros)
    return float(raw)


def quantile_bin_np(values: np.ndarray, cfg: QuantileConfig) -> np.ndarray:
    v = np.maximum(values.astype(np.float64), 0.0)
    safe = np.maximum(v, cfg.min_value)
    b = np.floor(np.log(safe / cfg.min_value) / cfg.gamma_log).astype(
        np.int64) + 1
    b = np.clip(b, 1, cfg.n_bins - 1)
    return np.where(v < cfg.min_value, 0, b)


def quantile_estimate_np(hist: np.ndarray, q: float,
                         cfg: QuantileConfig) -> float:
    total = hist.sum()
    if total == 0:
        return 0.0
    cdf = np.cumsum(hist)
    idx = int(np.searchsorted(cdf, q * total, side="left"))
    idx = min(idx, cfg.n_bins - 1)
    if idx == 0:
        return 0.0
    log_lo = (idx - 1.0) * cfg.gamma_log
    return float(cfg.min_value * math.exp(log_lo + 0.5 * cfg.gamma_log))


# ---- session state ---------------------------------------------------------

@dataclass
class _Session:
    start: int
    end: int                      # last record ts
    accs: dict[str, Any] = field(default_factory=dict)


def _acc_init(agg: AggSpec, hll: HLLConfig, qcfg: QuantileConfig):
    if agg.kind in (AggKind.COUNT_ALL, AggKind.COUNT):
        return 0
    if agg.kind in (AggKind.SUM,):
        return 0.0
    if agg.kind == AggKind.AVG:
        return (0.0, 0)
    if agg.kind == AggKind.MIN:
        return math.inf
    if agg.kind == AggKind.MAX:
        return -math.inf
    if agg.kind == AggKind.APPROX_COUNT_DISTINCT:
        return np.zeros(hll.m, dtype=np.int8)
    if agg.kind == AggKind.APPROX_QUANTILE:
        return np.zeros(qcfg.n_bins, dtype=np.int64)
    if agg.kind in (AggKind.TOPK, AggKind.TOPK_DISTINCT):
        return []  # descending value list, trimmed to k
    raise SQLCodegenError(f"session agg {agg.kind} unsupported")


def _acc_merge(agg: AggSpec, a, b):
    if agg.kind in (AggKind.COUNT_ALL, AggKind.COUNT, AggKind.SUM):
        return a + b
    if agg.kind == AggKind.AVG:
        return (a[0] + b[0], a[1] + b[1])
    if agg.kind == AggKind.MIN:
        return min(a, b)
    if agg.kind == AggKind.MAX:
        return max(a, b)
    if agg.kind == AggKind.APPROX_COUNT_DISTINCT:
        return np.maximum(a, b)
    if agg.kind == AggKind.APPROX_QUANTILE:
        return a + b
    if agg.kind == AggKind.TOPK:
        from hstream_tpu.engine.lattice import agg_width

        return sorted(a + b, reverse=True)[: agg_width(agg)]
    if agg.kind == AggKind.TOPK_DISTINCT:
        from hstream_tpu.engine.lattice import agg_width

        return sorted(set(a) | set(b), reverse=True)[: agg_width(agg)]
    raise SQLCodegenError(f"session agg {agg.kind} unsupported")


class SessionExecutor:
    """Windowed-by-session grouped aggregation (host merge engine).

    API-compatible with QueryExecutor: process(rows, ts_ms) -> emitted
    rows; emitted rows carry winStart/winEnd = [session start,
    session end + gap) like the reference's session serde."""

    def __init__(self, node: AggregateNode, schema: Schema, *,
                 emit_changes: bool = False,
                 hll: HLLConfig = HLLConfig(),
                 qcfg: QuantileConfig = QuantileConfig()):
        if not isinstance(node.window, SessionWindow):
            raise SQLCodegenError("SessionExecutor needs a SessionWindow")
        self.node = node
        self.schema = schema
        self.window: SessionWindow = node.window
        self.emit_changes = emit_changes
        self.hll = hll
        self.qcfg = qcfg
        self.group_cols = [g.name for g in node.group_keys]
        self.aggs = list(node.aggs)
        self.watermark: int = -1
        # key tuple -> list[_Session], kept sorted by start
        self.sessions: dict[tuple, list[_Session]] = {}
        self._filter = QueryExecutor._extract_filter(self)  # same chain walk

    # QueryExecutor._extract_filter reads self.node only.

    def _agg_input(self, agg: AggSpec, row: Mapping[str, Any]):
        if agg.input is None:
            return 1
        try:
            v = eval_host(agg.input, row)
        except (TypeError, KeyError):
            return None
        if v is None or (isinstance(v, float) and not math.isfinite(v)):
            return None
        return v

    def _acc_update(self, agg: AggSpec, acc, v):
        if agg.kind == AggKind.COUNT_ALL:
            return acc + 1
        if v is None:
            return acc
        if agg.kind == AggKind.COUNT:
            return acc + 1
        if agg.kind == AggKind.SUM:
            return acc + float(v)
        if agg.kind == AggKind.AVG:
            return (acc[0] + float(v), acc[1] + 1)
        if agg.kind == AggKind.MIN:
            return min(acc, float(v))
        if agg.kind == AggKind.MAX:
            return max(acc, float(v))
        if agg.kind == AggKind.APPROX_COUNT_DISTINCT:
            reg, rank = hll_update_np(np.asarray([float(v)]), self.hll)
            acc = acc.copy()
            acc[reg[0]] = max(acc[reg[0]], rank[0])
            return acc
        if agg.kind == AggKind.APPROX_QUANTILE:
            b = int(quantile_bin_np(np.asarray([float(v)]), self.qcfg)[0])
            acc = acc.copy()
            acc[b] += 1
            return acc
        if agg.kind in (AggKind.TOPK, AggKind.TOPK_DISTINCT):
            return _acc_merge(agg, acc, [float(v)])
        raise SQLCodegenError(f"session agg {agg.kind} unsupported")

    def process(self, rows: Sequence[Mapping[str, Any]],
                ts_ms: Sequence[int]) -> list[dict[str, Any]]:
        if not rows:
            return []
        gap = self.window.gap_ms
        grace = self.window.grace_ms
        touched: set[tuple] = set()
        order = sorted(range(len(rows)), key=lambda i: ts_ms[i])
        for i in order:
            row, ts = rows[i], int(ts_ms[i])
            if self._filter is not None:
                try:
                    if not eval_host(self._filter, row):
                        continue
                except (TypeError, KeyError):
                    continue
            key = canon_key(tuple(row.get(c) for c in self.group_cols))
            sess_list = self.sessions.setdefault(key, [])
            # find sessions overlapping [ts - gap, ts + gap]
            overl = [s for s in sess_list
                     if s.start - gap <= ts <= s.end + gap]
            # Late-record policy (reference merge-on-overlap,
            # SessionWindowedStream.hs:84-118): drop only when the record
            # is past grace AND cannot merge into any still-open session.
            if (not overl and self.watermark >= 0
                    and ts + gap + grace <= self.watermark):
                continue
            if overl:
                merged = overl[0]
                for s in overl[1:]:
                    merged.end = max(merged.end, s.end)
                    merged.start = min(merged.start, s.start)
                    for a in self.aggs:
                        merged.accs[a.out_name] = _acc_merge(
                            a, merged.accs[a.out_name], s.accs[a.out_name])
                    sess_list.remove(s)
                merged.start = min(merged.start, ts)
                merged.end = max(merged.end, ts)
                target = merged
            else:
                target = _Session(start=ts, end=ts, accs={
                    a.out_name: _acc_init(a, self.hll, self.qcfg)
                    for a in self.aggs})
                sess_list.append(target)
                sess_list.sort(key=lambda s: s.start)
            for a in self.aggs:
                target.accs[a.out_name] = self._acc_update(
                    a, target.accs[a.out_name],
                    self._agg_input(a, row))
            touched.add(key)
        new_wm = max(int(t) for t in ts_ms)
        if new_wm > self.watermark:
            self.watermark = new_wm

        out: list[dict[str, Any]] = []
        if self.emit_changes:
            for key in touched:
                for s in self.sessions.get(key, []):
                    r = self._emit_row(key, s)
                    if r is not None:
                        out.append(r)
        out.extend(self.close_due_sessions())
        return out

    def close_due_sessions(self) -> list[dict[str, Any]]:
        # A session may only close once no acceptable future record can
        # still merge into it. Acceptable records have ts > wm-gap-grace
        # (the in-grace gate) and merge into s when ts <= s.end + gap, so
        # the session is safe to close when wm >= end + 2*gap + grace.
        # The reference never eagerly deletes session state
        # (SessionWindowedStream.hs:84-118); closing one gap-width later
        # preserves its merge-on-overlap semantics while still emitting.
        gap, grace = self.window.gap_ms, self.window.grace_ms
        rows = []
        for key, sess_list in list(self.sessions.items()):
            due = [s for s in sess_list
                   if s.end + 2 * gap + grace <= self.watermark]
            for s in due:
                if not self.emit_changes:
                    rows.append(self._emit_row(key, s))
                sess_list.remove(s)
            if not sess_list:
                del self.sessions[key]
        return [r for r in rows if r is not None]

    def _finalize(self, agg: AggSpec, acc):
        if agg.kind == AggKind.AVG:
            return acc[0] / max(acc[1], 1)
        if agg.kind == AggKind.MIN:
            return 0.0 if acc == math.inf else acc
        if agg.kind == AggKind.MAX:
            return 0.0 if acc == -math.inf else acc
        if agg.kind == AggKind.APPROX_COUNT_DISTINCT:
            return int(round(hll_estimate_np(acc, self.hll)))
        if agg.kind == AggKind.APPROX_QUANTILE:
            return quantile_estimate_np(acc, agg.quantile or 0.5, self.qcfg)
        if agg.kind in (AggKind.TOPK, AggKind.TOPK_DISTINCT):
            return list(acc)
        return acc

    def _emit_row(self, key: tuple, s: _Session) -> dict[str, Any] | None:
        row = dict(zip(self.group_cols, key))
        for a in self.aggs:
            row[a.out_name] = self._finalize(a, s.accs[a.out_name])
        row["winStart"] = s.start
        row["winEnd"] = s.end + self.window.gap_ms
        if self.node.having is not None:
            try:
                if not eval_host(self.node.having, row):
                    return None
            except (TypeError, KeyError):
                return None
        if self.node.post_projections:
            proj = {}
            for name, expr in self.node.post_projections:
                proj[name] = eval_host(expr, row)
            for meta in ("winStart", "winEnd"):
                proj[meta] = row[meta]
            return proj
        return row

    def peek(self) -> list[dict[str, Any]]:
        rows = []
        for key, sess_list in self.sessions.items():
            for s in sess_list:
                r = self._emit_row(key, s)
                if r is not None:
                    rows.append(r)
        return rows
