"""Double-buffered ingest pipeline: encode on a worker thread, step on
the caller's thread, in strict batch order.

The executor's hot loop has two host-side phases per micro-batch:
  1. wire-encode (numpy bit-packing) + host->device upload
  2. jitted step dispatch + window bookkeeping
Phase 1 is pure w.r.t. engine state (the wire codec's adaptive state is
owned by the encoder thread; batch order is preserved end-to-end), so it
overlaps with phase 2 of earlier batches — upload of batch i+1 rides the
link while batch i's scatter runs on the device. The reference has no
analogue (its poll loop is strictly serial — Processor.hs:99-144); on
TPU the overlap matters because the host->device link is the ingest
bottleneck.

Usage:
    pipe = IngestPipeline(executor, depth=4)
    emitted += pipe.submit(kids, ts_ms, cols)   # may return earlier
    emitted += pipe.flush()                     # batches' emissions
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Mapping

import numpy as np


class IngestPipeline:
    """Pipelines stage_columnar (worker thread) with process_staged
    (caller thread) for one QueryExecutor. Not thread-safe itself: one
    producer calls submit()/flush()."""

    def __init__(self, executor, depth: int = 4):
        self._ex = executor
        self._in: queue.Queue = queue.Queue(maxsize=depth)
        self._staged: queue.Queue = queue.Queue()
        self._pending = 0          # batches submitted but not yet processed
        self._dead = False         # worker exited (error or close())
        self._err: BaseException | None = None
        self._worker = threading.Thread(target=self._encode_loop,
                                        daemon=True)
        self._worker.start()

    def _encode_loop(self) -> None:
        while True:
            item = self._in.get()
            if item is None:
                self._staged.put(None)
                return
            try:
                kids, ts, cols, nulls = item
                self._staged.put(self._ex.stage_columnar(kids, ts, cols,
                                                         nulls))
            except BaseException as e:  # surfaced on the caller thread
                self._err = e
                self._staged.put(None)
                return

    @property
    def pending(self) -> int:
        """Batches submitted but not yet processed."""
        return self._pending

    def _raise_worker_error(self) -> None:
        if self._err is not None:
            err, self._err = self._err, None
            raise err

    def _process_one(self, block: bool) -> list[dict[str, Any]] | None:
        """Process one staged batch if available; None when none ready."""
        try:
            staged = self._staged.get(block=block)
        except queue.Empty:
            return None
        if staged is None:  # worker exit sentinel (error or close())
            self._dead = True
            self._raise_worker_error()
            return []
        self._pending -= 1
        return self._ex.process_staged(staged)

    def submit(self, key_ids: np.ndarray, ts_ms: np.ndarray,
               cols: Mapping[str, np.ndarray],
               nulls: Mapping[str, np.ndarray] | None = None,
               ) -> list[dict[str, Any]]:
        """Enqueue one micro-batch; processes any batches whose encode
        already finished and returns their emitted rows (rows therefore
        lag submission by the pipeline depth — call flush() for a
        barrier)."""
        self._raise_worker_error()
        if self._dead:
            raise RuntimeError("ingest pipeline worker has exited")
        out: list[dict[str, Any]] = []
        # backpressure: when the encoder is depth behind, block for one
        block = self._in.full()
        while True:
            rows = self._process_one(block)
            if rows is None:
                break
            out.extend(rows)
            block = False
        cap = self._ex.batch_capacity
        for i in range(0, len(key_ids), cap):
            sl = slice(i, i + cap)
            self._in.put((np.asarray(key_ids)[sl],
                          np.asarray(ts_ms)[sl],
                          {k: np.asarray(v)[sl] for k, v in cols.items()},
                          None if nulls is None else
                          {k: np.asarray(v)[sl] for k, v in nulls.items()}))
            self._pending += 1
        return out

    def flush(self) -> list[dict[str, Any]]:
        """Barrier: wait until every submitted batch is staged and
        processed; returns their emitted rows."""
        out: list[dict[str, Any]] = []
        while self._pending > 0:
            if self._dead:
                raise RuntimeError(
                    "ingest pipeline worker died with batches pending")
            rows = self._process_one(block=True)
            if rows is not None:
                out.extend(rows)
        return out

    def close(self) -> None:
        if self._worker.is_alive():
            try:
                # a worker that died with a full input queue never
                # drains it — a plain put() would hang this thread
                self._in.put(None, timeout=5)
            except queue.Full:
                pass
        self._worker.join(timeout=5)
