"""Overlapped ingest pipeline: a pool of host-encode workers feeding a
bounded staging ring, stepped in strict batch order on the caller's
thread.

The executor's hot loop has three host-side phases per micro-batch:
  1. wire-encode (numpy/native bit-packing)
  2. host->device upload (async device_put, double-buffered)
  3. jitted step dispatch + window bookkeeping + change drain
Phases 1-2 are pure w.r.t. engine state (the wire codec's adaptive
state tolerates out-of-order planning — every batch's combo/bases/words
triple is self-consistent; see transport.BitpackTransport) AND kernel-
dispatch/fetch-free (executor.stage_columnar declares `# contract:
dispatches<=0 fetches<=0`, checked by the tools/analyze dispatch pass
— a sync on a worker thread would serialize the overlap this pipeline
exists for), so N encode workers overlap with the ordered step
dispatches of earlier batches:
batch i+2 encodes on one worker while batch i+1's upload rides the link
and batch i's scatter runs on the device. Order is restored by sequence
tags: workers deposit staged batches into a reorder ring and the caller
consumes them strictly in submission order, so watermarks, window
closes, and emitted rows are identical to the synchronous path.

The reference has no analogue (its poll loop is strictly serial —
Processor.hs:99-144); on TPU the overlap matters because host encode
and the host->device link, not device FLOPs, bound ingest.

Usage:
    pipe = IngestPipeline(executor, depth=4, workers=2)
    emitted += pipe.submit(kids, ts_ms, cols)   # may return earlier
    emitted += pipe.flush()                     # barrier: all batches
    pipe.stats()                                # per-stage occupancy
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Mapping

import numpy as np

from hstream_tpu.common.columnar import extend_rows


class IngestPipeline:
    """Pipelines stage_columnar (worker pool) with process_staged
    (caller thread) for one QueryExecutor. Not thread-safe itself: one
    producer calls submit()/flush()."""

    def __init__(self, executor, depth: int = 4, workers: int = 1):
        self._ex = executor
        self.depth = max(int(depth), 1)
        self.workers = max(int(workers), 1)
        # bounded staging ring: (seq, batch) items; blocking put() is the
        # backpressure when encode falls `depth` behind
        self._in: queue.Queue = queue.Queue(maxsize=self.depth)
        # reorder buffer: seq -> StagedBatch | _WorkerError; the caller
        # pops strictly in sequence order
        self._ready: dict[int, Any] = {}
        self._cond = threading.Condition()
        self._next_seq = 0         # next sequence tag to assign
        self._take_seq = 0         # next sequence the caller processes
        self._live_workers = self.workers
        self._dead = False         # a worker error was delivered
        self._closed = False
        # per-stage busy-seconds (encode is summed across workers; wall
        # starts at construction) — bench/tracing read stats()
        self._t0 = time.perf_counter()
        self._stat_lock = threading.Lock()
        self._busy = {"encode_s": 0.0, "step_s": 0.0}
        self._threads = [
            threading.Thread(target=self._encode_loop, daemon=True,
                             name=f"ingest-enc-{i}")
            for i in range(self.workers)
        ]
        for t in self._threads:
            t.start()

    # ---- encode workers ----------------------------------------------------

    def _encode_loop(self) -> None:
        while True:
            try:
                item = self._in.get(timeout=0.5)
            except queue.Empty:
                if self._closed:
                    break
                continue
            if item is None:  # wake-up sentinel from close()
                break
            seq, (kids, ts, cols, nulls) = item
            try:
                t0 = time.perf_counter()
                staged = self._ex.stage_columnar(kids, ts, cols, nulls)
                with self._stat_lock:
                    self._busy["encode_s"] += time.perf_counter() - t0
            except BaseException as e:  # surfaced in order on the caller
                staged = _WorkerError(e)
            with self._cond:
                self._ready[seq] = staged
                self._cond.notify_all()
        with self._cond:
            self._live_workers -= 1
            self._cond.notify_all()

    # ---- ordered consumption (caller thread) -------------------------------

    @property
    def pending(self) -> int:
        """Batches submitted but not yet processed."""
        return self._next_seq - self._take_seq

    def _process_one(self, block: bool) -> list[dict[str, Any]] | None:
        """Process the next staged batch in sequence order; None when it
        is not staged yet (non-blocking mode) or nothing is pending."""
        if self._take_seq >= self._next_seq:
            return None
        seq = self._take_seq
        with self._cond:
            while seq not in self._ready:
                if not block:
                    return None
                if self._live_workers <= 0:
                    raise RuntimeError(
                        "ingest pipeline workers died with batches "
                        "pending")
                self._cond.wait(0.5)
            staged = self._ready.pop(seq)
        self._take_seq = seq + 1
        if isinstance(staged, _WorkerError):
            self._dead = True
            raise staged.err
        t0 = time.perf_counter()
        rows = self._ex.process_staged(staged)
        with self._stat_lock:
            self._busy["step_s"] += time.perf_counter() - t0
        return rows

    def submit(self, key_ids: np.ndarray, ts_ms: np.ndarray,
               cols: Mapping[str, np.ndarray],
               nulls: Mapping[str, np.ndarray] | None = None,
               ) -> list[dict[str, Any]]:
        """Enqueue one micro-batch; processes any batches whose encode
        already finished and returns their emitted rows (rows therefore
        lag submission by the pipeline depth — call flush() for a
        barrier)."""
        if self._dead or self._closed:
            raise RuntimeError("ingest pipeline worker has exited")
        # rows accumulate via extend_rows so a lone columnar close
        # batch (engine ColumnarEmit) reaches the sink unmaterialized
        out: Any = None
        # backpressure: when the encoders are depth behind, block for one
        block = self._in.full()
        while True:
            rows = self._process_one(block)
            if rows is None:
                break
            out = extend_rows(out, rows)
            block = False
        key_ids = np.asarray(key_ids)
        if len(key_ids) and self._ex.epoch is None:
            # anchor the epoch HERE, in submission order: with several
            # encode workers the first batch to finish staging is not
            # necessarily the first submitted, and an epoch anchored to
            # a later batch would push earlier records negative-relative
            self._ex._ensure_epoch(int(np.min(np.asarray(ts_ms))))
        cap = self._ex.batch_capacity
        for i in range(0, len(key_ids), cap):
            sl = slice(i, i + cap)
            item = (key_ids[sl], np.asarray(ts_ms)[sl],
                    {k: np.asarray(v)[sl] for k, v in cols.items()},
                    None if nulls is None else
                    {k: np.asarray(v)[sl] for k, v in nulls.items()})
            seq = self._next_seq
            self._next_seq = seq + 1
            while True:
                try:
                    self._in.put((seq, item), timeout=0.5)
                    break
                except queue.Full:
                    # ring full AND nothing staged yet: keep draining so
                    # a stalled worker cannot deadlock the producer
                    rows = self._process_one(block=False)
                    if rows is not None:
                        out = extend_rows(out, rows)
        return out if out is not None else []

    def flush(self) -> list[dict[str, Any]]:
        """Barrier: wait until every submitted batch is staged and
        processed; returns their emitted rows."""
        if self._dead:
            raise RuntimeError("ingest pipeline worker has exited")
        out: Any = None
        while self.pending > 0:
            rows = self._process_one(block=True)
            if rows is not None:
                out = extend_rows(out, rows)
        return out if out is not None else []

    def stats(self) -> dict[str, float]:
        """Per-stage busy seconds + occupancy since construction.
        encode: worker-pool time in stage_columnar (wire pack + upload
        dispatch, summed over workers); step: caller time in
        process_staged (step dispatch + window bookkeeping + inline
        drains). The executor contributes upload-wait and change-drain
        counters when it tracks them (executor.stage_stats)."""
        wall = max(time.perf_counter() - self._t0, 1e-9)
        with self._stat_lock:
            out = dict(self._busy)
        for k, v in getattr(self._ex, "stage_stats", {}).items():
            out[k] = out.get(k, 0.0) + v
        out["wall_s"] = wall
        out["encode_occupancy"] = min(
            out.get("encode_s", 0.0) / (wall * self.workers), 1.0)
        out["step_occupancy"] = min(out.get("step_s", 0.0) / wall, 1.0)
        if "drain_s" in out:
            out["drain_occupancy"] = min(out["drain_s"] / wall, 1.0)
        return out

    def reset_stats(self) -> None:
        """Zero the per-stage counters and restart the wall clock (call
        after warmup so occupancies reflect the steady state only)."""
        with self._stat_lock:
            self._busy = {"encode_s": 0.0, "step_s": 0.0}
        ex_stats = getattr(self._ex, "stage_stats", None)
        if ex_stats is not None:
            lock = getattr(self._ex, "_stats_lock", None)
            if lock is not None:
                with lock:
                    for k in ex_stats:
                        ex_stats[k] = 0.0
            else:
                for k in ex_stats:
                    ex_stats[k] = 0.0
        self._t0 = time.perf_counter()

    def close(self) -> None:
        """Teardown, not a flush barrier: workers exit after their
        current batch. The _closed flag is the authoritative stop
        signal (workers poll it on an idle queue); the None sentinels
        are best-effort wake-ups only, so a full queue cannot strand a
        worker."""
        if self._closed:
            return
        self._closed = True
        for _ in self._threads:
            try:
                self._in.put_nowait(None)
            except queue.Full:
                break  # workers notice _closed within their poll tick
        for t in self._threads:
            t.join(timeout=5)


class _WorkerError:
    """A worker exception, delivered at its batch's turn so errors
    surface in submission order."""

    def __init__(self, err: BaseException):
        self.err = err
