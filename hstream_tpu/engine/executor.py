"""Host-side query executor: drives the jitted lattice step.

Responsibilities (the reference spreads these across runTask's polling
loop and the aggregate processors — Processor.hs:99-144,
TimeWindowedStream.hs:82-103):

  * columnarize decoded JSON rows into padded HostBatches
  * maintain the group-key dictionary (tuple of group values <-> dense id)
  * maintain the time epoch: device time = int32 ms relative to `epoch`,
    re-anchored (rebase) long before int32 overflow
  * track the watermark (max event time seen = the reference's
    `observedStreamTime`) and the set of open windows ON HOST, so the
    device step never syncs back per batch
  * when the watermark passes win_end + grace: extract + reset that slot
    (window close), finalize, decode keys, apply HAVING + projections
  * EMIT CHANGES mode: additionally extract touched (key, window) pairs
    after each batch (one change per touched pair per micro-batch — the
    batched analogue of the reference's per-record emission)

The executor is single-threaded per query, like the reference's one green
thread per task; concurrency comes from running many executors and from
the device pipelining enqueued steps.
"""

from __future__ import annotations

import itertools
import math
import threading
import time
from collections import deque
from concurrent import futures
from dataclasses import dataclass
from typing import Any, Callable, Mapping, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from hstream_tpu.common.columnar import ColumnarEmit, extend_rows
from hstream_tpu.common.errors import SQLCodegenError
from hstream_tpu.common.faultinject import FAULTS
from hstream_tpu.common.logger import get_logger
from hstream_tpu.common.tracing import kernel_family
from hstream_tpu.engine import lattice, transport
from hstream_tpu.engine.expr import (
    BinOp,
    Col,
    Expr,
    columns_of,
    encode_strings,
    eval_host,
    eval_host_vec,
)
from hstream_tpu.engine.plan import AggKind, AggregateNode, AggSpec
from hstream_tpu.engine.types import (
    ColumnType,
    HostBatch,
    Schema,
    StringDictionary,
    canon_key,
    round_up_pow2,
)
from hstream_tpu.engine.window import FixedWindow, SessionWindow

log = get_logger("executor")

REBASE_THRESHOLD = 1 << 30  # re-anchor epoch when relative time passes this

EmitFn = Callable[[list[dict[str, Any]]], None]

# Shared device->host change-drain workers: ONE small pool for every
# executor in the process, so N concurrent queries batch their blocking
# D2H fetches onto drain threads instead of each stalling its own task
# loop. 2 workers: one fetch can ride the link while another decodes.
_DRAIN_POOL: futures.ThreadPoolExecutor | None = None
_DRAIN_POOL_LOCK = threading.Lock()


def _change_drain_pool() -> futures.ThreadPoolExecutor:
    global _DRAIN_POOL
    with _DRAIN_POOL_LOCK:
        if _DRAIN_POOL is None:
            _DRAIN_POOL = futures.ThreadPoolExecutor(
                max_workers=2, thread_name_prefix="change-drain")
        return _DRAIN_POOL


def _align_down(ts: int, step: int) -> int:
    return ts - (ts % step)


# Per-instance read-version nonces (ISSUE 20): a restored/rebuilt
# executor must never alias a predecessor's version tuple, so every
# instance draws a process-unique id at construction.
_READ_NONCE = itertools.count(1)


@dataclass
class _OpenWindow:
    start_abs: int  # absolute ms
    slot: int


@dataclass
class StagedBatch:
    """A micro-batch encoded (and optionally uploaded) ahead of its step
    dispatch — the unit of work between the ingest pipeline's encoder
    thread and the executor's ordered step loop. Host copies are kept so
    rare control-flow (gap split, rebase, epoch change) can fall back to
    the synchronous path."""

    n: int
    cap: int
    combo: Any
    bases: Any                      # np.int32 [n_streams] per-stream bases
    words: Any                      # np.ndarray or device array
    epoch: int
    ts_min: int
    ts_max: int
    key_ids: np.ndarray
    ts_ms: np.ndarray
    cols: Mapping[str, np.ndarray]
    nulls: Mapping[str, np.ndarray] | None


class QueryExecutor:
    """Executes one windowed/global GROUP BY aggregation plan."""

    # whether _drain_changes honors defer_change_decode (subclasses with
    # their own drain path override this capability)
    supports_deferred_changes = True

    def __init__(
        self,
        node: AggregateNode,
        schema: Schema,
        *,
        emit_changes: bool = True,
        initial_keys: int = 1024,
        batch_capacity: int = 4096,
    ):
        if isinstance(node.window, SessionWindow):
            raise SQLCodegenError("session windows use SessionExecutor")
        self.node = node
        self.schema = schema
        self.emit_changes = emit_changes
        self.batch_capacity = batch_capacity

        # group keys must be plain columns (validated upstream)
        self.group_cols: list[str] = []
        for k in node.group_keys:
            if not isinstance(k, Col):
                raise SQLCodegenError("GROUP BY supports plain columns")
            self.group_cols.append(k.name)

        self.window: FixedWindow | None = node.window
        self.dicts: dict[str, StringDictionary] = {
            name: StringDictionary() for name, t in schema.fields
            if t == ColumnType.STRING
        }

        self._key_ids: dict[tuple, int] = {}
        self._key_rev: list[tuple] = []

        # Pre-encode string literals (fills the column dictionaries) so the
        # expressions are hashable and compiled functions can be shared.
        encoded_aggs = []
        for agg in node.aggs:
            if agg.input is not None:
                agg = AggSpec(kind=agg.kind, out_name=agg.out_name,
                              input=encode_strings(agg.input, schema, self.dicts),
                              quantile=agg.quantile, k=agg.k)
            encoded_aggs.append(agg)
        self._filter_expr = self._extract_filter()
        if self._filter_expr is not None:
            self._filter_expr = encode_strings(
                self._filter_expr, schema, self.dicts)

        # columns the device step actually needs
        needed = set()
        for agg in encoded_aggs:
            if agg.input is not None:
                needed |= columns_of(agg.input)
        if self._filter_expr is not None:
            needed |= columns_of(self._filter_expr)
        self._needed_cols = sorted(needed)

        self.spec = lattice.LatticeSpec(
            n_keys=initial_keys, window=self.window,
            aggs=tuple(encoded_aggs), track_touched=emit_changes)
        self.state = lattice.init_state(self.spec)
        # sticky adaptive wire codec; survives recompiles (key growth).
        # The lock serializes encode() between an IngestPipeline encoder
        # thread and synchronous fallbacks on the caller thread.
        self._transport = transport.BitpackTransport()
        self._transport_lock = threading.Lock()
        self._null_sticky: set[str] = set()  # null streams once seen
        self._compile()

        self.epoch: int | None = None        # absolute ms anchor, advance-aligned
        self.watermark_abs: int = -1
        self._open: dict[int, _OpenWindow] = {}  # start_abs -> window
        # Window starts whose closure is deferred until the next process()
        # call: populated by the gap-split path so a stream-time jump inside
        # a batch cannot close (and emit) windows that records earlier in
        # the same batch just aggregated into.
        self._no_close: set[int] = set()
        # window starts that received records during the current process()
        # call (populated by _track_windows, cleared per call)
        self._touched_this_call: set[int] = set()
        self.rebase_threshold = REBASE_THRESHOLD
        # Deferred close decode: when True, closing a window dispatches
        # the fused extract+reset on device but keeps the packed result
        # as a device value; drain_closed() decodes them later. This
        # keeps the hot ingest loop free of forced device->host syncs
        # (pull-based emission — the TPU analogue of the reference's
        # sink append). Each entry is (window starts, packed [P,rows,K]).
        self.defer_close_decode = False
        self._pending_closes: list[tuple[list[int], Any]] = []
        # close-path dispatch accounting: the fused close contract is
        # ONE lattice-kernel dispatch and (outside changelog mode) ONE
        # device->host fetch per close cycle, regardless of how many
        # windows are due — tests and bench assert on these
        self.close_stats = {"close_cycles": 0, "close_dispatches": 0,
                            "close_fetches": 0}
        # fused-close health: a fused kernel failure (activation /
        # compile / injected fault) permanently degrades THIS executor
        # to the retained per-slot reference close; the query task
        # mirrors device_fallbacks into device_path_fallbacks
        self._fused_close_ok = True
        self.device_fallbacks = 0
        # cached reverse key-index columns for vectorized key decode:
        # (version = len(_key_rev) when built, [object array per group
        # column]); _key_rev is append-only so a stale cache is only
        # ever too short
        self._key_cols_cache: tuple[int, list[np.ndarray]] = (0, [])
        # Deferred CHANGE decode (emit_changes mode): keep the touched
        # extract as a device value and decode it one batch later, so
        # the blocking device->host fetch overlaps the next batch's host
        # work instead of stalling the loop (matters on high-RTT links).
        # Changes then lag emission by one micro-batch; flush_changes()
        # drains the tail.
        self._caps_used: set[int] = set()  # compiled staged-step shapes
        self._caps_lock = threading.Lock()
        self.defer_change_decode = False
        # how many change extracts may queue before a batched fetch; >1
        # amortizes the device->host round trip over many micro-batches
        # (changelog rows then lag ingest by up to `depth` batches)
        self.change_drain_depth = 1
        self._pending_changes: list[Any] = []
        # Async change drain: batched change fetches run on the shared
        # drain pool instead of the caller's thread, so the D2H round
        # trip overlaps later batches' encode/step work entirely. Rows
        # are collected strictly in submission order (FIFO head-pop),
        # so emitted change order matches the synchronous path.
        self.async_change_drain = False
        self._drain_futs: deque = deque()
        # double-buffered device staging: at most upload_slots H2D
        # transfers in flight; staging a batch past that waits on the
        # OLDEST outstanding transfer (classic double-buffer handoff)
        self.upload_slots = 2
        self._upload_ring: deque = deque()
        self._upload_lock = threading.Lock()
        # per-stage busy-seconds shared with IngestPipeline.stats()
        self.stage_stats: dict[str, float] = {"upload_wait_s": 0.0,
                                              "drain_s": 0.0}
        self._stats_lock = threading.Lock()
        # observability plane (ISSUE 13), all host-mirror values the
        # owning task mirrors into /metrics: per-family dispatch-time
        # observer (None = one branch per dispatch), late-record drops
        # (the host twin of the device's watermark mask), and H2D/D2H
        # byte totals on the staging and stacked-drain paths
        self.dispatch_observer = None   # callable (family, seconds)
        self.late_drops = 0
        self.transfer_stats = {"h2d_bytes": 0, "d2h_bytes": 0}
        # read-plane versioning (ISSUE 20): read_epoch bumps at every
        # state-mutating choke point (step dispatch, window close), so
        # (nonce, read_epoch, close_cycles, watermark) is an exact key
        # for "would peek() return the same rows". Plain int writes —
        # readers may sample it lock-free; a torn read can only cause a
        # spurious cache miss, never a stale hit.
        self.read_epoch = 0
        self._read_nonce = next(_READ_NONCE)

    def _extract_filter(self) -> Expr | None:
        # Walk the child chain down to the source, ANDing every FilterNode
        # predicate; reject node types this executor cannot honor so a
        # malformed plan fails loudly instead of silently skipping filters.
        from hstream_tpu.engine.plan import FilterNode, SourceNode

        pred: Expr | None = None
        child = self.node.child
        while not isinstance(child, SourceNode):
            if isinstance(child, FilterNode):
                pred = child.predicate if pred is None else \
                    BinOp("AND", pred, child.predicate)
                child = child.child
            else:
                raise SQLCodegenError(
                    f"aggregate over unsupported child node "
                    f"{type(child).__name__}")
        return pred

    def _compile(self) -> None:
        n_per = self.spec.windows_per_record
        self._layout = tuple(
            (name, lattice.layout_tag(self.schema.type_of(name)))
            for name in self._needed_cols)
        # changelog extraction is bounded by the touched-pair space
        # (n_keys * n_slots), usually far below batch capacity — keeps
        # the per-batch device->host extract buffer small
        max_out = min(self.batch_capacity * n_per,
                      self.spec.n_keys * self.spec.n_slots)
        fns = lattice.compiled(self.spec, self.schema, self._filter_expr,
                               max_out, self._layout)
        # close-path kernels are wrapped so close_stats counts ACTUAL
        # device dispatches at the call sites — a reintroduced
        # per-slot close loop shows up as dispatches > cycles
        self._extract_slot = self._count_close_kernel(fns.extract_slot)
        self._reset_slot = self._count_close_kernel(fns.reset_slot)
        self._extract_reset_slots = self._count_close_kernel(
            fns.extract_reset_slots)
        self._extract_slots = fns.extract_slots  # peek: read path
        self._reset_slots = self._count_close_kernel(fns.reset_slots)
        self._extract_touched = fns.extract_touched
        # (null-flag stream name, referenced columns) per null-tracked agg
        self._null_specs = [
            (key, sorted(columns_of(agg.input)))
            for key, agg in zip(fns.null_keys, self.spec.aggs)
            if key is not None
        ]

    def _count_close_kernel(self, fn):
        """Wrap a close-path lattice kernel so every device dispatch
        bumps close_stats — the accounting the one-dispatch-per-cycle
        contract is asserted against (tests/test_close_batched.py)."""

        def counted(*args):
            self.close_stats["close_dispatches"] += 1
            res = None

            def _ready():  # the kernel result once the body ran
                return self.state if res is None else res

            with kernel_family("close", self.dispatch_observer,
                               ready=_ready):
                res = fn(*args)
            return res

        return counted

    # ---- device cost plane (ISSUE 18) --------------------------------------

    # contract: dispatches<=0 fetches<=0
    def _device_values(self):
        """The executor's live device arrays — the fence/measure target
        of the device-time sampler (a zero-arg late binding: self.state
        is REPLACED by every step/close dispatch)."""
        return self.state

    # contract: dispatches<=0 fetches<=0
    def device_plane_bytes(self) -> dict[str, int]:
        """Exact per-plane device bytes of the live lattice state —
        nbytes metadata reads only, zero dispatches, zero fetches."""
        from hstream_tpu.stats.devicecost import plane_bytes

        return plane_bytes(self.state)

    # contract: dispatches<=1 fetches<=0
    def _run_step(self, cap: int, n: int, key_ids, ts_rel, cols,
                  valid, null_streams, wm_rel) -> None:
        """Encode one micro-batch with the v2 wire codec and dispatch the
        jitted (decode+scatter) step. Null streams, once seen, stay on the
        wire (sticky) so the encoding combo — and the compiled executable
        — is stable batch-to-batch."""
        if FAULTS.active:  # chaos: fail/delay a staged step dispatch
            FAULTS.point("device.dispatch")
        self.read_epoch += 1
        combo, bases, words = self._encode_locked(
            cap, n, key_ids, ts_rel, cols, valid, null_streams)
        step = lattice.compiled_encoded_step(
            self.spec, self.schema, self._filter_expr, combo, cap,
            donate_words=True)
        staged_words = self._device_stage(words)
        with kernel_family("step", self.dispatch_observer,
                           ready=self._device_values):
            self.state = step(self.state, wm_rel, np.int32(n), bases,
                              staged_words)

    def _encode_locked(self, cap, n, key_ids, ts_rel, cols, valid,
                       null_streams):
        """Wire-encode one batch. Only the sticky-null merge holds the
        transport lock (a concurrent add during iteration would throw);
        the encode itself runs UNLOCKED so a pool of pipeline encode
        workers packs batches in parallel — safe because every batch's
        (combo, bases, words) triple is self-consistent and the codec's
        adaptive state tolerates racy updates (transport.BitpackTransport
        thread-safety note). Null streams, once seen, stay on the wire
        (sticky) so the encoding combo — and the compiled executable —
        converges batch-to-batch."""
        with self._transport_lock:
            for nk in null_streams:
                self._null_sticky.add(nk)
            sticky = tuple(self._null_sticky)
        for nk in sticky:
            if nk not in null_streams:
                null_streams[nk] = np.zeros(n, dtype=np.bool_)
        return self._transport.encode(
            cap, n, key_ids, ts_rel, cols, self._layout,
            valid=valid, null_streams=null_streams)

    # ---- keys --------------------------------------------------------------

    def _key_id(self, row: Mapping[str, Any]) -> int:
        return self.key_id_for(tuple(row.get(c) for c in self.group_cols))

    def _grow_keys(self) -> None:
        new_k = self.spec.n_keys * 2
        self.state = lattice.grow_keys(self.state, self.spec, new_k)
        self.spec = lattice.LatticeSpec(
            n_keys=new_k, window=self.spec.window, aggs=self.spec.aggs,
            hll=self.spec.hll, qcfg=self.spec.qcfg,
            track_touched=self.spec.track_touched)
        self._compile()

    # ---- time --------------------------------------------------------------

    def _advance_step(self) -> int:
        return 1 if self.window is None else self.window.advance_ms

    def _ensure_epoch(self, min_ts: int) -> None:
        if self.epoch is None:
            # anchor so every window that can ever legally receive records
            # has a non-negative relative start: hopping windows reach back
            # size - advance before the first record, and out-of-order
            # records within the grace period reach back another
            # size + grace (window valid while start + size + grace > wm,
            # and the watermark only grows from the first batch's max).
            if self.window is None:
                back = 0
            else:
                w = self.window
                adv = w.advance_ms
                back = (w.size_ms - adv) + \
                    ((w.size_ms + w.grace_ms + adv - 1) // adv) * adv
            self.epoch = _align_down(min_ts, self._advance_step()) - back

    def _maybe_rebase(self, max_ts_abs: int) -> None:
        if self.epoch is None:
            return
        if max_ts_abs - self.epoch < self.rebase_threshold:
            return
        # Re-anchor at the oldest still-open window (or the watermark).
        # delta must be a multiple of advance * n_slots so the slot
        # mapping (start // advance) mod W of every open window is
        # preserved across the rebase.
        anchor = min([w.start_abs for w in self._open.values()]
                     + [self.watermark_abs if self.watermark_abs >= 0 else max_ts_abs])
        period = self._advance_step() * self.spec.n_slots
        delta = _align_down(anchor - self.epoch, period)
        if delta <= 0:
            return
        self.state = lattice.rebase(self.state, np.int32(delta))
        self.epoch = self.epoch + delta

    # ---- ingest ------------------------------------------------------------

    def process(self, rows: Sequence[Mapping[str, Any]],
                ts_ms: Sequence[int]) -> list[dict[str, Any]]:
        """Feed one micro-batch of decoded records; returns emitted rows."""
        if not rows:
            return []
        try:
            return self._process_batch(list(rows), list(ts_ms))
        finally:
            # deferred closes apply only within the call that deferred them
            self._no_close.clear()
            self._touched_this_call.clear()

    def _new_window_starts(self, ts_ms: Sequence[int]) -> set[int]:
        """Window starts this batch's records aggregate into (late ones
        — already past end+grace at the current watermark — excluded,
        matching the device mask).

        Fast path: when the batch's aligned time range is small (the
        steady state — a micro-batch spans a handful of advances), the
        candidate starts are simply every aligned value in
        [align(min)-back, align(max)] — O(range/advance), no scan of
        the 100k+ timestamps. Aligned values with no records just open
        empty windows that close without emitting (count>0 filter), so
        the overapproximation is semantics-free. Sparse/jumpy batches
        fall back to the exact np.unique scan."""
        w = self.window
        ts = np.asarray(ts_ms, dtype=np.int64)
        adv = w.advance_ms
        a_lo = int(ts.min())
        a_hi = int(ts.max())
        a_lo -= a_lo % adv
        a_hi -= a_hi % adv
        span = (a_hi - a_lo) // adv + 1
        back = w.windows_per_record - 1
        # tight gate: a sparse/gappy batch (few records over a wide time
        # range) must use the exact scan, or every aligned gap value
        # becomes a phantom open window tracked (and closed) on host
        if span + back <= min(self.spec.n_slots, 64):
            starts = np.arange(a_lo - back * adv, a_hi + adv, adv)
        else:
            latest = np.unique(ts - ts % adv)
            offs = np.arange(w.windows_per_record, dtype=np.int64) * adv
            starts = np.unique((latest[:, None] - offs[None, :]).ravel())
        if self.watermark_abs >= 0:
            starts = starts[starts + w.size_ms + w.grace_ms
                            > self.watermark_abs]
        return set(starts.tolist())

    def _gap_guard(self, ts_arr: np.ndarray, sub):
        """Gap/slot-collision guard, shared by the row and columnar paths.

        Window start s occupies lattice slot (s // advance) mod W, so two
        distinct live windows whose starts are congruent mod W*advance (a
        stream gap / restart jump) would alias the same slot.

        (a) Exact aliasing among (open windows ∪ this batch's windows):
            split the batch in time order at the first aliasing start and
            force-close only the open windows whose slot the suffix
            actually needs — such windows are provably past end+grace,
            since aliasing requires a gap of W*advance > size+grace.
        (b) A stream-time jump past the slot horizon (even without
            aliasing) defers closure of windows this call's records
            aggregated into until the next call: records within a batch
            are concurrent, so a far-future record must not retroactively
            finalize windows its batch-mates just updated. In-horizon
            progress still closes windows at end of batch as usual.

        `sub(idx)` recursively processes the records at positions `idx`
        (an int ndarray). Returns (emitted_rows, None) when the guard
        split the batch (case a), or (None, new_starts) when the caller
        should proceed — possibly after case (b) recorded deferred closes;
        new_starts is this batch's window-start set for _track_windows."""
        w = self.window
        period = w.advance_ms * self.spec.n_slots
        back = w.size_ms - w.advance_ms
        aligned_min = _align_down(int(ts_arr.min()), w.advance_ms) - back
        anchor = min(list(self._open) + [aligned_min])
        horizon = anchor + (self.spec.n_slots - 1) * w.advance_ms
        new_starts = self._new_window_starts(ts_arr)
        by_res: dict[int, list[int]] = {}
        for s in set(self._open) | new_starts:
            by_res.setdefault(s % period, []).append(s)
        colliding = [sorted(g) for g in by_res.values() if len(g) > 1]
        if colliding:
            cut = min(g[1] for g in colliding)  # first aliasing start
            pre = np.nonzero(ts_arr < cut)[0]
            suf = np.nonzero(ts_arr >= cut)[0]
            out = []
            if len(pre):
                out.extend(sub(pre))
            self._no_close |= set(self._open) & self._touched_this_call
            suf_ts = ts_arr[suf]
            suf_starts = self._new_window_starts(suf_ts)
            suf_res = {s % period for s in suf_starts}
            collide = [s for s in self._open
                       if s % period in suf_res and s not in suf_starts]
            if collide:
                # real closes, not early ones — see proof above; the
                # watermark advances to their close boundary so they
                # cannot reopen into a now-occupied slot
                boundary = max(s + w.size_ms + w.grace_ms for s in collide)
                if boundary > int(suf_ts.max()):
                    raise AssertionError(
                        "aliasing window not due — slot layout invariant "
                        "broken")
                self.watermark_abs = max(self.watermark_abs, boundary)
                out.extend(self._close_windows(sorted(collide)))
            out.extend(sub(suf))
            return out, None
        if int(ts_arr.max()) > horizon:
            self._no_close |= (set(self._open) & self._touched_this_call
                               ) | new_starts
        return None, new_starts

    def _process_batch(self, rows: list, ts_ms: list) -> list[dict[str, Any]]:
        if len(rows) > self.batch_capacity:
            out = []
            for i in range(0, len(rows), self.batch_capacity):
                out.extend(self._process_batch(
                    rows[i:i + self.batch_capacity],
                    ts_ms[i:i + self.batch_capacity]))
            return out

        batch_starts = None
        if self.window is not None:
            def sub(idx):
                return self._process_batch([rows[i] for i in idx],
                                           [ts_ms[i] for i in idx])

            guarded, batch_starts = self._gap_guard(
                np.asarray(ts_ms, dtype=np.int64), sub)
            if guarded is not None:
                return guarded

        self._ensure_epoch(min(ts_ms))
        self._maybe_rebase(max(ts_ms))

        n = len(rows)
        cap = round_up_pow2(n)
        key_ids = np.zeros(cap, dtype=np.int32)
        for i, row in enumerate(rows):
            key_ids[i] = self._key_id(row)

        batch = HostBatch.from_rows(self.schema, rows, ts_ms, self.dicts,
                                    capacity=cap)
        ts_rel64 = np.asarray(ts_ms, dtype=np.int64) - self.epoch
        if int(ts_rel64.max()) >= (1 << 31):
            # epoch couldn't rebase far enough (an ancient window is still
            # open with an extreme grace) — fail loudly over corrupting.
            raise OverflowError(
                "stream time span exceeds int32 relative range; "
                "reduce grace or close the stalled window")
        ts_rel = np.zeros(cap, dtype=np.int32)
        ts_rel[:n] = ts_rel64

        wm_rel = np.int32(max(self.watermark_abs - self.epoch, -1)
                          if self.watermark_abs >= 0 else -1)

        # SQL NULL handling: a NULL operand makes the WHERE predicate
        # not-true (row excluded) and excludes the row from that aggregate.
        valid, null_streams = self._null_valid_streams(n, batch.nulls)
        self._note_late(np.asarray(ts_ms, dtype=np.int64))
        self._run_step(cap, n, key_ids, ts_rel, batch.cols, valid,
                       null_streams, wm_rel)

        # host window bookkeeping
        out = None
        if self.window is not None:
            self._track_windows(np.asarray(ts_ms, dtype=np.int64),
                                batch_starts)
        new_wm = max(ts_ms)
        if new_wm > self.watermark_abs:
            self.watermark_abs = new_wm

        if self.emit_changes:
            out = extend_rows(out, self._drain_changes())
        # a lone columnar batch (changes or closes) stays columnar all
        # the way to the caller
        out = extend_rows(out, self.close_due_windows())
        return out if out is not None else []

    def _note_late(self, ts_arr: np.ndarray) -> None:
        """Host mirror of the device's late mask (ISSUE 13): a record
        whose NEWEST window is already past close at the pre-batch
        watermark aggregates nowhere — count it so /metrics carries a
        per-query late-drop series. Steady in-order streams pay one
        integer compare (the quick gate); only batches actually
        carrying late rows pay the vector count."""
        w = self.window
        if w is None or self.watermark_abs < 0 or len(ts_arr) == 0:
            return
        cutoff = self.watermark_abs - w.size_ms - w.grace_ms
        lo = int(ts_arr.min())
        if lo - lo % w.advance_ms > cutoff:
            return
        self.late_drops += int(np.count_nonzero(
            ts_arr - ts_arr % w.advance_ms <= cutoff))

    def _track_windows(self, ts_abs: np.ndarray,
                       starts: set[int] | None = None) -> None:
        advance = self.window.advance_ms
        if starts is None:
            starts = self._new_window_starts(ts_abs)
        for s in starts:
            if s < self.epoch:
                continue
            self._touched_this_call.add(s)
            if s not in self._open:
                slot = (((s - self.epoch) // advance) % self.spec.n_slots)
                self._open[s] = _OpenWindow(start_abs=s, slot=slot)

    def process_columnar(self, key_ids: np.ndarray, ts_ms: np.ndarray,
                         cols: Mapping[str, np.ndarray],
                         nulls: Mapping[str, np.ndarray] | None = None,
                         ) -> list[dict[str, Any]]:
        """Columnar ingest fast path: pre-encoded dense key ids + int64
        absolute-ms timestamps + device columns, skipping per-row Python
        decode (the production ingest path stages columnar batches from
        the native layer). Key-dictionary state must have been populated
        by the caller via key_id_for(); string columns must be pre-encoded
        dictionary ids. Gap jumps that would alias lattice slots go
        through the same _gap_guard split as the row path — rare; the
        steady-state path is pure numpy + one jitted step."""
        if len(key_ids) == 0:
            return []
        try:
            return self._process_columnar(np.asarray(key_ids),
                                          np.asarray(ts_ms, dtype=np.int64),
                                          cols, nulls)
        finally:
            self._no_close.clear()
            self._touched_this_call.clear()

    def _stage_cap(self, n: int) -> int:
        """Padded capacity for a columnar micro-batch. Floored at 4096
        (or batch_capacity when smaller) so variable-size coalesced
        batches share compiled step shapes — each distinct cap is a
        separate XLA compile (SECONDS on a tunneled backend), and
        scatter cost on padded rows is noise. Sticky: a batch reuses
        the smallest already-chosen cap that fits within 8x padding,
        so varying coalesce sizes converge on a few shapes instead of
        compiling each power of two they happen to hit. (A gap-guard
        fallback can discard a chosen cap before its shape compiles —
        at worst that costs one compile at a nearby size later.)

        Called from both the pipeline's encoder thread and the task
        thread (sync fallbacks): the lock keeps the set iteration and
        the insert from racing."""
        with self._caps_lock:
            for c in sorted(self._caps_used):
                if n <= c <= 8 * max(n, 1):
                    return c
            cap = round_up_pow2(n, lo=min(self.batch_capacity, 4096))
            self._caps_used.add(cap)
            return cap

    def _process_columnar(self, key_ids, ts_ms, cols, nulls
                          ) -> list[dict[str, Any]]:
        n = len(key_ids)
        if n > self.batch_capacity:
            # split BEFORE choosing a staged cap: an oversize batch's
            # cap would be registered but never compiled (the chunks
            # compute their own), corrupting the sticky-cap cache
            out = []
            for i in range(0, n, self.batch_capacity):
                sl = slice(i, i + self.batch_capacity)
                out.extend(self._process_columnar(
                    key_ids[sl], ts_ms[sl],
                    {k: v[sl] for k, v in cols.items()},
                    None if nulls is None else
                    {k: v[sl] for k, v in nulls.items()}))
            return out
        cap = self._stage_cap(n)

        ts_list = np.asarray(ts_ms, dtype=np.int64)
        min_ts, max_ts = int(ts_list.min()), int(ts_list.max())
        batch_starts = None
        if self.window is not None:
            def sub(idx):
                return self._process_columnar(
                    key_ids[idx], ts_list[idx],
                    {k: v[idx] for k, v in cols.items()},
                    None if nulls is None else
                    {k: v[idx] for k, v in nulls.items()})

            guarded, batch_starts = self._gap_guard(ts_list, sub)
            if guarded is not None:
                return guarded

        self._ensure_epoch(min_ts)
        self._maybe_rebase(max_ts)

        ts_rel64 = ts_list - self.epoch
        if int(ts_rel64.max()) >= (1 << 31):
            raise OverflowError(
                "stream time span exceeds int32 relative range")
        # SQL NULL in a WHERE operand makes the predicate not-true: fold
        # filter-column null masks into `valid` exactly like the row path.
        valid, null_streams = self._null_valid_streams(n, nulls)
        wm_rel = np.int32(max(self.watermark_abs - self.epoch, -1)
                          if self.watermark_abs >= 0 else -1)
        self._note_late(ts_list)
        self._run_step(cap, n, key_ids, ts_rel64, cols, valid,
                       null_streams, wm_rel)

        out = None
        if self.window is not None:
            self._track_windows(ts_list, batch_starts)
        if max_ts > self.watermark_abs:
            self.watermark_abs = max_ts
        if self.emit_changes:
            out = extend_rows(out, self._drain_changes())
        out = extend_rows(out, self.close_due_windows())
        return out if out is not None else []

    # ---- pipelined ingest (stage on one thread, step on another) ----------

    def _device_stage(self, words):
        """Double-buffered H2D staging: dispatch the async upload, then
        bound in-flight transfers to `upload_slots` by waiting on the
        OLDEST outstanding one (the classic double-buffer handoff). The
        wait blocks an encode worker, never the step-dispatch thread,
        so upload N+1 rides the link while batch N computes. Buffers
        already consumed (donated) by a step are skipped — donation IS
        the recycling of the staging slot."""
        nbytes = getattr(words, "nbytes", None)
        if nbytes is not None:
            self.transfer_stats["h2d_bytes"] += int(nbytes)
        dev = jax.device_put(words)
        wait = None
        with self._upload_lock:
            self._upload_ring.append(dev)
            if len(self._upload_ring) > max(self.upload_slots, 1):
                wait = self._upload_ring.popleft()
        if wait is not None and not wait.is_deleted():
            t0 = time.perf_counter()
            try:
                # deliberate double-buffer backpressure: bounds in-flight
                # H2D to upload_slots, blocking an encode worker only.
                # analyze: ok dispatch-sync — never the step thread
                wait.block_until_ready()
            except RuntimeError:
                pass  # donated to a step between the check and the wait
            with self._stats_lock:
                self.stage_stats["upload_wait_s"] += \
                    time.perf_counter() - t0
        return dev

    def _null_valid_streams(self, n: int, nulls):
        null_streams: dict[str, np.ndarray] = {}
        if nulls is not None:
            for nk, refs in self._null_specs:
                nm = np.zeros(n, dtype=np.bool_)
                for c in refs:
                    if c in nulls:
                        nm |= nulls[c][:n]
                if nm.any():
                    null_streams[nk] = nm
        valid = None
        if self._filter_expr is not None and nulls is not None:
            fm = np.zeros(n, dtype=np.bool_)
            for c in columns_of(self._filter_expr):
                if c in nulls:
                    fm |= nulls[c][:n]
            if fm.any():
                valid = ~fm
        return valid, null_streams

    # contract: dispatches<=0 fetches<=0
    def stage_columnar(self, key_ids, ts_ms, cols, nulls=None,
                       upload: bool = True) -> StagedBatch | None:
        """Encode (and upload) one micro-batch ahead of its step — safe to
        run on an encoder thread while the main thread dispatches earlier
        batches, as long as stage calls happen in batch order (the wire
        codec's adaptive state is ordered). Staging must stay kernel-
        dispatch- and fetch-FREE (the contract above): it overlaps the
        ordered step loop, and a sync here would serialize the pipeline.
        Rare control flow (epoch rebase, int32 overflow, gap splits)
        falls back to the synchronous path inside process_staged()."""
        key_ids = np.asarray(key_ids, dtype=np.int32)
        n = len(key_ids)
        if n == 0:
            return None
        if n > self.batch_capacity:
            raise ValueError("stage_columnar: batch exceeds capacity; "
                             "split upstream")
        ts = np.asarray(ts_ms, dtype=np.int64)
        self._ensure_epoch(int(ts.min()))
        # single epoch read: a concurrent rebase on the caller thread
        # between here and the stamp below must not split the two (the
        # stamp is what process_staged validates against)
        epoch = self.epoch
        ts_rel64 = ts - epoch
        staged = StagedBatch(
            n=n, cap=self._stage_cap(n),
            combo=None, bases=None, words=None, epoch=epoch,
            ts_min=int(ts.min()), ts_max=int(ts.max()),
            key_ids=key_ids, ts_ms=ts, cols=cols, nulls=nulls)
        if int(ts_rel64.max()) >= (1 << 31):
            return staged  # combo=None -> synchronous fallback (rebases)
        valid, null_streams = self._null_valid_streams(n, nulls)
        combo, bases, words = self._encode_locked(
            staged.cap, n, key_ids, ts_rel64, cols, valid, null_streams)
        staged.combo = combo
        staged.bases = bases
        staged.words = self._device_stage(words) if upload else words
        return staged

    def process_staged(self, staged: StagedBatch | None
                       ) -> list[dict[str, Any]]:
        """Ordered step dispatch for a staged batch (main thread)."""
        if staged is None:
            return []
        if (staged.combo is None or staged.epoch != self.epoch
                or staged.ts_max - self.epoch >= self.rebase_threshold):
            # stale encode (epoch rebased since) or wide time span:
            # synchronous path re-encodes with full handling
            try:
                return self._process_columnar(staged.key_ids, staged.ts_ms,
                                              staged.cols, staged.nulls)
            finally:
                self._no_close.clear()
                self._touched_this_call.clear()
        try:
            return self._process_staged(staged)
        finally:
            self._no_close.clear()
            self._touched_this_call.clear()

    # contract: dispatches<=1 fetches<=0
    def _process_staged(self, staged: StagedBatch) -> list[dict[str, Any]]:
        ts_list = staged.ts_ms
        batch_starts = None
        if self.window is not None:
            def sub(idx):
                return self._process_columnar(
                    staged.key_ids[idx], ts_list[idx],
                    {k: np.asarray(v)[idx] for k, v in staged.cols.items()},
                    None if staged.nulls is None else
                    {k: np.asarray(v)[idx] for k, v in staged.nulls.items()})

            guarded, batch_starts = self._gap_guard(ts_list, sub)
            if guarded is not None:
                return guarded

        # process_staged routes any batch with ts_max - epoch >=
        # rebase_threshold (< 2^31) to the guarded synchronous path.
        # analyze: ok overflow-narrowing — caller-guarded narrow
        wm_rel = np.int32(max(self.watermark_abs - self.epoch, -1)
                          if self.watermark_abs >= 0 else -1)
        self._note_late(ts_list)
        self.read_epoch += 1
        step = lattice.compiled_encoded_step(
            self.spec, self.schema, self._filter_expr, staged.combo,
            staged.cap, donate_words=True)
        with kernel_family("step", self.dispatch_observer,
                           ready=self._device_values):
            self.state = step(self.state, wm_rel, np.int32(staged.n),
                              staged.bases, staged.words)

        out = None
        if self.window is not None:
            self._track_windows(ts_list, batch_starts)
        if staged.ts_max > self.watermark_abs:
            self.watermark_abs = staged.ts_max
        if self.emit_changes:
            out = extend_rows(out, self._drain_changes())
        out = extend_rows(out, self.close_due_windows())
        return out if out is not None else []

    def key_id_for(self, key: tuple) -> int:
        """Dense id for a group-key tuple (columnar-path key dictionary).
        Float key values are canonicalized through float32 so JSON and
        columnar producers agree on group identity."""
        key = canon_key(key)
        kid = self._key_ids.get(key)
        if kid is None:
            kid = len(self._key_rev)
            if kid >= self.spec.n_keys:
                self._grow_keys()
            self._key_ids[key] = kid
            self._key_rev.append(key)
        return kid

    # ---- emission ----------------------------------------------------------

    def _decode_key(self, kid: int) -> dict[str, Any]:
        return dict(zip(self.group_cols, self._key_rev[kid]))

    def _postprocess(self, row: dict[str, Any]) -> dict[str, Any] | None:
        if self.node.having is not None:
            if not eval_host(self.node.having, row):
                return None
        if self.node.post_projections:
            projected = {}
            for name, expr in self.node.post_projections:
                projected[name] = eval_host(expr, row)
            # keep window metadata
            for meta in ("winStart", "winEnd"):
                if meta in row:
                    projected[meta] = row[meta]
            return projected
        return row

    def _agg_row(self, kid: int, outs: Mapping[str, np.ndarray], idx: int,
                 win_start_abs: int | None) -> dict[str, Any] | None:
        row = self._decode_key(kid)
        for name, arr in outs.items():
            spec = next(a for a in self.spec.aggs if a.out_name == name)
            if spec.kind in (AggKind.TOPK, AggKind.TOPK_DISTINCT):
                vals = np.asarray(arr[idx])
                row[name] = [float(x) for x in vals
                             if np.isfinite(x)]
                continue
            v = float(arr[idx])
            if spec.kind in (AggKind.COUNT_ALL, AggKind.COUNT,
                             AggKind.APPROX_COUNT_DISTINCT):
                v = int(round(v))
            row[name] = v
        if win_start_abs is not None and self.window is not None:
            row["winStart"] = win_start_abs
            row["winEnd"] = win_start_abs + self.window.size_ms
        return self._postprocess(row)

    # contract: dispatches<=1 fetches<=1
    def _drain_changes(self) -> "ColumnarEmit | list[dict[str, Any]]":
        self.state, packed = self._extract_touched(self.state)
        if not self.defer_change_decode:
            host = np.asarray(packed)
            self.transfer_stats["d2h_bytes"] += host.nbytes
            return self._decode_changes(host, self.epoch)
        # the epoch is captured WITH the extract: a rebase between
        # extract and the deferred decode must not shift window bounds
        self._pending_changes.append((self.epoch, packed))
        out = self._collect_drained(block=False)
        if len(self._pending_changes) <= max(self.change_drain_depth, 1):
            return out if out is not None else []
        # keep the newest extract deferred (it pipelines behind the
        # next batch's work); fetch everything older in one transfer
        keep = self._pending_changes.pop()
        batch = self._pending_changes
        self._pending_changes = [keep]
        if self.async_change_drain:
            # the blocking D2H fetch + columnar decode move to the
            # shared drain pool; batches surface on later calls, in
            # FIFO order
            self._drain_futs.append(
                _change_drain_pool().submit(self._drain_job, batch))
            out = extend_rows(out, self._collect_drained(block=False))
        else:
            out = extend_rows(out, self._decode_pending(batch))
        return out if out is not None else []

    def _drain_job(self, batch: list) -> list[dict[str, Any]]:
        """One async drain unit (drain-pool thread). Reads only
        append-only / immutable executor state: _key_rev grows
        monotonically, spec.aggs never changes (grow_keys swaps n_keys
        only), and the packed buffers are immutable device values."""
        t0 = time.perf_counter()
        try:
            return self._decode_pending(batch)
        finally:
            with self._stats_lock:
                self.stage_stats["drain_s"] += time.perf_counter() - t0

    def _collect_drained(self, block: bool):
        """Completed async drains, strictly in submission order (head
        pop only — a done future behind an unfinished one waits, so
        change rows never reorder). block=True takes everything. A lone
        columnar batch rides through unmaterialized (extend_rows)."""
        rows = None
        while self._drain_futs:
            f = self._drain_futs[0]
            if not block and not f.done():
                break
            self._drain_futs.popleft()
            rows = extend_rows(rows, f.result())
        return rows

    def flush_changes(self) -> list[dict[str, Any]]:
        """Decode every deferred changelog extract (forces the async
        drain queue, then the still-pending tail)."""
        rows = extend_rows(self._collect_drained(block=True),
                           self._decode_pending(self._pending_changes))
        self._pending_changes = []
        return rows if rows is not None else []

    def has_pending_changes(self) -> bool:
        """True when deferred change extracts (queued or in the async
        drain) still hold undelivered rows."""
        return bool(self._pending_changes or self._drain_futs)

    # contract: dispatches<=0 fetches<=1
    def _decode_pending(self, pending: list
                        ) -> "ColumnarEmit | list[dict[str, Any]]":
        """Decode deferred change extracts, fetching device buffers in
        ONE device->host transfer per buffer shape (fetch count, not
        bytes, dominates on real links — each np.asarray is a full
        round trip). Shapes differ only across grow_keys boundaries.
        A single extract's batch stays columnar (ColumnarEmit)."""
        if not pending:
            return []
        if len(pending) == 1:
            epoch, buf = pending[0]
            host = np.asarray(buf)
            self.transfer_stats["d2h_bytes"] += host.nbytes
            return self._decode_changes(host, epoch)
        rows = None
        by_shape: dict[tuple, list] = {}
        for ep, buf in pending:
            by_shape.setdefault(tuple(buf.shape), []).append((ep, buf))
        for group in by_shape.values():
            stacked = np.asarray(lattice.stack_pow2(
                [b for _, b in group]))
            self.transfer_stats["d2h_bytes"] += stacked.nbytes
            for (ep, _), buf in zip(group, stacked):
                rows = extend_rows(rows, self._decode_changes(buf, ep))
        return rows if rows is not None else []

    def _decode_changes(self, packed: np.ndarray, epoch: int | None
                        ) -> "ColumnarEmit | list[dict[str, Any]]":
        """Batched changelog decode: unpack the touched extract, gather
        group-key columns through the cached reverse index, finalize
        aggregate columns, and hand the whole batch to the columnar
        HAVING/projection pass — a ColumnarEmit, no per-row walk (the
        changelog twin of _decode_extract_batch). The retained per-row
        reference is _decode_changes_rows (equivalence tests)."""
        n, kidx, win_start_rel, outs = lattice.unpack_touched_rows(
            self.spec, packed)
        if n == 0:
            return []
        cols: dict[str, Any] = {}
        kidx = kidx.astype(np.int64)
        for name, arr in zip(self.group_cols, self._key_rev_columns()):
            cols[name] = arr[kidx]
        for agg in self.spec.aggs:
            v = outs[agg.out_name]
            if agg.kind in (AggKind.TOPK, AggKind.TOPK_DISTINCT):
                finite = np.isfinite(v)
                vals = np.empty(len(v), object)
                vals[:] = [[float(x) for x in row[m]]
                           for row, m in zip(v, finite)]
                cols[agg.out_name] = vals
            elif agg.kind in (AggKind.COUNT_ALL, AggKind.COUNT,
                              AggKind.APPROX_COUNT_DISTINCT):
                cols[agg.out_name] = np.rint(v).astype(np.int64)
            else:
                cols[agg.out_name] = v.astype(np.float64)
        if self.window is not None:
            ws = win_start_rel.astype(np.int64) + epoch
            cols["winStart"] = ws
            cols["winEnd"] = ws + self.window.size_ms
        return self._postprocess_cols(cols, n)

    def _decode_changes_rows(self, packed: np.ndarray,
                             epoch: int | None) -> list[dict[str, Any]]:
        """Per-row changelog decode (the pre-columnar reference path,
        kept for equivalence tests)."""
        n, kidx, win_start_rel, outs_np = lattice.unpack_touched_rows(
            self.spec, packed)
        rows = []
        for i in range(n):
            ws = (int(win_start_rel[i]) + epoch
                  if self.window is not None else None)
            row = self._agg_row(int(kidx[i]), outs_np, i, ws)
            if row is not None:
                rows.append(row)
        return rows

    def _pad_slots(self, slots: list[int]) -> np.ndarray:
        """Due-slot vector padded (with -1) to a power of two, so close
        cycles of varying width share a handful of compiled shapes
        instead of one XLA executable per distinct due-count (shared
        with the session extract path via lattice.pad_slots)."""
        return lattice.pad_slots(slots)

    # contract: dispatches<=1 fetches<=1
    def _close_windows(self, starts: list[int]) -> list[dict[str, Any]]:
        """Pop + close every window in `starts` with ONE fused
        extract+reset dispatch (the close-cycle contract: one lattice
        kernel and one device->host fetch regardless of how many
        windows are due). A fused-kernel failure (activation/compile,
        device loss, or an injected ``device.activate`` fault) degrades
        this executor PERMANENTLY to the retained per-slot reference
        close — identical results, counted in device_fallbacks —
        instead of killing the query (ISSUE 8)."""
        if not starts:
            return []
        ows = [(s, self._open.pop(s).slot) for s in starts]
        self.read_epoch += 1
        self.close_stats["close_cycles"] += 1
        if not self._fused_close_ok:
            return self._close_windows_ref(ows)
        slots = self._pad_slots([slot for _s, slot in ows])
        packed = None
        prev_state = self.state  # no donation: stays valid for restore
        try:
            if FAULTS.active:  # chaos: provoke a fused-close failure
                FAULTS.point("device.activate")
            if self.emit_changes:
                # the changelog already carried final values: batched
                # reset only, no extract and no fetch
                self.state = self._reset_slots(self.state, slots)
            else:
                self.state, packed = self._extract_reset_slots(
                    self.state, slots)
        except Exception as e:  # noqa: BLE001 — dispatch failed before
            # any state mutation (functional update): the reference
            # path closes the same windows from unchanged state
            log.warning(
                "fused close failed (%s: %s); degrading to the "
                "per-slot reference close", type(e).__name__, e)
            self._fused_close_ok = False
            self.device_fallbacks += 1
            return self._close_windows_ref(ows)
        if self.emit_changes:
            rows = []
        elif self.defer_close_decode:
            # keep the packed batch as a device value; no host sync
            self._pending_closes.append((list(starts), packed))
            rows = []
        else:
            self.close_stats["close_fetches"] += 1
            try:
                packed_host = np.asarray(packed)
                self.transfer_stats["d2h_bytes"] += packed_host.nbytes
            except Exception as e:  # noqa: BLE001 — the dispatch is
                # async: a device-side execution failure surfaces at
                # this D2H sync, AFTER self.state was reassigned to the
                # reset result. Restore the pre-close state (functional
                # update, still valid) and close the same windows on
                # the reference path instead of killing the query.
                log.warning(
                    "fused close fetch failed (%s: %s); degrading to "
                    "the per-slot reference close", type(e).__name__, e)
                self._fused_close_ok = False
                self.device_fallbacks += 1
                self.state = prev_state
                return self._close_windows_ref(ows)
            rows = self._decode_extract_batch(packed_host, starts)
        for s in starts:
            self._no_close.discard(s)
        return rows

    def _close_windows_ref(self, ows: list) -> list[dict[str, Any]]:
        """The retained per-slot reference close (the equivalence path
        tests patch in): one extract + one reset dispatch per window,
        per-kid row decode. Only reached after a fused-close failure —
        correctness over dispatch count on a degraded executor."""
        rows: list[dict[str, Any]] = []
        for s, slot in ows:
            if not self.emit_changes:
                # degraded per-slot fallback after a fused-close
                # failure; one fetch per window is the price of
                # staying alive
                # analyze: ok dispatch-sync — reference close fallback
                packed = np.asarray(self._extract_slot(
                    self.state, np.int32(slot)))
                count, _sr, outs = lattice.unpack_extract_rows(
                    self.spec, packed)
                for kid in np.nonzero(count > 0)[0]:
                    row = self._agg_row(int(kid), outs, int(kid), s)
                    if row is not None:
                        rows.append(row)
            self.state = self._reset_slot(self.state, np.int32(slot))
            self._no_close.discard(s)
        return rows

    # contract: dispatches<=0 fetches<=1
    def drain_closed(self) -> list[dict[str, Any]]:
        """Decode every deferred window close (forces the device queue).
        Multiple pending close cycles fetch in ONE device->host transfer
        per buffer shape — fetch count, not bytes, dominates drain cost
        on real links."""
        if not self._pending_closes:
            return []
        # A fetch failure here deliberately propagates: the deferred
        # packed batches' source windows were reset when the close was
        # deferred, so there is no pre-close state to fall back to —
        # task death + supervised restart from snapshot (at-least-once
        # replay) is the correct recovery, unlike the in-place degrade
        # _close_windows can do at its own sync point.
        if FAULTS.active:  # chaos: fail/delay the deferred-close drain
            FAULTS.point("device.fetch")
        out = None
        if len(self._pending_closes) == 1:
            starts, packed_dev = self._pending_closes[0]
            self.close_stats["close_fetches"] += 1
            packed_host = np.asarray(packed_dev)
            self.transfer_stats["d2h_bytes"] += packed_host.nbytes
            out = self._decode_extract_batch(packed_host, starts)
            self._pending_closes.clear()  # only after decode succeeded
            return out if out is not None else []
        # Group by buffer shape: grow_keys() between two deferred closes
        # changes the K dimension (and cycle width changes P), and
        # jnp.stack over mixed shapes raises.
        by_shape: dict[tuple, list[tuple[list[int], Any]]] = {}
        for starts, packed in self._pending_closes:
            by_shape.setdefault(tuple(packed.shape), []).append(
                (starts, packed))
        for group in by_shape.values():
            self.close_stats["close_fetches"] += 1
            stacked = np.asarray(lattice.stack_pow2(
                [p for _, p in group]))
            self.transfer_stats["d2h_bytes"] += stacked.nbytes
            for (starts, _), packed in zip(group, stacked):
                out = extend_rows(
                    out, self._decode_extract_batch(packed, starts))
        self._pending_closes.clear()  # only after every decode succeeded
        return out if out is not None else []

    def close_due_windows(self) -> list[dict[str, Any]]:
        """Extract + reset every open window past end+grace: one fused
        device dispatch + one fetch for the whole cycle. Host-driven."""
        if self.window is None or self.watermark_abs < 0:
            return []
        w = self.window
        due = [s for s in self._open
               if s + w.size_ms + w.grace_ms <= self.watermark_abs
               and s not in self._no_close]
        return self._close_windows(sorted(due))

    def _key_rev_columns(self) -> list[np.ndarray]:
        """Per-group-column object arrays over the key dictionary, for
        vectorized key decode (one gather per column instead of one
        _decode_key dict per row). Rebuilt only when keys were added."""
        version = len(self._key_rev)
        if self._key_cols_cache[0] != version:
            cols = []
            for g in range(len(self.group_cols)):
                arr = np.empty(version, object)
                for i, key in enumerate(self._key_rev):
                    arr[i] = key[g]
                cols.append(arr)
            self._key_cols_cache = (version, cols)
        return self._key_cols_cache[1]

    def _decode_extract_batch(self, packed: np.ndarray,
                              starts: Sequence[int | None]
                              ) -> "ColumnarEmit | list[dict[str, Any]]":
        """Vectorized decode of a batched extract buffer [P, 2+rows, K]
        into a ColumnarEmit: key decode is a cached reverse-index
        gather, agg finalization is columnar numpy, HAVING evaluates
        columnwise — no per-kid Python loop. `starts[p]` is window p's
        absolute start (None when windowless)."""
        count = packed[:, 0, :]
        widx, kids = np.nonzero(count > 0)
        if len(widx) == 0:
            return []
        cols: dict[str, Any] = {}
        for name, arr in zip(self.group_cols, self._key_rev_columns()):
            cols[name] = arr[kids]
        outs = lattice.gather_extract_batch(self.spec, packed, widx, kids)
        for agg in self.spec.aggs:
            v = outs[agg.out_name]
            if agg.kind in (AggKind.TOPK, AggKind.TOPK_DISTINCT):
                finite = np.isfinite(v)
                vals = np.empty(len(v), object)
                vals[:] = [[float(x) for x in row[m]]
                           for row, m in zip(v, finite)]
                cols[agg.out_name] = vals
            elif agg.kind in (AggKind.COUNT_ALL, AggKind.COUNT,
                              AggKind.APPROX_COUNT_DISTINCT):
                cols[agg.out_name] = np.rint(v).astype(np.int64)
            else:
                cols[agg.out_name] = v
        if self.window is not None and starts and starts[0] is not None:
            ws = np.asarray(starts, np.int64)[widx]
            cols["winStart"] = ws
            cols["winEnd"] = ws + self.window.size_ms
        return self._postprocess_cols(cols, len(widx))

    def _postprocess_cols(self, cols: dict[str, Any], n: int
                          ) -> "ColumnarEmit | list[dict[str, Any]]":
        """HAVING + SELECT projections over a columnar batch. The
        vectorized evaluator covers the numeric/comparison core; any
        op outside it falls back to the per-row interpreter so
        semantics match the legacy path exactly."""
        if self.node.having is not None:
            try:
                keep = np.broadcast_to(
                    np.asarray(eval_host_vec(self.node.having, cols),
                               np.bool_), (n,))
            except Exception:  # noqa: BLE001 — host-only op / NULLs:
                return self._postprocess_rows(ColumnarEmit(cols, n))
            if not keep.all():
                cols = {k: np.asarray(v)[keep] for k, v in cols.items()}
                n = int(keep.sum())
                if n == 0:
                    return []
        if self.node.post_projections:
            try:
                projected: dict[str, Any] = {}
                for name, expr in self.node.post_projections:
                    v = eval_host_vec(expr, cols)
                    projected[name] = np.broadcast_to(
                        np.asarray(v), (n,)) if np.ndim(v) == 0 \
                        else np.asarray(v)
                for meta in ("winStart", "winEnd"):
                    if meta in cols:
                        projected[meta] = cols[meta]
                cols = projected
            except Exception:  # noqa: BLE001
                return self._postprocess_rows(ColumnarEmit(cols, n))
        return ColumnarEmit(cols, n)

    def _postprocess_rows(self, rows) -> list[dict[str, Any]]:
        """Per-row HAVING/projection fallback (host-only ops)."""
        out = []
        for row in rows:
            row = self._postprocess(row)
            if row is not None:
                out.append(row)
        return out

    # ---- pull queries (materialized views) ---------------------------------

    # contract: dispatches<=0 fetches<=0
    def read_version(self) -> tuple:
        """Exact version of the peek-visible aggregate: equal tuples
        guarantee peek() would return the same rows (the read cache's
        validity key — ISSUE 20). Host ints only; lock-free readers get
        at worst a spurious mismatch."""
        return ("agg", self._read_nonce, self.read_epoch,
                self.close_stats["close_cycles"], self.watermark_abs)

    # contract: dispatches<=0 fetches<=0
    def live_min_win_end(self) -> int | None:
        """Smallest winEnd any live (open OR due-but-unclosed) window
        could emit, or None when no live window exists. Lets a reader
        whose WHERE bounds winEnd strictly below this skip peek()
        entirely — closed rows alone answer the query (ISSUE 20)."""
        if self.window is None or not self._open:
            return None
        return min(self._open) + self.window.size_ms

    # contract: dispatches<=1 fetches<=1
    def peek(self) -> list[dict[str, Any]]:
        """Current (open-window) aggregate rows without resetting state —
        the live half of a materialized view; closed windows are kept by
        the view store that owns this executor. ONE batched extract
        dispatch + ONE fetch covers every open window."""
        if self.window is None:
            packed = np.asarray(self._extract_slots(
                self.state, self._pad_slots([0])))
            return self._decode_extract_batch(packed, [None])
        starts = sorted(self._open)
        if not starts:
            return []
        slots = self._pad_slots([self._open[s].slot for s in starts])
        packed = np.asarray(self._extract_slots(self.state, slots))
        return self._decode_extract_batch(packed, starts)

    # contract: dispatches<=0 fetches<=1
    def block_until_ready(self) -> None:
        jax.block_until_ready(self.state)
