"""Host-side query executor: drives the jitted lattice step.

Responsibilities (the reference spreads these across runTask's polling
loop and the aggregate processors — Processor.hs:99-144,
TimeWindowedStream.hs:82-103):

  * columnarize decoded JSON rows into padded HostBatches
  * maintain the group-key dictionary (tuple of group values <-> dense id)
  * maintain the time epoch: device time = int32 ms relative to `epoch`,
    re-anchored (rebase) long before int32 overflow
  * track the watermark (max event time seen = the reference's
    `observedStreamTime`) and the set of open windows ON HOST, so the
    device step never syncs back per batch
  * when the watermark passes win_end + grace: extract + reset that slot
    (window close), finalize, decode keys, apply HAVING + projections
  * EMIT CHANGES mode: additionally extract touched (key, window) pairs
    after each batch (one change per touched pair per micro-batch — the
    batched analogue of the reference's per-record emission)

The executor is single-threaded per query, like the reference's one green
thread per task; concurrency comes from running many executors and from
the device pipelining enqueued steps.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Callable, Mapping, Sequence

import jax
import numpy as np

from hstream_tpu.common.errors import SQLCodegenError
from hstream_tpu.engine import lattice
from hstream_tpu.engine.expr import (
    BinOp,
    Col,
    Expr,
    columns_of,
    encode_strings,
    eval_host,
)
from hstream_tpu.engine.plan import AggKind, AggregateNode, AggSpec
from hstream_tpu.engine.types import (
    ColumnType,
    HostBatch,
    Schema,
    StringDictionary,
    round_up_pow2,
)
from hstream_tpu.engine.window import FixedWindow, SessionWindow

REBASE_THRESHOLD = 1 << 30  # re-anchor epoch when relative time passes this

EmitFn = Callable[[list[dict[str, Any]]], None]


def _align_down(ts: int, step: int) -> int:
    return ts - (ts % step)


@dataclass
class _OpenWindow:
    start_abs: int  # absolute ms
    slot: int


class QueryExecutor:
    """Executes one windowed/global GROUP BY aggregation plan."""

    def __init__(
        self,
        node: AggregateNode,
        schema: Schema,
        *,
        emit_changes: bool = True,
        initial_keys: int = 1024,
        batch_capacity: int = 4096,
    ):
        if isinstance(node.window, SessionWindow):
            raise SQLCodegenError("session windows use SessionExecutor")
        self.node = node
        self.schema = schema
        self.emit_changes = emit_changes
        self.batch_capacity = batch_capacity

        # group keys must be plain columns (validated upstream)
        self.group_cols: list[str] = []
        for k in node.group_keys:
            if not isinstance(k, Col):
                raise SQLCodegenError("GROUP BY supports plain columns")
            self.group_cols.append(k.name)

        self.window: FixedWindow | None = node.window
        self.dicts: dict[str, StringDictionary] = {
            name: StringDictionary() for name, t in schema.fields
            if t == ColumnType.STRING
        }

        self._key_ids: dict[tuple, int] = {}
        self._key_rev: list[tuple] = []

        # Pre-encode string literals (fills the column dictionaries) so the
        # expressions are hashable and compiled functions can be shared.
        encoded_aggs = []
        for agg in node.aggs:
            if agg.input is not None:
                agg = AggSpec(kind=agg.kind, out_name=agg.out_name,
                              input=encode_strings(agg.input, schema, self.dicts),
                              quantile=agg.quantile, k=agg.k)
            encoded_aggs.append(agg)
        self._filter_expr = self._extract_filter()
        if self._filter_expr is not None:
            self._filter_expr = encode_strings(
                self._filter_expr, schema, self.dicts)

        # columns the device step actually needs
        needed = set()
        for agg in encoded_aggs:
            if agg.input is not None:
                needed |= columns_of(agg.input)
        if self._filter_expr is not None:
            needed |= columns_of(self._filter_expr)
        self._needed_cols = sorted(needed)

        self.spec = lattice.LatticeSpec(
            n_keys=initial_keys, window=self.window, aggs=tuple(encoded_aggs))
        self.state = lattice.init_state(self.spec)
        self._compile()

        self.epoch: int | None = None        # absolute ms anchor, advance-aligned
        self.watermark_abs: int = -1
        self._open: dict[int, _OpenWindow] = {}  # start_abs -> window
        self.rebase_threshold = REBASE_THRESHOLD

    def _extract_filter(self) -> Expr | None:
        # Walk the child chain down to the source, ANDing every FilterNode
        # predicate; reject node types this executor cannot honor so a
        # malformed plan fails loudly instead of silently skipping filters.
        from hstream_tpu.engine.plan import FilterNode, SourceNode

        pred: Expr | None = None
        child = self.node.child
        while not isinstance(child, SourceNode):
            if isinstance(child, FilterNode):
                pred = child.predicate if pred is None else \
                    BinOp("AND", pred, child.predicate)
                child = child.child
            else:
                raise SQLCodegenError(
                    f"aggregate over unsupported child node "
                    f"{type(child).__name__}")
        return pred

    def _compile(self) -> None:
        n_per = self.spec.windows_per_record
        fns = lattice.compiled(self.spec, self.schema, self._filter_expr,
                               self.batch_capacity * n_per)
        self._step = fns.step
        self._extract_slot = fns.extract_slot
        self._reset_slot = fns.reset_slot
        self._extract_touched = fns.extract_touched
        self._agg_null_cols = {
            key: sorted(columns_of(agg.input))
            for key, agg in zip(fns.null_keys, self.spec.aggs)
            if key is not None
        }

    # ---- keys --------------------------------------------------------------

    def _key_id(self, row: Mapping[str, Any]) -> int:
        key = tuple(row.get(c) for c in self.group_cols)
        kid = self._key_ids.get(key)
        if kid is None:
            kid = len(self._key_rev)
            if kid >= self.spec.n_keys:
                self._grow_keys()
            self._key_ids[key] = kid
            self._key_rev.append(key)
        return kid

    def _grow_keys(self) -> None:
        new_k = self.spec.n_keys * 2
        self.state = lattice.grow_keys(self.state, self.spec, new_k)
        self.spec = lattice.LatticeSpec(
            n_keys=new_k, window=self.spec.window, aggs=self.spec.aggs,
            hll=self.spec.hll, qcfg=self.spec.qcfg)
        self._compile()

    # ---- time --------------------------------------------------------------

    def _advance_step(self) -> int:
        return 1 if self.window is None else self.window.advance_ms

    def _ensure_epoch(self, min_ts: int) -> None:
        if self.epoch is None:
            # anchor so every window that can ever legally receive records
            # has a non-negative relative start: hopping windows reach back
            # size - advance before the first record, and out-of-order
            # records within the grace period reach back another
            # size + grace (window valid while start + size + grace > wm,
            # and the watermark only grows from the first batch's max).
            if self.window is None:
                back = 0
            else:
                w = self.window
                adv = w.advance_ms
                back = (w.size_ms - adv) + \
                    ((w.size_ms + w.grace_ms + adv - 1) // adv) * adv
            self.epoch = _align_down(min_ts, self._advance_step()) - back

    def _maybe_rebase(self, max_ts_abs: int) -> None:
        if self.epoch is None:
            return
        if max_ts_abs - self.epoch < self.rebase_threshold:
            return
        # Re-anchor at the oldest still-open window (or the watermark).
        # delta must be a multiple of advance * n_slots so the slot
        # mapping (start // advance) mod W of every open window is
        # preserved across the rebase.
        anchor = min([w.start_abs for w in self._open.values()]
                     + [self.watermark_abs if self.watermark_abs >= 0 else max_ts_abs])
        period = self._advance_step() * self.spec.n_slots
        delta = _align_down(anchor - self.epoch, period)
        if delta <= 0:
            return
        self.state = lattice.rebase(self.state, np.int32(delta))
        self.epoch = self.epoch + delta

    # ---- ingest ------------------------------------------------------------

    def process(self, rows: Sequence[Mapping[str, Any]],
                ts_ms: Sequence[int]) -> list[dict[str, Any]]:
        """Feed one micro-batch of decoded records; returns emitted rows."""
        if not rows:
            return []
        if len(rows) > self.batch_capacity:
            out = []
            for i in range(0, len(rows), self.batch_capacity):
                out.extend(self.process(rows[i:i + self.batch_capacity],
                                        ts_ms[i:i + self.batch_capacity]))
            return out

        # Slot-collision guard: a window W*advance newer than the oldest
        # still-open window would land in the same lattice slot. If this
        # batch spans that far (a stream gap / restart), split it in time
        # order and force-close due windows in between; the watermark then
        # advances at sub-batch granularity.
        if self.window is not None:
            w = self.window
            back = w.size_ms - w.advance_ms
            aligned_min = _align_down(min(ts_ms), w.advance_ms) - back
            anchor = min([ow for ow in self._open] + [aligned_min])
            threshold = anchor + (self.spec.n_slots - 1) * w.advance_ms
            if max(ts_ms) > threshold:
                order = sorted(range(len(rows)), key=lambda i: ts_ms[i])
                pre = [i for i in order if ts_ms[i] <= threshold]
                suf = [i for i in order if ts_ms[i] > threshold]
                out = []
                if pre:
                    out.extend(self.process([rows[i] for i in pre],
                                            [ts_ms[i] for i in pre]))
                # Close the windows the suffix's watermark will make due,
                # advancing the watermark only to their close boundaries —
                # suffix records within grace of still-open windows keep
                # the semantics the non-split path gives them.
                prospective = max(ts_ms[i] for i in suf)
                due = [s for s in self._open
                       if s + w.size_ms + w.grace_ms <= prospective]
                if due:
                    boundary = max(s + w.size_ms + w.grace_ms for s in due)
                    self.watermark_abs = max(self.watermark_abs, boundary)
                    out.extend(self.close_due_windows())
                out.extend(self.process([rows[i] for i in suf],
                                        [ts_ms[i] for i in suf]))
                return out

        self._ensure_epoch(min(ts_ms))
        self._maybe_rebase(max(ts_ms))

        n = len(rows)
        cap = round_up_pow2(n)
        key_ids = np.zeros(cap, dtype=np.int32)
        for i, row in enumerate(rows):
            key_ids[i] = self._key_id(row)

        batch = HostBatch.from_rows(self.schema, rows, ts_ms, self.dicts,
                                    capacity=cap)
        ts_rel64 = np.asarray(ts_ms, dtype=np.int64) - self.epoch
        if int(ts_rel64.max()) >= (1 << 31):
            # epoch couldn't rebase far enough (an ancient window is still
            # open with an extreme grace) — fail loudly over corrupting.
            raise OverflowError(
                "stream time span exceeds int32 relative range; "
                "reduce grace or close the stalled window")
        ts_rel = np.zeros(cap, dtype=np.int32)
        ts_rel[:n] = ts_rel64

        wm_rel = np.int32(max(self.watermark_abs - self.epoch, -1)
                          if self.watermark_abs >= 0 else -1)

        cols = {name: batch.cols[name] for name in self._needed_cols}
        # SQL NULL handling: a NULL operand makes the WHERE predicate
        # not-true (row excluded) and excludes the row from that aggregate.
        valid = batch.valid
        if self._filter_expr is not None:
            fm = np.zeros(cap, dtype=np.bool_)
            for c in columns_of(self._filter_expr):
                fm |= batch.nulls[c]
            valid = valid & ~fm
        for null_key, refs in self._agg_null_cols.items():
            nm = np.zeros(cap, dtype=np.bool_)
            for c in refs:
                nm |= batch.nulls[c]
            cols[null_key] = nm
        self.state = self._step(self.state, wm_rel, key_ids, ts_rel,
                                valid, cols)

        # host window bookkeeping
        out: list[dict[str, Any]] = []
        if self.window is not None:
            self._track_windows(np.asarray(ts_ms, dtype=np.int64))
        new_wm = max(ts_ms)
        if new_wm > self.watermark_abs:
            self.watermark_abs = new_wm

        if self.emit_changes:
            out.extend(self._drain_changes())
        out_closed = self.close_due_windows()
        out.extend(out_closed)
        return out

    def _track_windows(self, ts_abs: np.ndarray) -> None:
        w = self.window
        advance = w.advance_ms
        latest = ts_abs - (ts_abs % advance)
        starts: set[int] = set()
        for j in range(w.windows_per_record):
            starts.update((latest - j * advance).tolist())
        wm = self.watermark_abs
        for s in starts:
            if s < self.epoch:
                continue
            if wm >= 0 and s + w.size_ms + w.grace_ms <= wm:
                continue  # late, dropped on device too
            if s not in self._open:
                slot = (((s - self.epoch) // advance) % self.spec.n_slots)
                self._open[s] = _OpenWindow(start_abs=s, slot=slot)

    # ---- emission ----------------------------------------------------------

    def _decode_key(self, kid: int) -> dict[str, Any]:
        return dict(zip(self.group_cols, self._key_rev[kid]))

    def _postprocess(self, row: dict[str, Any]) -> dict[str, Any] | None:
        if self.node.having is not None:
            if not eval_host(self.node.having, row):
                return None
        if self.node.post_projections:
            projected = {}
            for name, expr in self.node.post_projections:
                projected[name] = eval_host(expr, row)
            # keep window metadata
            for meta in ("winStart", "winEnd"):
                if meta in row:
                    projected[meta] = row[meta]
            return projected
        return row

    def _agg_row(self, kid: int, outs: Mapping[str, np.ndarray], idx: int,
                 win_start_abs: int | None) -> dict[str, Any] | None:
        row = self._decode_key(kid)
        for name, arr in outs.items():
            v = float(arr[idx])
            spec = next(a for a in self.spec.aggs if a.out_name == name)
            if spec.kind in (AggKind.COUNT_ALL, AggKind.COUNT,
                             AggKind.APPROX_COUNT_DISTINCT):
                v = int(round(v))
            row[name] = v
        if win_start_abs is not None and self.window is not None:
            row["winStart"] = win_start_abs
            row["winEnd"] = win_start_abs + self.window.size_ms
        return self._postprocess(row)

    def _drain_changes(self) -> list[dict[str, Any]]:
        self.state, n, kidx, win_start_rel, outs = \
            self._extract_touched(self.state)
        n = int(n)
        if n == 0:
            return []
        kidx = np.asarray(kidx[:n])
        win_start_rel = np.asarray(win_start_rel[:n])
        outs_np = {k: np.asarray(v[:n]) for k, v in outs.items()}
        rows = []
        for i in range(n):
            ws = (int(win_start_rel[i]) + self.epoch
                  if self.window is not None else None)
            row = self._agg_row(int(kidx[i]), outs_np, i, ws)
            if row is not None:
                rows.append(row)
        return rows

    def close_due_windows(self) -> list[dict[str, Any]]:
        """Extract + reset every open window past end+grace. Host-driven."""
        if self.window is None or self.watermark_abs < 0:
            return []
        w = self.window
        due = [s for s in self._open
               if s + w.size_ms + w.grace_ms <= self.watermark_abs]
        rows: list[dict[str, Any]] = []
        for s in sorted(due):
            ow = self._open.pop(s)
            if not self.emit_changes:
                rows.extend(self._extract_window_rows(ow))
            self.state = self._reset_slot(self.state, np.int32(ow.slot))
        return rows

    def _extract_window_rows(self, ow: _OpenWindow) -> list[dict[str, Any]]:
        mask, _start_rel, outs = self._extract_slot(
            self.state, np.int32(ow.slot))
        mask = np.asarray(mask)
        outs_np = {k: np.asarray(v) for k, v in outs.items()}
        rows = []
        for kid in np.nonzero(mask)[0]:
            row = self._agg_row(int(kid), outs_np, int(kid), ow.start_abs)
            if row is not None:
                rows.append(row)
        return rows

    # ---- pull queries (materialized views) ---------------------------------

    def peek(self) -> list[dict[str, Any]]:
        """Current (open-window) aggregate rows without resetting state —
        the live half of a materialized view; closed windows are kept by
        the view store that owns this executor."""
        rows: list[dict[str, Any]] = []
        if self.window is None:
            mask, _s, outs = self._extract_slot(self.state, np.int32(0))
            mask = np.asarray(mask)
            outs_np = {k: np.asarray(v) for k, v in outs.items()}
            for kid in np.nonzero(mask)[0]:
                row = self._agg_row(int(kid), outs_np, int(kid), None)
                if row is not None:
                    rows.append(row)
            return rows
        for s in sorted(self._open):
            rows.extend(self._extract_window_rows(self._open[s]))
        return rows

    def block_until_ready(self) -> None:
        jax.block_until_ready(self.state)
