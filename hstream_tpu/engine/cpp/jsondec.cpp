// Native batch decoder: HStreamRecord + google.protobuf.Struct wire
// format -> columnar arrays, one pass over a whole appended batch.
//
// The server's per-record JSON ingest path (proto parse + Struct->dict
// in Python) costs ~8us/record; at changelog rates that IS the query
// loop. This decoder walks the protobuf wire format directly (the
// field layout of proto/api.proto:87-97 and the well-known Struct) and
// emits dense typed columns + per-column null masks + a string
// dictionary, which feed the executor's staged columnar path with no
// per-record Python at all. SURVEY §7: "protobuf decode + key
// dictionary off the critical path (C++ ingest, columnar staging)".
//
// The reference's analogue is its native store client decode
// (hstream-store cbits reader path); its JSON values ride protobuf
// Structs exactly like ours (HStreamApi.proto HStreamRecord).
//
// Per-record classification (out_class):
//   0 = flat JSON decoded into columns
//   1 = RAW-flagged record (columnar producer batches etc — Python
//       routes by payload magic)
//   2 = needs the Python fallback (nested struct/list values, type
//       conflict with an established column, malformed bytes)
//
// Build: common/nativebuild.py (g++ -O3, no deps).

#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct Reader {
    const uint8_t *p;
    const uint8_t *end;
    bool ok = true;

    bool more() const { return ok && p < end; }

    uint64_t varint() {
        uint64_t v = 0;
        int shift = 0;
        while (p < end && shift < 64) {
            uint8_t b = *p++;
            v |= (uint64_t)(b & 0x7f) << shift;
            if (!(b & 0x80)) return v;
            shift += 7;
        }
        ok = false;
        return 0;
    }

    // length-delimited span; returns false on overrun
    bool span(const uint8_t **s, int64_t *len) {
        uint64_t l = varint();
        if (!ok || (uint64_t)(end - p) < l) { ok = false; return false; }
        *s = p;
        *len = (int64_t)l;
        p += l;
        return true;
    }

    bool skip(uint32_t wire) {
        switch (wire) {
            case 0: varint(); return ok;
            case 1:
                if (end - p < 8) { ok = false; return false; }
                p += 8;
                return true;
            case 2: {
                const uint8_t *s; int64_t l;
                return span(&s, &l);
            }
            case 5:
                if (end - p < 4) { ok = false; return false; }
                p += 4;
                return true;
            default: ok = false; return false;
        }
    }
};

enum ColType { T_NUM = 0, T_STR = 1, T_BOOL = 2 };

struct Col {
    int type = -1;
    std::vector<double> nums;
    std::vector<int32_t> sids;
    std::vector<uint8_t> bools;
    std::vector<uint8_t> nulls;  // 1 = null / missing
    std::unordered_map<std::string, int32_t> dict;
    std::vector<std::string> dict_list;
    int64_t dict_bytes = 0;
};

struct Scan {
    int64_t n = 0;
    std::vector<std::string> names;  // insertion order
    std::unordered_map<std::string, int> index;
    std::vector<Col> cols;

    Col &get(const std::string &name) {
        auto it = index.find(name);
        if (it != index.end()) return cols[it->second];
        index.emplace(name, (int)cols.size());
        names.push_back(name);
        cols.emplace_back();
        Col &c = cols.back();
        c.nums.assign(n, 0.0);
        c.sids.assign(n, 0);
        c.bools.assign(n, 0);
        c.nulls.assign(n, 1);  // rows before discovery are missing
        return c;
    }
};

// one decoded field of the record being scanned (commit only when the
// whole record parses flat — a rejected record must not half-write)
struct FieldVal {
    std::string name;
    int type;     // ColType, or -1 for explicit null
    double num = 0.0;
    uint8_t b = 0;
    std::string str;
};

// Value message: returns false -> record needs Python fallback
static bool parse_value(const uint8_t *s, int64_t len, FieldVal *fv) {
    Reader r{s, s + len};
    fv->type = -1;  // empty Value == null (WhichOneof None)
    while (r.more()) {
        uint64_t tag = r.varint();
        if (!r.ok) return false;
        uint32_t field = (uint32_t)(tag >> 3), wire = (uint32_t)(tag & 7);
        if (field == 1 && wire == 0) {          // null_value
            r.varint();
            fv->type = -1;
        } else if (field == 2 && wire == 1) {   // number_value
            if (r.end - r.p < 8) return false;
            double d;
            std::memcpy(&d, r.p, 8);
            r.p += 8;
            fv->type = T_NUM;
            fv->num = d;
        } else if (field == 3 && wire == 2) {   // string_value
            const uint8_t *vs; int64_t vl;
            if (!r.span(&vs, &vl)) return false;
            fv->type = T_STR;
            fv->str.assign((const char *)vs, (size_t)vl);
        } else if (field == 4 && wire == 0) {   // bool_value
            uint64_t v = r.varint();
            if (!r.ok) return false;
            fv->type = T_BOOL;
            fv->b = v ? 1 : 0;
        } else if (field == 5 || field == 6) {  // struct_value / list_value
            return false;  // nested -> Python fallback
        } else {
            if (!r.skip(wire)) return false;
        }
    }
    return r.ok;
}

}  // namespace

extern "C" {

// Scan n records (record i = buf[offs[i]..offs[i+1])). out_ts[i] =
// publish_time_ms or default_ts[i] when unset. Returns an opaque Scan*.
void *jd_scan(const uint8_t *buf, const int64_t *offs, int64_t n,
              const int64_t *default_ts, int64_t *out_ts,
              uint8_t *out_class) {
    Scan *sc = new Scan();
    sc->n = n;
    std::vector<FieldVal> scratch;
    for (int64_t i = 0; i < n; ++i) {
        out_ts[i] = default_ts[i];
        out_class[i] = 2;
        Reader r{buf + offs[i], buf + offs[i + 1]};
        uint64_t flag = 0;
        int64_t publish = 0;
        const uint8_t *payload = nullptr;
        int64_t paylen = -1;
        bool bad = false;
        while (r.more()) {
            uint64_t tag = r.varint();
            if (!r.ok) { bad = true; break; }
            uint32_t field = (uint32_t)(tag >> 3),
                     wire = (uint32_t)(tag & 7);
            if (field == 1 && wire == 2) {        // header
                const uint8_t *hs; int64_t hl;
                if (!r.span(&hs, &hl)) { bad = true; break; }
                Reader h{hs, hs + hl};
                while (h.more()) {
                    uint64_t htag = h.varint();
                    if (!h.ok) { bad = true; break; }
                    uint32_t hf = (uint32_t)(htag >> 3),
                             hw = (uint32_t)(htag & 7);
                    if (hf == 1 && hw == 0) flag = h.varint();
                    else if (hf == 3 && hw == 0)
                        publish = (int64_t)h.varint();
                    else if (!h.skip(hw)) { bad = true; break; }
                }
                if (!h.ok) bad = true;
            } else if (field == 2 && wire == 2) { // payload
                if (!r.span(&payload, &paylen)) { bad = true; break; }
            } else if (!r.skip(wire)) { bad = true; break; }
        }
        if (bad || !r.ok) continue;  // class 2: Python reproduces the
                                     // old path's error behavior
        if (publish > 0) out_ts[i] = publish;
        if (flag != 0) { out_class[i] = 1; continue; }  // RAW
        // JSON payload: Struct { map<string, Value> fields = 1 }
        scratch.clear();
        bool flat = true;
        if (paylen >= 0) {
            Reader s{payload, payload + paylen};
            while (s.more()) {
                uint64_t tag = s.varint();
                if (!s.ok) { flat = false; break; }
                uint32_t field = (uint32_t)(tag >> 3),
                         wire = (uint32_t)(tag & 7);
                if (field == 1 && wire == 2) {
                    const uint8_t *es; int64_t el;
                    if (!s.span(&es, &el)) { flat = false; break; }
                    Reader e{es, es + el};
                    FieldVal fv;
                    bool have_key = false, have_val = false;
                    fv.type = -1;
                    while (e.more()) {
                        uint64_t etag = e.varint();
                        if (!e.ok) { flat = false; break; }
                        uint32_t ef = (uint32_t)(etag >> 3),
                                 ew = (uint32_t)(etag & 7);
                        if (ef == 1 && ew == 2) {
                            const uint8_t *ks; int64_t kl;
                            if (!e.span(&ks, &kl)) { flat = false; break; }
                            if (kl > 255) { flat = false; break; }
                            // (>255-byte field names -> Python fallback
                            // so jd_col_meta's fixed buffer never
                            // silently merges distinct columns)
                            fv.name.assign((const char *)ks, (size_t)kl);
                            have_key = true;
                        } else if (ef == 2 && ew == 2) {
                            const uint8_t *vs; int64_t vl;
                            if (!e.span(&vs, &vl)) { flat = false; break; }
                            if (!parse_value(vs, vl, &fv)) {
                                flat = false;
                                break;
                            }
                            have_val = true;
                        } else if (!e.skip(ew)) { flat = false; break; }
                    }
                    if (!flat || !e.ok) { flat = false; break; }
                    if (have_key) {
                        (void)have_val;  // missing Value == null
                        scratch.push_back(std::move(fv));
                    }
                } else if (!s.skip(wire)) { flat = false; break; }
            }
            if (!s.ok) flat = false;
        }
        if (!flat) continue;  // class 2
        // type-compat check against established columns BEFORE commit
        for (const FieldVal &fv : scratch) {
            if (fv.type < 0) continue;
            auto it = sc->index.find(fv.name);
            if (it != sc->index.end()) {
                int t = sc->cols[it->second].type;
                if (t != -1 && t != fv.type) { flat = false; break; }
            }
        }
        // duplicate keys with conflicting types inside ONE record
        for (size_t a = 0; flat && a + 1 < scratch.size(); ++a)
            for (size_t b = a + 1; b < scratch.size(); ++b)
                if (scratch[a].type >= 0 && scratch[b].type >= 0 &&
                    scratch[a].type != scratch[b].type &&
                    scratch[a].name == scratch[b].name) {
                    flat = false;
                    break;
                }
        if (!flat) continue;  // class 2 (conflicting value type)
        for (FieldVal &fv : scratch) {
            Col &c = sc->get(fv.name);
            if (fv.type < 0) {           // explicit null
                c.nulls[i] = 1;
                continue;
            }
            if (c.type == -1) c.type = fv.type;
            c.nulls[i] = 0;
            if (fv.type == T_NUM) {
                c.nums[i] = fv.num;
            } else if (fv.type == T_BOOL) {
                c.bools[i] = fv.b;
            } else {
                auto di = c.dict.find(fv.str);
                int32_t sid;
                if (di == c.dict.end()) {
                    sid = (int32_t)c.dict_list.size();
                    c.dict.emplace(fv.str, sid);
                    c.dict_bytes += (int64_t)fv.str.size();
                    c.dict_list.push_back(std::move(fv.str));
                } else {
                    sid = di->second;
                }
                c.sids[i] = sid;
            }
        }
        out_class[i] = 0;
    }
    return sc;
}

int64_t jd_ncols(void *h) { return (int64_t)((Scan *)h)->cols.size(); }

// name (<=255 bytes; *name_len_out gives the exact byte length so NUL
// bytes inside names survive), type (ColType; -1 = all-null column),
// dict entry count + total dict bytes (string columns)
void jd_col_meta(void *h, int64_t i, char *name_out,
                 int32_t *name_len_out, int32_t *type_out,
                 int32_t *ndict_out, int64_t *dict_bytes_out) {
    Scan *sc = (Scan *)h;
    const std::string &nm = sc->names[i];
    size_t l = nm.size() < 255 ? nm.size() : 255;
    std::memcpy(name_out, nm.data(), l);
    *name_len_out = (int32_t)l;
    Col &c = sc->cols[i];
    *type_out = c.type;
    *ndict_out = (int32_t)c.dict_list.size();
    *dict_bytes_out = c.dict_bytes;
}

// copy column i's data; pass the buffer matching its type (others may
// be null). nulls is always filled.
void jd_col_data(void *h, int64_t i, double *nums, int32_t *sids,
                 uint8_t *bools, uint8_t *nulls) {
    Scan *sc = (Scan *)h;
    Col &c = sc->cols[i];
    if (nums) std::memcpy(nums, c.nums.data(), sc->n * sizeof(double));
    if (sids) std::memcpy(sids, c.sids.data(), sc->n * sizeof(int32_t));
    if (bools) std::memcpy(bools, c.bools.data(), sc->n);
    std::memcpy(nulls, c.nulls.data(), sc->n);
}

// string dictionary as concatenated bytes + per-entry lengths
void jd_dict_data(void *h, int64_t i, uint8_t *concat, int32_t *lens) {
    Col &c = ((Scan *)h)->cols[i];
    uint8_t *w = concat;
    for (size_t j = 0; j < c.dict_list.size(); ++j) {
        const std::string &s = c.dict_list[j];
        std::memcpy(w, s.data(), s.size());
        w += s.size();
        lens[j] = (int32_t)s.size();
    }
}

void jd_free(void *h) { delete (Scan *)h; }

}  // extern "C"
