// Native columnar wire-encode kernels for the bit-packed transport
// (engine/transport.py). The Python planner decides encodings; these
// loops do the heavy per-element passes: streaming bit-pack, delta
// pack, bool pack, and decimal quantize+verify — each a single pass.
//
// Reference parallel: the reference's ingest hot path is native too
// (hstream-store cbits append/batch path, hs_writer.cpp); SURVEY §7
// calls for "C++ ingest, columnar staging" so the host never stalls
// the device. Build: engine/build.py (g++ -O3, no deps).

#include <cstdint>
#include <cstring>
#include <cmath>

extern "C" {

// ---- streaming bit-pack: out words = (cap*bits+31)/32 + 1 ------------------

static inline void pack_stream(const uint64_t *u, int64_t n, int bits,
                               uint32_t *out, int64_t nw) {
    std::memset(out, 0, nw * sizeof(uint32_t));
    uint64_t acc = 0;
    int fill = 0;
    uint32_t *w = out;
    for (int64_t i = 0; i < n; ++i) {
        acc |= u[i] << fill;
        fill += bits;
        if (fill >= 32) {
            *w++ = (uint32_t)acc;
            acc >>= 32;
            fill -= 32;
        }
    }
    if (fill > 0) *w++ = (uint32_t)acc;
}

// pack (v[i] - base) at `bits` bits each; v int64
void enc_pack_i64(const int64_t *v, int64_t n, int64_t base, int bits,
                  uint32_t *out, int64_t nw) {
    std::memset(out, 0, nw * sizeof(uint32_t));
    uint64_t acc = 0;
    int fill = 0;
    uint32_t *w = out;
    for (int64_t i = 0; i < n; ++i) {
        acc |= (uint64_t)(v[i] - base) << fill;
        fill += bits;
        if (fill >= 32) { *w++ = (uint32_t)acc; acc >>= 32; fill -= 32; }
    }
    if (fill > 0) *w++ = (uint32_t)acc;
}

void enc_pack_i32(const int32_t *v, int64_t n, int64_t base, int bits,
                  uint32_t *out, int64_t nw) {
    std::memset(out, 0, nw * sizeof(uint32_t));
    uint64_t acc = 0;
    int fill = 0;
    uint32_t *w = out;
    for (int64_t i = 0; i < n; ++i) {
        acc |= (uint64_t)(int64_t)(v[i] - base) << fill;
        fill += bits;
        if (fill >= 32) { *w++ = (uint32_t)acc; acc >>= 32; fill -= 32; }
    }
    if (fill > 0) *w++ = (uint32_t)acc;
}

// pack first differences (d[0] = 0) of a nondecreasing int64 stream
void enc_pack_diff_i64(const int64_t *v, int64_t n, int bits,
                       uint32_t *out, int64_t nw) {
    std::memset(out, 0, nw * sizeof(uint32_t));
    uint64_t acc = 0;
    int fill = 0;
    uint32_t *w = out;
    int64_t prev = n > 0 ? v[0] : 0;
    for (int64_t i = 0; i < n; ++i) {
        acc |= (uint64_t)(v[i] - prev) << fill;
        prev = v[i];
        fill += bits;
        if (fill >= 32) { *w++ = (uint32_t)acc; acc >>= 32; fill -= 32; }
    }
    if (fill > 0) *w++ = (uint32_t)acc;
}

void enc_pack_bool(const uint8_t *v, int64_t n, uint32_t *out, int64_t nw) {
    std::memset(out, 0, nw * sizeof(uint32_t));
    for (int64_t i = 0; i < n; ++i)
        if (v[i]) out[i >> 5] |= (uint32_t)1 << (i & 31);
}

// ---- stats (single pass, no intermediate arrays) ---------------------------

void enc_minmax_i64(const int64_t *v, int64_t n, int64_t *out_min,
                    int64_t *out_max) {
    int64_t lo = n ? v[0] : 0, hi = n ? v[0] : 0;
    for (int64_t i = 1; i < n; ++i) {
        if (v[i] < lo) lo = v[i];
        if (v[i] > hi) hi = v[i];
    }
    *out_min = lo;
    *out_max = hi;
}

void enc_minmax_i32(const int32_t *v, int64_t n, int64_t *out_min,
                    int64_t *out_max) {
    int32_t lo = n ? v[0] : 0, hi = n ? v[0] : 0;
    for (int64_t i = 1; i < n; ++i) {
        if (v[i] < lo) lo = v[i];
        if (v[i] > hi) hi = v[i];
    }
    *out_min = lo;
    *out_max = hi;
}

// nondecreasing check + max first-difference (for delta planning)
// returns 1 if nondecreasing, 0 otherwise
int32_t enc_diff_stats_i64(const int64_t *v, int64_t n, int64_t *out_dmax) {
    int64_t dmax = 0;
    for (int64_t i = 1; i < n; ++i) {
        int64_t d = v[i] - v[i - 1];
        if (d < 0) { *out_dmax = 0; return 0; }
        if (d > dmax) dmax = d;
    }
    *out_dmax = dmax;
    return 1;
}

// ---- decimal quantize + bit-exact verify (one pass) ------------------------
//
// q[i] = rint(v[i] * scale); fails (returns 0) on |q| > max_q or when
// (float)q * inv_scale != v[i] (the exact device-decode round trip).
// On success fills q (int32) and min/max.
int32_t enc_quantize_f32(const float *v, int64_t n, float scale,
                         float inv_scale, int64_t max_q, int32_t *q_out,
                         int64_t *out_min, int64_t *out_max) {
    int64_t lo = INT64_MAX, hi = INT64_MIN;
    for (int64_t i = 0; i < n; ++i) {
        float qf = std::nearbyintf(v[i] * scale);
        if (!(std::fabs(qf) <= (float)max_q)) return 0;  // NaN/inf too
        int32_t q = (int32_t)qf;
        if ((float)q * inv_scale != v[i]) return 0;
        q_out[i] = q;
        if (q < lo) lo = q;
        if (q > hi) hi = q;
    }
    *out_min = n ? lo : 0;
    *out_max = n ? hi : 0;
    return 1;
}

}  // extern "C"
