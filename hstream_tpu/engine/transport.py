"""Bit-packed columnar host->device transport (v2).

The ingest wall on real deployments is the host->device link: every byte
of a record batch crosses PCIe (or, on tunneled dev chips, a far slower
link), so wire bytes per event — not host CPU and not device FLOPs — set
the throughput ceiling. This module is the engine's answer: a
Parquet-style adaptive columnar codec that encodes each micro-batch into
ONE uint32 buffer, decoded on-device inside the jitted step (shifts and
masks on the VPU, fused into the aggregation kernel by XLA).

Per-stream encodings, chosen adaptively per column with sticky,
monotone-widening policies so jit specializations stay bounded:

  u8 / u16   unsigned bit-pack (4 / 2 values per word) — key ids,
             timestamp deltas against a per-batch base, dictionary ids,
             small ints
  dec        int16 fixed-point for decimal-quantized floats (sensor
             readings, prices): encodes round(v*scale) iff the exact
             f32 round-trip  decode(encode(v)) == v  holds elementwise
             (verified per batch, falls back to raw32 otherwise);
             device decode is  i16 / scale  — IEEE division keeps the
             round-trip bit-exact
  bool8      bools / null bitmaps, one byte per value
  raw32      f32 bitcast or i32, the lossless fallback

The reference has no analogue (its ingest is per-record protobuf over a
local socket — hstream-store cbits append path); this is TPU-first
design: the wire format exists so the MXU/VPU never starves behind the
link. Typical footprint: u16 key + u8 time delta + dec16 payload = 5
bytes per event, vs 16 in the naive int32 transport — a 3.2x ingest
ceiling raise.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Mapping

import jax
import jax.numpy as jnp
import numpy as np

ENC_U8 = "u8"
ENC_U16 = "u16"
ENC_DEC = "dec"      # int16 fixed-point, scale in StreamPlan.scale
ENC_BOOL8 = "bool8"
ENC_RAW_F32 = "rawf"
ENC_RAW_I32 = "rawi"

_WORDS_PER_VALUE = {ENC_U8: 0.25, ENC_U16: 0.5, ENC_DEC: 0.5,
                    ENC_BOOL8: 0.25, ENC_RAW_F32: 1.0, ENC_RAW_I32: 1.0}

DEC_SCALES = (1, 10, 100)  # fixed-point scales tried for float columns
DEC_LIMIT = 32767


@dataclass(frozen=True)
class StreamPlan:
    """Encoding of one logical stream; part of the jit specialization key."""

    name: str          # "__kid", "__dt", "__valid", or a column name
    enc: str
    scale: int = 0     # ENC_DEC only

    def words(self, cap: int) -> int:
        return int(cap * _WORDS_PER_VALUE[self.enc])


Combo = tuple[StreamPlan, ...]


def wire_bytes(combo: Combo, cap: int) -> int:
    return 4 * sum(p.words(cap) for p in combo)


def _pack_stream(plan: StreamPlan, vals: np.ndarray, cap: int) -> np.ndarray:
    """Encode one stream (length n <= cap) into uint32 words."""
    n = len(vals)
    if plan.enc == ENC_U8:
        buf = np.zeros(cap, np.uint8)
        buf[:n] = vals
        return buf.view(np.uint32)
    if plan.enc == ENC_U16:
        buf = np.zeros(cap, np.uint16)
        buf[:n] = vals
        return buf.view(np.uint32)
    if plan.enc == ENC_DEC:
        buf = np.zeros(cap, np.int16)
        q = np.rint(np.asarray(vals, np.float64) * plan.scale)
        buf[:n] = q.astype(np.int16)
        return buf.view(np.uint32)
    if plan.enc == ENC_BOOL8:
        buf = np.zeros(cap, np.uint8)
        buf[:n] = np.asarray(vals, np.bool_)
        return buf.view(np.uint32)
    if plan.enc == ENC_RAW_F32:
        buf = np.zeros(cap, np.float32)
        buf[:n] = vals
        return buf.view(np.uint32)
    buf = np.zeros(cap, np.int32)
    buf[:n] = vals
    return buf.view(np.uint32)


def _unpack_stream(plan: StreamPlan, words: jnp.ndarray, cap: int):
    """Traced device decode of one stream -> [cap] array."""
    if plan.enc in (ENC_U8, ENC_BOOL8):
        lanes = (words[:, None] >> jnp.uint32([0, 8, 16, 24])[None, :]
                 ) & jnp.uint32(0xFF)
        v = lanes.reshape(cap).astype(jnp.int32)
        return v != 0 if plan.enc == ENC_BOOL8 else v
    if plan.enc in (ENC_U16, ENC_DEC):
        lanes = (words[:, None] >> jnp.uint32([0, 16])[None, :]
                 ) & jnp.uint32(0xFFFF)
        v = lanes.reshape(cap).astype(jnp.int32)
        if plan.enc == ENC_U16:
            return v
        signed = v - ((v >> 15) << 16)  # sign-extend int16
        # multiply by the f32 reciprocal — a single IEEE multiply is
        # bit-identical between numpy (the encoder's verifier) and XLA,
        # unlike division by a constant, which XLA strength-reduces
        return signed.astype(jnp.float32) * jnp.float32(1.0 / plan.scale)
    if plan.enc == ENC_RAW_F32:
        return jax.lax.bitcast_convert_type(words, jnp.float32)
    return jax.lax.bitcast_convert_type(words, jnp.int32)


def decode_batch(words: jnp.ndarray, combo: Combo, cap: int, n, dt_base):
    """Traced: ONE uint32 buffer -> (key_ids, ts_rel, valid, cols).

    `n` and `dt_base` are device scalars (no recompile per batch). Rows
    past n are masked invalid, so padding never reaches the lattice.
    """
    off = 0
    streams: dict[str, jnp.ndarray] = {}
    for plan in combo:
        w = plan.words(cap)
        streams[plan.name] = _unpack_stream(plan, words[off:off + w], cap)
        off += w
    key_ids = streams.pop("__kid")
    ts = streams.pop("__dt") + dt_base
    valid = jnp.arange(cap, dtype=jnp.int32) < n
    if "__valid" in streams:
        valid = valid & streams.pop("__valid")
    return key_ids, ts, valid, streams


class BitpackTransport:
    """Per-query encoder with sticky adaptive per-column encoding.

    Policies are monotone (u8 -> u16 -> raw32; dec -> raw32) so the set
    of combos — and therefore jit recompiles — is bounded over a query's
    lifetime.
    """

    def __init__(self) -> None:
        self._dec_scale: dict[str, int] = {}   # col -> last good scale
        self._demoted: set[str] = set()        # dec failed -> raw32 forever
        self._uint_width: dict[str, str] = {}  # stream -> widest enc so far

    def _widen_uint(self, name: str, vals: np.ndarray) -> str:
        cur = self._uint_width.get(name, ENC_U8)
        hi = int(vals.max()) if len(vals) else 0
        lo = int(vals.min()) if len(vals) else 0
        need = ENC_RAW_I32 if (lo < 0 or hi > 0xFFFF) else \
            ENC_U16 if hi > 0xFF else ENC_U8
        order = (ENC_U8, ENC_U16, ENC_RAW_I32)
        enc = order[max(order.index(cur), order.index(need))]
        self._uint_width[name] = enc
        return enc

    def _plan_float(self, name: str, vals: np.ndarray) -> StreamPlan:
        if name in self._demoted:
            return StreamPlan(name, ENC_RAW_F32)
        scales = [self._dec_scale[name]] if name in self._dec_scale \
            else list(DEC_SCALES)
        v64 = np.asarray(vals, np.float64)
        v32 = np.asarray(vals, np.float32)
        for s in scales:
            q = np.rint(v64 * s)
            # NaN/inf fail the range check and demote to raw32; the
            # round-trip check mirrors the device decode formula exactly
            if (np.abs(q) <= DEC_LIMIT).all() and \
                    (q.astype(np.float32) * np.float32(1.0 / s)
                     == v32).all():
                self._dec_scale[name] = s
                return StreamPlan(name, ENC_DEC, s)
        self._demoted.add(name)
        self._dec_scale.pop(name, None)
        return StreamPlan(name, ENC_RAW_F32)

    def encode(self, cap: int, n: int, key_ids: np.ndarray,
               ts_rel: np.ndarray,
               cols: Mapping[str, np.ndarray],
               layout: tuple[tuple[str, str], ...],
               valid: np.ndarray | None = None,
               null_streams: Mapping[str, np.ndarray] | None = None,
               ) -> tuple[Combo, int, np.ndarray]:
        """Encode one micro-batch -> (combo, dt_base, uint32 words).

        `layout` is the (name, "f32"|"i32"|"bool") column layout from the
        executor. `null_streams` maps __null_a{i} flag-stream names to
        bool arrays (each becomes a bool8 stream; absent means no nulls).
        """
        plans: list[StreamPlan] = []
        streams: list[np.ndarray] = []

        plans.append(StreamPlan("__kid", self._widen_uint("__kid",
                                                          key_ids[:n])))
        streams.append(key_ids[:n])

        dt_base = int(np.asarray(ts_rel[:n]).min()) if n else 0
        dt = np.asarray(ts_rel[:n], np.int64) - dt_base
        plans.append(StreamPlan("__dt", self._widen_uint("__dt", dt)))
        streams.append(dt)

        if valid is not None:
            plans.append(StreamPlan("__valid", ENC_BOOL8))
            streams.append(valid[:n])

        for name, tag in layout:
            vals = np.asarray(cols[name])[:n]
            if tag == "f32":
                plan = self._plan_float(name, vals)
            elif tag == "bool":
                plan = StreamPlan(name, ENC_BOOL8)
            else:
                plan = StreamPlan(name, self._widen_uint(name, vals))
            plans.append(plan)
            streams.append(vals)
        for name, mask in (null_streams or {}).items():
            plans.append(StreamPlan(name, ENC_BOOL8))
            streams.append(mask[:n])

        combo = tuple(plans)
        total = sum(p.words(cap) for p in combo)
        words = np.empty(total, np.uint32)
        off = 0
        for plan, vals in zip(combo, streams):
            w = plan.words(cap)
            words[off:off + w] = _pack_stream(plan, vals, cap)
            off += w
        return combo, dt_base, words
