"""Bit-packed columnar host->device transport (v3).

The ingest wall on real deployments is the host->device link: every byte
of a record batch crosses PCIe (or, on tunneled dev chips, a far slower
link), so wire bytes per event — not host CPU and not device FLOPs — set
the throughput ceiling. This module is the engine's answer: a
Parquet-style adaptive columnar codec that encodes each micro-batch into
ONE uint32 buffer, decoded on-device inside the jitted step (shifts and
masks on the VPU, fused into the aggregation kernel by XLA).

v3 packs at TRUE bit granularity with a per-batch integer base per
stream (the base vector rides as a tiny device argument, so changing
bases never recompiles):

  bp      unsigned bit-pack of (v - base) at `bits` bits per value,
          contiguous across word boundaries; bits=0 encodes a constant
          column in zero words
  bpd     delta pack for NONDECREASING streams (timestamps): packs the
          first differences, device reconstructs with a cumsum — a
          sorted ms-resolution time column costs ~1 bit/event
  bool1   bools / null bitmaps at one bit per value
  dec     decimal floats: round(v*scale) quantization, then bp of
          (q - qmin); encodes iff the exact f32 round-trip
          decode(encode(v)) == v holds elementwise (verified per batch,
          falls back to raw32 otherwise) — device decode is
          (base + u) * (1/scale), a single IEEE multiply that matches
          the host verifier bit-for-bit
  raw32   f32 bitcast or i32, the lossless fallback

Width policies are sticky and monotone-widening (bits only grow; bpd
and dec demote at most once), so the set of combos — and therefore jit
specializations — stays bounded over a query's lifetime.

The reference has no analogue (its ingest is per-record protobuf over a
local socket — hstream-store cbits append path); this is TPU-first
design: the wire format exists so the MXU/VPU never starves behind the
link. Typical footprint on the headline workload (1k keys, sorted ms
timestamps, one decimal-quantized payload): 10-bit key + 1-bit time
delta + ~10-bit dec payload ≈ 2.7 bytes/event, vs 5 in the byte-aligned
v2 codec and 16 in the naive int32 transport.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Mapping

import jax
import jax.numpy as jnp
import numpy as np

ENC_BP = "bp"
ENC_BPD = "bpd"
ENC_BOOL = "bool1"
ENC_DEC = "dec"
ENC_RAW_F32 = "rawf"
ENC_RAW_I32 = "rawi"

DEC_SCALES = (1, 10, 100)  # fixed-point scales tried for float columns
DEC_MAX_Q = 1 << 30        # |q| bound: base+u must stay in int32
DEC_MAX_BITS = 24          # wider ranges fall back to raw32

# only streams known to be time-ordered attempt delta packing (bounded
# combo churn: everything else would demote on the first unsorted batch)
_DELTA_STREAMS = frozenset({"__dt"})


@dataclass(frozen=True)
class StreamPlan:
    """Encoding of one logical stream; part of the jit specialization key."""

    name: str          # "__kid", "__dt", "__valid", or a column name
    enc: str
    scale: int = 0     # ENC_DEC only
    bits: int = 0      # bp/bpd/dec width (bool1 is implicitly 1)

    def words(self, cap: int) -> int:
        if self.enc in (ENC_RAW_F32, ENC_RAW_I32):
            return cap
        b = 1 if self.enc == ENC_BOOL else self.bits
        # +1 pad word so the device's two-word gather never reads OOB
        return (cap * b + 31) // 32 + 1


Combo = tuple[StreamPlan, ...]


def wire_bytes(combo: Combo, cap: int) -> int:
    return 4 * sum(p.words(cap) for p in combo)


# quantized width ladder: widths only take these values, so a stream
# whose range creeps up recompiles the fused decode+aggregate step at
# most len(ladder) times, not once per bit (recompiles are seconds)
_BIT_LADDER = (0, 1, 2, 3, 4, 5, 6, 8, 10, 12, 16, 20, 24, 28, 32)


def _bits_for(hi: int) -> int:
    """Smallest ladder width holding values in [0, hi]."""
    need = int(hi).bit_length()
    for b in _BIT_LADDER:
        if b >= need:
            return b
    return 32


def _bitpack(vals: np.ndarray, bits: int, cap: int) -> np.ndarray:
    """Pack uint values (< 2**bits) at `bits` bits each into uint32
    words (+1 pad). Vectorized: values are laid out in blocks of 32 —
    a block spans exactly `bits` words, so per-lane shifts/offsets are
    compile-time constants and the pack is 32 vectorized ORs."""
    nw = (cap * bits + 31) // 32 + 1
    n = len(vals)
    if bits == 0 or n == 0:
        return np.zeros(nw, np.uint32)
    if bits == 32:
        out = np.zeros(nw, np.uint32)
        out[:n] = vals.astype(np.uint32)
        return out
    q = -(-n // 32)  # blocks
    v = np.zeros(q * 32, np.uint64)
    v[:n] = vals.astype(np.uint64)
    # transposed [32, q] layout: lane r is a CONTIGUOUS row, so the 32
    # shift/or ops below stream through memory instead of striding.
    # lane r lands in in-block word (r*bits)>>5 <= bits-1, so a block's
    # cells never spill past its own `bits` words; the sub-word carry
    # into the next 32-bit word is handled by the u64 lo/hi fold below.
    vt = np.ascontiguousarray(v.reshape(q, 32).T)
    buft = np.zeros((bits, q), np.uint64)
    for r in range(32):
        dr = (r * bits) >> 5
        sr = (r * bits) & 31
        buft[dr] |= vt[r] << np.uint64(sr)
    cells = np.zeros(q * bits + 1, np.uint64)
    cells[: q * bits] = buft.T.reshape(q * bits)
    out = np.zeros(nw, np.uint32)
    m = min(nw, len(cells))
    out[:m] = (cells[:m] & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    out[1:m] |= (cells[: m - 1] >> np.uint64(32)).astype(np.uint32)
    return out


def _bp_decode(words: jnp.ndarray, bits: int, cap: int) -> jnp.ndarray:
    """Traced: unpack `cap` uint values of `bits` bits -> int32 [cap].

    Block-structured: 32 values span exactly `bits` words, so lane r of
    every block reads words (r*bits)>>5 [and +1] at a COMPILE-TIME
    shift — the whole unpack is static slices + shifts (VPU-friendly),
    no dynamic gathers."""
    if bits == 0:
        return jnp.zeros(cap, jnp.int32)
    if bits == 32:
        return words[:cap].astype(jnp.int32)
    mask = jnp.uint32((1 << bits) - 1)
    if cap % 32 == 0:
        q = cap // 32
        w = words[: q * bits].reshape(q, bits)
        lanes = []
        for r in range(32):
            dr = (r * bits) >> 5
            sr = (r * bits) & 31
            v = w[:, dr] >> jnp.uint32(sr)
            if sr + bits > 32:
                v = v | (w[:, dr + 1] << jnp.uint32(32 - sr))
            lanes.append(v & mask)
        return jnp.stack(lanes, axis=1).reshape(cap).astype(jnp.int32)
    # odd capacities (not produced by the executor): gather fallback
    pos = jnp.arange(cap, dtype=jnp.int32) * bits
    w0 = pos >> 5
    sh = (pos & 31).astype(jnp.uint32)
    lo = words[w0] >> sh
    hi = jnp.where(sh == jnp.uint32(0), jnp.uint32(0),
                   words[w0 + 1] << (jnp.uint32(32) - sh))
    return ((lo | hi) & mask).astype(jnp.int32)


def _unpack_stream(plan: StreamPlan, words: jnp.ndarray, cap: int, base):
    """Traced device decode of one stream -> [cap] array."""
    if plan.enc == ENC_RAW_F32:
        return jax.lax.bitcast_convert_type(words[:cap], jnp.float32)
    if plan.enc == ENC_RAW_I32:
        return jax.lax.bitcast_convert_type(words[:cap], jnp.int32)
    if plan.enc == ENC_BOOL:
        return _bp_decode(words, 1, cap) != 0
    u = _bp_decode(words, plan.bits, cap)
    if plan.enc == ENC_BPD:
        return base + jnp.cumsum(u)
    v = base + u
    if plan.enc == ENC_DEC:
        # single IEEE multiply — bit-identical between numpy (the
        # encoder's verifier) and XLA, unlike division by a constant,
        # which XLA strength-reduces
        return v.astype(jnp.float32) * jnp.float32(1.0 / plan.scale)
    return v


def decode_batch(words: jnp.ndarray, combo: Combo, cap: int, n, bases):
    """Traced: ONE uint32 buffer -> (key_ids, ts_rel, valid, cols).

    `n` (scalar) and `bases` (i32 [len(combo)], per-stream integer base)
    are device values — changing them never recompiles. Rows past n are
    masked invalid, so padding never reaches the lattice.
    """
    off = 0
    streams: dict[str, jnp.ndarray] = {}
    for i, plan in enumerate(combo):
        w = plan.words(cap)
        streams[plan.name] = _unpack_stream(plan, words[off:off + w], cap,
                                            bases[i])
        off += w
    key_ids = streams.pop("__kid")
    ts = streams.pop("__dt")
    valid = jnp.arange(cap, dtype=jnp.int32) < n
    if "__valid" in streams:
        valid = valid & streams.pop("__valid")
    return key_ids, ts, valid, streams


def _lib():
    from hstream_tpu.engine import codec_native

    return codec_native.load()


def _ptr(arr: np.ndarray, ctype):
    import ctypes as C

    return arr.ctypes.data_as(C.POINTER(ctype))


def _native_minmax(lib, v: np.ndarray) -> tuple[int, int]:
    import ctypes as C

    lo = C.c_int64()
    hi = C.c_int64()
    if v.dtype == np.int32:
        lib.enc_minmax_i32(_ptr(v, C.c_int32), len(v),
                           C.byref(lo), C.byref(hi))
    else:
        lib.enc_minmax_i64(_ptr(v, C.c_int64), len(v),
                           C.byref(lo), C.byref(hi))
    return lo.value, hi.value


class BitpackTransport:
    """Per-query encoder with sticky adaptive per-column encoding.

    Policies are monotone (bits only widen; bpd -> bp and dec -> raw32
    demote at most once) so the set of combos — and therefore jit
    recompiles — is bounded over a query's lifetime. The per-element
    passes (stats, quantize, pack) run in the native codec kernels
    (cpp/encode.cpp) when buildable, with pure-numpy fallbacks.

    Thread-safety: encode() may be called CONCURRENTLY from several
    pipeline encode workers without a lock. Each call's returned
    (combo, bases, words) triple is built only from call-local state,
    so every batch is self-describing regardless of interleaving; the
    adaptive dicts/sets (_bits, _dec_scale, _demoted, ...) are touched
    only via single GIL-atomic get/set/add ops, and a racy lost update
    merely delays a sticky widening/demotion by one batch (costing at
    most one extra jit specialization later, never a wrong decode).
    """

    def __init__(self) -> None:
        self._dec_scale: dict[str, int] = {}   # col -> last good scale
        self._demoted: set[str] = set()        # dec failed -> raw32 forever
        self._raw_int: set[str] = set()        # int stream too wide -> raw32
        self._bits: dict[str, int] = {}        # stream -> widest bits so far
        self._no_delta: set[str] = set()       # bpd failed -> bp forever

    def _widen(self, name: str, need: int) -> int:
        bits = max(self._bits.get(name, 0), need)
        self._bits[name] = bits
        return bits

    def _plan_uint(self, name: str, vals: np.ndarray
                   ) -> tuple[StreamPlan, int, np.ndarray]:
        """(plan, base, payload) for an integer stream. The payload is
        the RAW contiguous array; _pack_into applies base/diff."""
        lib = _lib()
        v = np.ascontiguousarray(vals)
        if v.dtype not in (np.int32, np.int64):
            v = v.astype(np.int64)
        if len(v) == 0:
            return StreamPlan(name, ENC_BP, bits=0), 0, v
        if name in _DELTA_STREAMS and name not in self._no_delta:
            v64 = v if v.dtype == np.int64 else v.astype(np.int64)
            if lib is not None:
                import ctypes as C

                dmax = C.c_int64()
                ok = lib.enc_diff_stats_i64(_ptr(v64, C.c_int64),
                                            len(v64), C.byref(dmax))
                ok, dmax = bool(ok), dmax.value
            else:
                d = np.diff(v64)
                ok = len(d) == 0 or d.min() >= 0
                dmax = int(d.max()) if ok and len(d) else 0
            if ok:
                bits = self._widen(name + "#d", _bits_for(dmax))
                return (StreamPlan(name, ENC_BPD, bits=bits),
                        int(v64[0]), v64)
            self._no_delta.add(name)
        if lib is not None:
            lo, hi = _native_minmax(lib, v)
        else:
            lo, hi = int(v.min()), int(v.max())
        if name in self._raw_int or lo < -(1 << 30) or hi > (1 << 30):
            self._raw_int.add(name)
            return StreamPlan(name, ENC_RAW_I32), 0, v
        bits = self._widen(name, _bits_for(hi - lo))
        return StreamPlan(name, ENC_BP, bits=bits), lo, v

    def _plan_float(self, name: str, vals: np.ndarray
                    ) -> tuple[StreamPlan, int, np.ndarray]:
        """(plan, base, payload): payload is the quantized int32 array
        for dec, or the raw floats for raw32."""
        if name in self._demoted:
            return StreamPlan(name, ENC_RAW_F32), 0, vals
        lib = _lib()
        # single atomic read: a concurrent encode worker demoting this
        # column pops the scale between a `in` check and a subscript
        sticky_scale = self._dec_scale.get(name)
        scales = [sticky_scale] if sticky_scale is not None \
            else list(DEC_SCALES)
        # all-f32 quantization; any rounding discrepancy vs a wider path
        # is caught by the round-trip verification, the actual guarantee
        v32 = np.ascontiguousarray(vals, np.float32)
        for s in scales:
            if lib is not None:
                import ctypes as C

                q = np.empty(len(v32), np.int32)
                qlo = C.c_int64()
                qhi = C.c_int64()
                ok = lib.enc_quantize_f32(
                    _ptr(v32, C.c_float), len(v32), C.c_float(s),
                    C.c_float(np.float32(1.0 / s)), DEC_MAX_Q,
                    _ptr(q, C.c_int32), C.byref(qlo), C.byref(qhi))
                if not ok:
                    continue
                qmin, qmax = qlo.value, qhi.value
            else:
                qf = np.rint(v32 * np.float32(s))
                with np.errstate(invalid="ignore"):
                    if not (np.abs(qf) <= DEC_MAX_Q).all():
                        continue
                q = qf.astype(np.int32)
                # mirrors the device decode formula exactly
                if not (q.astype(np.float32) * np.float32(1.0 / s)
                        == v32).all():
                    continue
                qmin, qmax = int(q.min()), int(q.max())
            span_bits = _bits_for(qmax - qmin)
            if span_bits > DEC_MAX_BITS:
                continue
            self._dec_scale[name] = s
            bits = self._widen(name, span_bits)
            return StreamPlan(name, ENC_DEC, scale=s, bits=bits), qmin, q
        self._demoted.add(name)
        self._dec_scale.pop(name, None)
        return StreamPlan(name, ENC_RAW_F32), 0, vals

    def _pack_into(self, plan: StreamPlan, base: int, payload: np.ndarray,
                   out: np.ndarray, cap: int) -> None:
        """Pack one stream into its slice of the words buffer."""
        n = len(payload)
        if plan.enc == ENC_RAW_F32:
            buf = np.zeros(cap, np.float32)
            buf[:n] = payload
            out[:] = buf.view(np.uint32)
            return
        if plan.enc == ENC_RAW_I32:
            buf = np.zeros(cap, np.int32)
            buf[:n] = payload
            out[:] = buf.view(np.uint32)
            return
        lib = _lib()
        if lib is not None:
            import ctypes as C

            p_out = _ptr(out, C.c_uint32)
            if plan.enc == ENC_BOOL:
                b = np.ascontiguousarray(payload, np.uint8)
                lib.enc_pack_bool(_ptr(b, C.c_uint8), n, p_out, len(out))
            elif plan.enc == ENC_BPD:
                lib.enc_pack_diff_i64(_ptr(payload, C.c_int64), n,
                                      plan.bits, p_out, len(out))
            elif payload.dtype == np.int32:
                lib.enc_pack_i32(_ptr(payload, C.c_int32), n, base,
                                 plan.bits, p_out, len(out))
            else:
                lib.enc_pack_i64(_ptr(payload, C.c_int64), n, base,
                                 plan.bits, p_out, len(out))
            return
        if plan.enc == ENC_BOOL:
            out[:] = _bitpack(np.asarray(payload, np.uint8), 1, cap)
        elif plan.enc == ENC_BPD:
            d = np.diff(payload, prepend=payload[0] if n else 0)
            out[:] = _bitpack(d, plan.bits, cap)
        else:
            out[:] = _bitpack(
                np.asarray(payload, np.int64) - base, plan.bits, cap)

    def encode(self, cap: int, n: int, key_ids: np.ndarray,
               ts_rel: np.ndarray,
               cols: Mapping[str, np.ndarray],
               layout: tuple[tuple[str, str], ...],
               valid: np.ndarray | None = None,
               null_streams: Mapping[str, np.ndarray] | None = None,
               ) -> tuple[Combo, np.ndarray, np.ndarray]:
        """Encode one micro-batch -> (combo, bases i32, uint32 words).

        `layout` is the (name, "f32"|"i32"|"bool") column layout from the
        executor. `null_streams` maps __null_a{i} flag-stream names to
        bool arrays (each becomes a 1-bit stream; absent means no nulls).
        """
        plans: list[StreamPlan] = []
        bases: list[int] = []
        payloads: list[np.ndarray] = []

        def add(plan: StreamPlan, base: int, payload: np.ndarray) -> None:
            plans.append(plan)
            bases.append(base)
            payloads.append(payload)

        add(*self._plan_uint("__kid", key_ids[:n]))
        add(*self._plan_uint("__dt", np.asarray(ts_rel[:n], np.int64)))
        if valid is not None:
            add(StreamPlan("__valid", ENC_BOOL), 0,
                np.asarray(valid[:n], np.bool_))

        for name, tag in layout:
            vals = np.asarray(cols[name])[:n]
            if tag == "f32":
                add(*self._plan_float(name, vals))
            elif tag == "bool":
                add(StreamPlan(name, ENC_BOOL), 0,
                    np.asarray(vals, np.bool_))
            else:
                add(*self._plan_uint(name, vals))
        for name, mask in (null_streams or {}).items():
            add(StreamPlan(name, ENC_BOOL), 0,
                np.asarray(mask[:n], np.bool_))

        combo = tuple(plans)
        total = sum(p.words(cap) for p in combo)
        words = np.empty(total, np.uint32)
        off = 0
        for plan, base, payload in zip(combo, bases, payloads):
            w = plan.words(cap)
            self._pack_into(plan, base, payload, words[off:off + w], cap)
            off += w
        return combo, np.asarray(bases, np.int32), words
