"""Window specifications.

Reference semantics (hstream-processing Stream/TimeWindows.hs:23-43):
tumbling = hopping with advance == size; grace defaults to 24h; a record
with timestamp ts belongs to every window [s, s+size) with
s in (ts-size, ts] and s ≡ 0 (mod advance). Session windows
(SessionWindows.hs) merge records closer than `gap`.

Device mapping for fixed windows: window with start s occupies lattice
slot (s // advance) mod W, where W = ceil((size+grace)/advance) + 2 covers
every window that can still legally receive records, so a slot is never
reused before the host has closed and reset it.
"""

from __future__ import annotations

from dataclasses import dataclass

DEFAULT_GRACE_MS = 24 * 3600 * 1000


@dataclass(frozen=True)
class TumblingWindow:
    size_ms: int
    grace_ms: int = DEFAULT_GRACE_MS

    @property
    def advance_ms(self) -> int:
        return self.size_ms

    @property
    def windows_per_record(self) -> int:
        return 1


@dataclass(frozen=True)
class HoppingWindow:
    size_ms: int
    advance_ms: int
    grace_ms: int = DEFAULT_GRACE_MS

    def __post_init__(self):
        if self.size_ms % self.advance_ms != 0:
            raise ValueError("hop size must be a multiple of advance")

    @property
    def windows_per_record(self) -> int:
        return self.size_ms // self.advance_ms


@dataclass(frozen=True)
class SessionWindow:
    gap_ms: int
    grace_ms: int = DEFAULT_GRACE_MS


FixedWindow = TumblingWindow | HoppingWindow
WindowSpec = TumblingWindow | HoppingWindow | SessionWindow


def num_slots(w: FixedWindow) -> int:
    """In-flight slot count W for the state lattice."""
    return (w.size_ms + w.grace_ms + w.advance_ms - 1) // w.advance_ms + 2
