"""Stateless query execution: SELECT without aggregation.

The reference runs these as per-record filter/map processors in the task
DAG (Stream.hs:63-211). Here non-aggregating queries are host-side row
transforms over decoded micro-batches — they carry no device state, and
ingest decode dominates their cost; vectorizing them onto the device
buys nothing until the native columnar ingest path lands.
"""

from __future__ import annotations

from typing import Any, Mapping, Sequence

from hstream_tpu.common.errors import SQLCodegenError
from hstream_tpu.engine.expr import eval_host
from hstream_tpu.engine.plan import (
    FilterNode,
    PlanNode,
    ProjectNode,
    SourceNode,
)


class StatelessExecutor:
    """Filter + projection over row batches (no window, no state)."""

    def __init__(self, node: PlanNode):
        self.filters = []
        self.projections = None
        n = node
        while not isinstance(n, SourceNode):
            if isinstance(n, ProjectNode):
                if self.projections is not None:
                    raise SQLCodegenError("multiple projection nodes")
                self.projections = n.exprs
                n = n.child
            elif isinstance(n, FilterNode):
                self.filters.append(n.predicate)
                n = n.child
            else:
                raise SQLCodegenError(
                    f"stateless plan cannot contain {type(n).__name__}")
        self.source = n

    def process(self, rows: Sequence[Mapping[str, Any]],
                ts_ms: Sequence[int] | None = None
                ) -> list[dict[str, Any]]:
        out = []
        for row in rows:
            try:
                if any(not eval_host(p, row) for p in self.filters):
                    continue
            except (TypeError, KeyError):
                continue  # NULL operand -> predicate not true (SQL)
            if self.projections is None:
                out.append(dict(row))
            else:
                proj = {}
                for name, expr in self.projections:
                    try:
                        proj[name] = eval_host(expr, row)
                    except (TypeError, KeyError):
                        proj[name] = None
                out.append(proj)
        return out
