"""Operator-state snapshots: serialize / restore executor state.

The reference checkpoints only READER positions (three backends,
hstream-store/HStream/Store/Internal/LogDevice/Checkpoint.hs:37-46);
operator state lives in in-memory KV stores (mkInMemoryStateKVStore,
Codegen.hs:374-385), so a restarted query silently re-aggregates from
the checkpoint — every window spanning the restart undercounts. SURVEY
§7 item 8 asks to beat that: here the FULL operator state — lattice
planes, key dictionary, string dictionaries, epoch/watermark/open
windows, session state, join side-stores — serializes to one blob,
written ATOMICALLY with the read checkpoints it corresponds to, so
resume is exact (at-least-once only across the sink boundary: rows
emitted after the last snapshot are re-emitted on replay).

Wire format: a single .npz container; entry "__meta__" is UTF-8 JSON
(uint8 array), remaining entries are numpy arrays referenced from the
meta. Nested executors (a join's inner aggregate) embed their own npz
blob as a uint8 array.
"""

from __future__ import annotations

import io
import json
import math
import struct
import zlib
from typing import Any

import jax
import numpy as np

from hstream_tpu.common.errors import SQLCodegenError, StoreError
from hstream_tpu.engine.types import ColumnType, Schema, StringDictionary

SNAPSHOT_VERSION = 1

# ---- CRC-sealed blob framing ------------------------------------------------
#
# A snapshot blob written to the meta KV is sealed with a magic + crc32
# + length header so a torn or bit-rotted write is DETECTED at restore
# instead of surfacing as a numpy/JSON parse error (or worse, parsing
# into wrong state). The two-slot last-good rotation in
# server.tasks relies on this: a corrupt newest slot falls back to the
# previous sealed slot and replays the gap.

SEAL_MAGIC = b"HSNP1\x00"
_SEAL_HEADER = len(SEAL_MAGIC) + 8  # + u32 crc + u32 length


class SnapshotCorrupt(StoreError):
    """A sealed snapshot blob failed its integrity check."""


def seal_blob(blob: bytes) -> bytes:
    """Frame a snapshot blob with magic + crc32 + length."""
    return (SEAL_MAGIC
            + struct.pack("<II", zlib.crc32(blob) & 0xFFFFFFFF,
                          len(blob))
            + blob)


def open_blob(data: bytes) -> bytes:
    """Verify and unwrap a sealed blob. Legacy blobs (pre-seal raw npz,
    which always starts with the zip magic ``PK``) pass through
    unverified so snapshots written by older servers still restore.
    Raises SnapshotCorrupt on truncation or checksum mismatch."""
    if data.startswith(b"PK"):
        return data  # legacy unsealed npz
    if not data.startswith(SEAL_MAGIC):
        raise SnapshotCorrupt(
            f"snapshot blob has neither seal nor npz magic "
            f"({data[:6]!r})")
    if len(data) < _SEAL_HEADER:
        raise SnapshotCorrupt("snapshot blob truncated inside header")
    crc, length = struct.unpack_from("<II", data, len(SEAL_MAGIC))
    blob = data[_SEAL_HEADER:]
    if len(blob) != length:
        raise SnapshotCorrupt(
            f"snapshot blob truncated: {len(blob)} of {length} bytes")
    if (zlib.crc32(blob) & 0xFFFFFFFF) != crc:
        raise SnapshotCorrupt("snapshot blob checksum mismatch")
    return blob


# ---- tagged JSON for scalars JSON cannot carry ------------------------------

def _enc(v: Any) -> Any:
    if isinstance(v, np.ndarray):
        return {"__nd__": v.dtype.str, "d": v.tolist()}
    if isinstance(v, tuple):
        return {"__tp__": [_enc(x) for x in v]}
    if isinstance(v, float) and math.isinf(v):
        return {"__inf__": 1 if v > 0 else -1}
    if isinstance(v, dict):
        return {k: _enc(x) for k, x in v.items()}
    if isinstance(v, list):
        return [_enc(x) for x in v]
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    return v


def _dec(v: Any) -> Any:
    if isinstance(v, dict):
        if "__nd__" in v:
            return np.asarray(v["d"], dtype=np.dtype(v["__nd__"]))
        if "__tp__" in v:
            return tuple(_dec(x) for x in v["__tp__"])
        if "__inf__" in v:
            return math.inf if v["__inf__"] > 0 else -math.inf
        return {k: _dec(x) for k, x in v.items()}
    if isinstance(v, list):
        return [_dec(x) for x in v]
    return v


def _pack(meta: dict, arrays: dict[str, np.ndarray]) -> bytes:
    buf = io.BytesIO()
    meta_bytes = np.frombuffer(json.dumps(meta).encode("utf-8"),
                               dtype=np.uint8)
    np.savez(buf, __meta__=meta_bytes, **arrays)
    return buf.getvalue()


def _unpack(blob: bytes) -> tuple[dict, dict[str, np.ndarray]]:
    with np.load(io.BytesIO(blob)) as z:
        meta = json.loads(bytes(z["__meta__"].tobytes()).decode("utf-8"))
        arrays = {k: z[k] for k in z.files if k != "__meta__"}
    return meta, arrays


# ---- executor dispatch ------------------------------------------------------

def capture_executor(ex, extra: dict | None = None
                     ) -> tuple[dict, dict[str, Any]]:
    """Phase 1: take a CONSISTENT capture of an executor's state.

    Designed to be cheap enough to run under the executor's state lock:
    device arrays are captured by reference (jax arrays are immutable —
    steps replace the state dict, never mutate buffers), host structures
    are shallow-copied or encoded. Heavy work (device->host sync,
    npz/zlib packing) happens in serialize_capture() WITHOUT the lock."""
    from hstream_tpu.engine.executor import QueryExecutor
    from hstream_tpu.engine.join import JoinExecutor, TableJoinExecutor
    from hstream_tpu.engine.session import SessionExecutor
    from hstream_tpu.engine.stateless import StatelessExecutor

    if isinstance(ex, QueryExecutor):
        meta, arrays = _lattice_state(ex)
    elif isinstance(ex, SessionExecutor):
        meta, arrays = _session_state(ex), {}
    elif isinstance(ex, TableJoinExecutor):
        meta, arrays = _table_join_state(ex)
    elif isinstance(ex, JoinExecutor):
        meta, arrays = _join_state(ex)
    elif isinstance(ex, StatelessExecutor):
        meta, arrays = {"kind": "stateless"}, {}
    else:
        raise SQLCodegenError(
            f"cannot snapshot {type(ex).__name__}")
    meta["version"] = SNAPSHOT_VERSION
    meta["extra"] = extra or {}
    return meta, arrays


def serialize_capture(meta: dict, arrays: dict[str, Any]) -> bytes:
    """Phase 2: heavy serialization of a capture (no lock needed)."""
    return _pack(meta, {k: np.asarray(v) for k, v in arrays.items()})


def snapshot_executor(ex, extra: dict | None = None) -> bytes:
    """Serialize any executor's state to bytes. `extra` (JSON-able, e.g.
    the read checkpoints this state corresponds to) rides in the blob so
    the state/ckp pair is one atomic write."""
    meta, arrays = capture_executor(ex, extra)
    return serialize_capture(meta, arrays)


def restore_executor(plan, blob: bytes, *, initial_keys: int = 1024,
                     batch_capacity: int = 4096, mesh=None):
    """Rebuild an executor from a snapshot blob for a lowered SELECT
    plan. Returns (executor, extra). With `mesh`, lattice state restores
    into a ShardedQueryExecutor (snapshots are mesh-portable: capture
    merges shard partials into ONE canonical lattice, restore scatters
    it back — see _scatter_state)."""
    meta, arrays = _unpack(blob)
    ver = meta.get("version")
    if ver != SNAPSHOT_VERSION:
        raise SQLCodegenError(
            f"snapshot format version {ver!r} != supported "
            f"{SNAPSHOT_VERSION}; refusing to deserialize")
    kind = meta["kind"]
    if kind == "tablejoin":
        ex = _restore_table_join(plan, meta, arrays,
                                 initial_keys=initial_keys,
                                 batch_capacity=batch_capacity)
    elif kind == "join":
        ex = _restore_join(plan, meta, arrays,
                           initial_keys=initial_keys,
                           batch_capacity=batch_capacity, mesh=mesh)
    elif kind == "lattice":
        ex = _restore_lattice(plan.node, meta, arrays,
                              batch_capacity=batch_capacity, mesh=mesh)
    elif kind == "session":
        ex = _restore_session(plan.node, meta, mesh=mesh)
    elif kind == "stateless":
        from hstream_tpu.engine.stateless import StatelessExecutor

        ex = StatelessExecutor(plan.node)
    else:
        raise SQLCodegenError(f"unknown snapshot kind {kind!r}")
    return ex, meta.get("extra", {})


# ---- lattice (QueryExecutor) ------------------------------------------------

def _lattice_state(ex) -> tuple[dict, dict[str, np.ndarray]]:
    if ex._pending_closes:
        raise SQLCodegenError(
            "snapshot with deferred closes pending; drain_closed() first")
    if getattr(ex, "_pending_changes", None) \
            or getattr(ex, "_drain_futs", None):
        # the touched mask was already cleared on device: the queued
        # extracts (and any in-flight async drains) are the ONLY copy
        # of those change rows
        raise SQLCodegenError(
            "snapshot with deferred changes pending; flush_changes() "
            "first")
    meta = {
        "kind": "lattice",
        "n_keys": ex.spec.n_keys,
        "batch_capacity": ex.batch_capacity,
        "epoch": ex.epoch,
        "watermark_abs": ex.watermark_abs,
        "emit_changes": ex.emit_changes,
        "open": [[s, ow.slot] for s, ow in sorted(ex._open.items())],
        "key_rev": [_enc(k) for k in ex._key_rev],
        "dicts": {name: d._values for name, d in ex.dicts.items()},
        "null_sticky": sorted(ex._null_sticky),
        "schema": [[n, t.value] for n, t in ex.schema.fields],
    }
    # by reference: jax arrays are immutable; np.asarray (the device sync)
    # happens in serialize_capture, outside the caller's lock.
    # Sharded executors (leading data axis on every plane) canonicalize:
    # merge the partial lattices with each plane's monoid op so the blob
    # is mesh-portable (restorable single-chip or onto any mesh).
    if hasattr(ex, "_sharded"):
        arrays = {f"s/{k}": v
                  for k, v in _merge_partials(ex).items()}
    else:
        arrays = {f"s/{k}": v for k, v in ex.state.items()}
    return meta, arrays


def _merge_partials(ex) -> dict[str, Any]:
    """Reduce the leading data axis of a sharded executor's state with
    each plane's merge monoid -> canonical [K, W, ...] state (exact: all
    accumulators are commutative monoids, lattice.plane_merge_kinds).

    The reductions are DISPATCHED on device (jnp, async) so this stays
    cheap under the caller's state lock; the host sync happens in
    serialize_capture's np.asarray, outside the lock."""
    import jax.numpy as jnp

    from hstream_tpu.engine import lattice

    kinds = lattice.plane_merge_kinds(ex.spec)
    out = {}
    for k, v in ex.state.items():
        kind = kinds.get(k, "sum")
        if kind == "min":
            out[k] = jnp.min(v, axis=0)
        elif kind == "max":
            out[k] = (jnp.any(v, axis=0) if v.dtype == jnp.bool_
                      else jnp.max(v, axis=0).astype(v.dtype))
        elif kind == "sum":
            out[k] = jnp.sum(v, axis=0).astype(v.dtype)
        else:
            # e.g. "topk": summing shard partials would corrupt state.
            # Sharded execution currently rejects such specs upstream;
            # fail loudly if that restriction is ever lifted.
            raise SQLCodegenError(
                f"no shard-merge rule for plane {k!r} (kind {kind!r})")
    return out


def _restore_lattice(node, meta, arrays, *, batch_capacity: int = 4096,
                     mesh=None):
    from hstream_tpu.engine.executor import QueryExecutor, _OpenWindow

    schema = Schema(tuple((n, ColumnType(t)) for n, t in meta["schema"]))
    cap = meta.get("batch_capacity", batch_capacity)
    if mesh is not None:
        from hstream_tpu.engine.plan import AggKind

        if any(a.kind in (AggKind.TOPK, AggKind.TOPK_DISTINCT)
               for a in node.aggs):
            mesh = None  # no elementwise shard merge for TOPK planes
    if mesh is not None:
        from hstream_tpu.parallel import ShardedQueryExecutor

        ex = ShardedQueryExecutor(
            node, schema, mesh=mesh, emit_changes=meta["emit_changes"],
            initial_keys=meta["n_keys"], batch_capacity=cap)
    else:
        ex = QueryExecutor(node, schema,
                           emit_changes=meta["emit_changes"],
                           initial_keys=meta["n_keys"],
                           batch_capacity=cap)
    # __init__ re-encodes string literals deterministically (same node,
    # same schema => same dictionary prefix), so overwriting the dict
    # contents with the snapshot's (literals + runtime values, in the
    # original insertion order) keeps compiled literal ids consistent.
    for name, values in meta["dicts"].items():
        d = StringDictionary()
        for v in values:
            d.encode(v)
        ex.dicts[name] = d
    ex._key_rev = [tuple(_dec(k)) for k in meta["key_rev"]]
    ex._key_ids = {k: i for i, k in enumerate(ex._key_rev)}
    ex.epoch = meta["epoch"]
    ex.watermark_abs = meta["watermark_abs"]
    ex._open = {s: _OpenWindow(start_abs=s, slot=slot)
                for s, slot in meta["open"]}
    ex._null_sticky = set(meta["null_sticky"])
    canonical = {k[len("s/"):]: v
                 for k, v in arrays.items() if k.startswith("s/")}
    if mesh is not None:
        ex.state = _scatter_state(ex, canonical)
    else:
        ex.state = {k: jax.device_put(v) for k, v in canonical.items()}
    return ex


def _scatter_state(ex, canonical: dict[str, np.ndarray]):
    """Install a canonical (merged) lattice into a sharded executor:
    data-shard 0 carries the whole canonical lattice, the other shards
    carry merge identities — their monoid merge at drain points yields
    exactly the canonical values."""
    from hstream_tpu.engine import lattice

    identities = lattice.init_state(ex.spec)
    sh = ex._sharded
    out = {}
    for k, v in canonical.items():
        if k not in identities:
            # plane from an older snapshot format (e.g. the removed
            # COUNT_ALL alias plane): now derived, safe to drop
            continue
        ident = np.asarray(identities[k])
        g = np.broadcast_to(ident[None],
                            (sh.n_data,) + ident.shape).copy()
        g[0] = v
        out[k] = jax.device_put(g, sh.state_sharding(k))
    return out


# ---- session ----------------------------------------------------------------

def _session_state(ex) -> dict:
    if getattr(ex, "_pending_closes", None):
        # the deferred extract buffers are the ONLY copy of those
        # closed-session rows (mirror entries already retired)
        raise SQLCodegenError(
            "snapshot with deferred session closes pending; "
            "drain_closed() first")
    # device-resident sessions serialize through the host-format view
    # (one pytree fetch + acc decode); restore rebuilds the host engine
    # and the device path re-activates and re-migrates lazily on the
    # next batch, like the join store
    src = (ex._host_sessions_view()
           if getattr(ex, "_dev", None) is not None else ex.sessions)
    sessions = [
        {"k": _enc(key),
         "s": [{"a": s.start, "b": s.end, "acc": _enc(s.accs)}
               for s in sess_list]}
        for key, sess_list in src.items()
    ]
    return {
        "kind": "session",
        "watermark": ex.watermark,
        "emit_changes": ex.emit_changes,
        "schema": [[n, t.value] for n, t in ex.schema.fields],
        "sessions": sessions,
    }


def _restore_session(node, meta, mesh=None):
    """Session snapshots are mesh-portable: the blob holds the gathered
    host view, so restoring with a different `mesh` (or none) just
    re-shards when the device path re-activates on the next batch."""
    from hstream_tpu.engine.session import SessionExecutor, _Session

    schema = Schema(tuple((n, ColumnType(t)) for n, t in meta["schema"]))
    kw = {} if mesh is None else {"mesh": mesh}
    ex = SessionExecutor(node, schema, emit_changes=meta["emit_changes"],
                         **kw)
    ex.watermark = meta["watermark"]
    for ent in meta["sessions"]:
        key = tuple(_dec(ent["k"]))
        ex.sessions[key] = [
            _Session(start=s["a"], end=s["b"], accs=_dec(s["acc"]))
            for s in ent["s"]]
    return ex


# ---- stream-table join ------------------------------------------------------


def _table_join_state(ex) -> tuple[dict, dict[str, np.ndarray]]:
    meta = {
        "kind": "tablejoin",
        "batch_capacity": ex._batch_capacity,
        "table": [{"k": _enc(key), "t": ts, "r": row}
                  for key, (ts, row) in ex.table.items()],
    }
    arrays = {}
    if ex._inner is not None:
        arrays["i/blob"] = np.frombuffer(snapshot_executor(ex._inner),
                                         dtype=np.uint8)
    return meta, arrays


def _restore_table_join(plan, meta, arrays, *, initial_keys: int,
                        batch_capacity: int):
    from hstream_tpu.engine.join import TableJoinExecutor

    ex = TableJoinExecutor(plan, initial_keys=initial_keys,
                           batch_capacity=meta.get("batch_capacity",
                                                   batch_capacity))
    for ent in meta["table"]:
        ex.table[tuple(_dec(ent["k"]))] = (int(ent["t"]), ent["r"])
    if "i/blob" in arrays:
        inner, _ = restore_executor(ex._inner_plan,
                                    arrays["i/blob"].tobytes(),
                                    initial_keys=initial_keys,
                                    batch_capacity=batch_capacity)
        ex._inner = inner
    return ex


# ---- join -------------------------------------------------------------------
#
# (The stream-TABLE join above stays single-chip — mesh_exclusion_reason
# keeps its keyed last-value state on the host — so its restore takes no
# mesh. The interval join below re-shards.)

def _join_state(ex) -> tuple[dict, dict[str, np.ndarray]]:
    if getattr(ex, "_staged", None) or getattr(ex, "_pending_matches",
                                               None):
        # coalesced matches / deferred device match buffers live
        # outside the inner executor's state; the owning runtime must
        # flush_staged() (sinking the emitted rows) before a snapshot,
        # like deferred changelog extracts
        raise SQLCodegenError(
            "snapshot with coalesced join matches staged; "
            "flush_staged() first")

    def dump_store(store):
        return [{"k": _enc(key), "t": tss, "r": rows}
                for key, (tss, rows) in store.by_key.items()]

    # device-resident stores serialize through the same host view
    # (fetch + row reconstruction from the packed needed columns);
    # restore refills the host stores and the device re-activates and
    # re-migrates lazily on the next probe
    stores = (ex._host_store_view() if hasattr(ex, "_host_store_view")
              else ex._stores)
    meta = {
        "kind": "join",
        "batch_capacity": ex._batch_capacity,
        "watermark": ex.watermark,
        "stores": {side: dump_store(st)
                   for side, st in stores.items()},
    }
    arrays = {}
    if ex._inner is not None:
        inner_blob = snapshot_executor(ex._inner)
        arrays["i/blob"] = np.frombuffer(inner_blob, dtype=np.uint8)
    return meta, arrays


def _restore_join(plan, meta, arrays, *, initial_keys: int,
                  batch_capacity: int, mesh=None):
    """Join snapshots are mesh-portable like session ones: the blob
    holds the gathered host store view; a different `mesh` re-shards
    both side stores when the device path re-activates."""
    from hstream_tpu.engine.join import JoinExecutor

    ex = JoinExecutor(plan, initial_keys=initial_keys,
                      batch_capacity=meta.get("batch_capacity",
                                              batch_capacity),
                      mesh=mesh)
    ex.watermark = meta["watermark"]
    for side, ents in meta["stores"].items():
        codes: list[int] = []
        tss: list[int] = []
        rows: list = []
        for ent in ents:
            key = tuple(_dec(ent["k"]))
            c = ex._jcode.get(key)
            if c is None:
                c = len(ex._jcode_rev)
                ex._jcode[key] = c
                ex._jcode_rev.append(key)
            for t, r in zip(ent["t"], ent["r"]):
                codes.append(c)
                tss.append(int(t))
                rows.append(r)
        if not codes:
            continue
        code_a = np.asarray(codes, np.int64)
        ts_a = np.asarray(tss, np.int64)
        rows_a = np.empty(len(rows), object)
        rows_a[:] = rows
        order = np.lexsort((ts_a, code_a))
        ex._stores[side].insert_sorted(code_a[order], ts_a[order],
                                       rows_a[order])
    if "i/blob" in arrays:
        # the downstream aggregate re-shards with the join: a mixed
        # sharded-join / single-chip-inner pair would refuse the fused
        # feed plan (correct, but a silent perf cliff)
        inner, _ = restore_executor(ex._inner_plan,
                                    arrays["i/blob"].tobytes(),
                                    initial_keys=initial_keys,
                                    batch_capacity=batch_capacity,
                                    mesh=mesh)
        ex._inner = inner
    return ex
