"""Reusable state-store surface.

Reference: hstream-processing's Store.hs classes — `KVStore`
(ksGet/ksPut/ksRange/ksDump), `TimestampedKVStore` (tksPut/tksRange),
`SessionStore` (findSessions/ssPut/ssRemove) — the storage vocabulary
its processors build on (Store.hs:55-144,316-409). Here the hot
aggregation state lives in the device lattice instead, so these stores
serve the HOST-side stateful operators: the interval join's two-sided
timestamped store, the stream-table join's last-value table, and any
future host operator needing keyed state.
"""

from __future__ import annotations

import bisect


class TimestampedKVStore:
    """Per-key timestamped rows: key -> (sorted ts list, rows list).
    The reference's TimestampedKVStore tksPut/tksRange
    (Processing/Store.hs). The interval join's side stores use the flat
    batched restatement of this shape (join._FlatIntervalStore); this
    per-key form remains the reusable host-operator surface."""

    def __init__(self) -> None:
        self.by_key: dict[tuple, tuple[list[int], list[dict]]] = {}

    def put(self, key: tuple, ts: int, row: dict) -> None:
        tss, rows = self.by_key.setdefault(key, ([], []))
        i = bisect.bisect_right(tss, ts)
        tss.insert(i, ts)
        rows.insert(i, row)

    def range(self, key: tuple, lo: int, hi: int):
        """Rows with lo <= ts <= hi for this key (tksRange)."""
        ent = self.by_key.get(key)
        if ent is None:
            return []
        tss, rows = ent
        i = bisect.bisect_left(tss, lo)
        j = bisect.bisect_right(tss, hi)
        return list(zip(tss[i:j], rows[i:j]))

    def prune(self, min_ts: int) -> None:
        """Drop rows older than min_ts (bounds state where the
        reference's in-memory store grows forever)."""
        dead = []
        for key, (tss, rows) in self.by_key.items():
            i = bisect.bisect_left(tss, min_ts)
            if i:
                del tss[:i]
                del rows[:i]
            if not tss:
                dead.append(key)
        for key in dead:
            del self.by_key[key]


class LastValueStore:
    """Keyed latest-value table: newest timestamp wins, out-of-order
    older updates never clobber (the stream-table join's TABLE side,
    reference Stream.hs:302-344)."""

    def __init__(self) -> None:
        self.data: dict[tuple, tuple[int, dict]] = {}

    def update(self, key: tuple, ts: int, row) -> None:
        """Store `row` (copied) iff at least as new as the current
        entry — the copy only happens for accepted updates."""
        cur = self.data.get(key)
        if cur is None or ts >= cur[0]:
            self.data[key] = (ts, dict(row))

    def lookup(self, key: tuple) -> dict | None:
        ent = self.data.get(key)
        return None if ent is None else ent[1]

    def __len__(self) -> int:
        return len(self.data)
