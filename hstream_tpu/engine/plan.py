"""Logical query plans.

The reference's SQL codegen lowers SELECT into a processor-DAG builder
(hstream-sql Codegen.hs:532-567: source -> filter -> map/groupBy -> window
aggregate -> having -> sink). Here the DAG survives only as this logical
plan; the physical form is a single jitted step function built by
hstream_tpu.engine.compile (no per-record closures).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field

from hstream_tpu.engine.expr import Expr
from hstream_tpu.engine.types import Schema
from hstream_tpu.engine.window import WindowSpec


class AggKind(enum.Enum):
    COUNT_ALL = "count_all"        # COUNT(*)
    COUNT = "count"                # COUNT(col) — non-null count
    SUM = "sum"
    AVG = "avg"
    MIN = "min"
    MAX = "max"
    APPROX_COUNT_DISTINCT = "approx_count_distinct"  # HLL sketch
    APPROX_QUANTILE = "approx_quantile"              # log-binned histogram
    TOPK = "topk"                  # top-k values per group/window
    TOPK_DISTINCT = "topk_distinct"


@dataclass(frozen=True)
class AggSpec:
    kind: AggKind
    out_name: str
    input: Expr | None = None      # None for COUNT(*)
    quantile: float | None = None  # for APPROX_QUANTILE
    k: int | None = None           # for TOPK


@dataclass
class PlanNode:
    pass


@dataclass
class SourceNode(PlanNode):
    stream: str
    schema: Schema


@dataclass
class FilterNode(PlanNode):
    child: PlanNode
    predicate: Expr


@dataclass
class ProjectNode(PlanNode):
    """SELECT expressions for non-aggregating queries (host-evaluated on
    the emitted rows; device path forwards source columns)."""
    child: PlanNode
    exprs: list[tuple[str, Expr]]  # (output name, expr)


@dataclass
class AggregateNode(PlanNode):
    child: PlanNode
    group_keys: list[Expr]         # grouping columns
    window: WindowSpec | None      # None = global group-by
    aggs: list[AggSpec]
    having: Expr | None = None
    # host-side projections over aggregate outputs, e.g. SUM(x)/2 AS y
    post_projections: list[tuple[str, Expr]] = field(default_factory=list)


@dataclass
class JoinNode(PlanNode):
    left: PlanNode
    right: PlanNode
    left_key: Expr
    right_key: Expr
    window_ms: int                 # |ts_l - ts_r| <= window_ms (JOIN WITHIN)
    left_name: str = "l"
    right_name: str = "r"


@dataclass
class SinkNode(PlanNode):
    child: PlanNode
    stream: str


def plan_source(node: PlanNode) -> SourceNode:
    """The (single) source under a linear plan chain."""
    while not isinstance(node, SourceNode):
        if isinstance(node, (FilterNode, ProjectNode, AggregateNode, SinkNode)):
            node = node.child
        else:
            raise ValueError(f"no single source under {type(node).__name__}")
    return node
