"""The window-state lattice: device state + the jitted micro-batch step.

This is the hot path. The reference's equivalent is the per-record
aggregate processor (hstream-processing TimeWindowedStream.hs:82-103: per
record compute `windowsFor ts`, drop if past grace, get/agg/put a KV store
keyed by serialized (key, window)). Here the same semantics are one fused
scatter pass over a dense state lattice:

    state[plane][key_id, slot, ...]     slot = (win_start // advance) % W

W (hstream_tpu.engine.window.num_slots) covers every window that can still
receive records given the grace period, so a slot is always closed and
reset by the host watermark loop before it could be reused — `slot_start`
tracks the window start currently occupying each slot.

Late records (win_end + grace <= watermark, the reference's
`observedStreamTime` check at TimeWindowedStream.hs:92) are masked out and
scattered to a dropped out-of-bounds row (`mode="drop"`).

All accumulator updates are commutative monoid ops (add / min / max /
register-max / bin-add), so partial lattices from different chips merge
exactly — the basis for the data-parallel sharding in hstream_tpu.parallel.

Watermark lives on the HOST, not in device state: the step function is a
pure scatter-aggregation with no device->host sync; the host decides when
to call extract/reset for closed slots (rare, off the hot path).

Device time is int32 ms relative to a per-query epoch; `rebase` shifts
`slot_start` when the host re-anchors the epoch.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Callable, Mapping, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from hstream_tpu.engine.plan import AggKind, AggSpec
from hstream_tpu.engine.sketches import (
    HLLConfig,
    QuantileConfig,
    hll_estimate,
    hll_update_indices,
    quantile_bin,
    quantile_estimate,
)
from hstream_tpu.engine.window import FixedWindow, num_slots

NEG_INF = jnp.float32(-jnp.inf)
POS_INF = jnp.float32(jnp.inf)
EMPTY_START = -(1 << 31)  # slot_start sentinel for "slot unoccupied"


@dataclass(frozen=True)
class LatticeSpec:
    """Static configuration the step function is specialized on."""

    n_keys: int
    window: FixedWindow | None          # None = windowless global group-by
    aggs: tuple[AggSpec, ...]
    hll: HLLConfig = HLLConfig()
    qcfg: QuantileConfig = QuantileConfig()
    # changelog tracking (EMIT CHANGES): when False the per-batch
    # `touched` scatter is skipped — one fewer memory pass per record
    track_touched: bool = True

    @property
    def n_slots(self) -> int:
        return 1 if self.window is None else num_slots(self.window)

    @property
    def windows_per_record(self) -> int:
        return 1 if self.window is None else self.window.windows_per_record


def _plane_name(i: int, agg: AggSpec) -> str:
    return f"a{i}_{agg.kind.value}"


_TOPK_KINDS = (AggKind.TOPK, AggKind.TOPK_DISTINCT)


def agg_width(agg: AggSpec) -> int:
    """Values per key this aggregate emits (k for TOPK, else 1)."""
    if agg.kind in _TOPK_KINDS:
        if agg.k is None or agg.k < 1:
            raise ValueError(f"{agg.kind.value} needs k >= 1, got {agg.k}")
        return agg.k
    return 1


def init_state(spec: LatticeSpec) -> dict[str, jnp.ndarray]:
    K, W = spec.n_keys, spec.n_slots
    state: dict[str, jnp.ndarray] = {
        "count": jnp.zeros((K, W), jnp.int32),
        "slot_start": jnp.full((W,), EMPTY_START, jnp.int32),
        "touched": jnp.zeros((K, W), jnp.bool_),
    }
    for i, agg in enumerate(spec.aggs):
        name = _plane_name(i, agg)
        if agg.kind == AggKind.COUNT_ALL:
            continue  # aliases the built-in `count` plane (same mask)
        if agg.kind == AggKind.COUNT:
            state[name] = jnp.zeros((K, W), jnp.int32)
        elif agg.kind == AggKind.SUM:
            state[name] = jnp.zeros((K, W), jnp.float32)
        elif agg.kind == AggKind.AVG:
            state[name] = jnp.zeros((K, W), jnp.float32)
            state[name + "_n"] = jnp.zeros((K, W), jnp.int32)  # non-null count
        elif agg.kind == AggKind.MIN:
            state[name] = jnp.full((K, W), POS_INF, jnp.float32)
        elif agg.kind == AggKind.MAX:
            state[name] = jnp.full((K, W), NEG_INF, jnp.float32)
        elif agg.kind == AggKind.APPROX_COUNT_DISTINCT:
            state[name] = jnp.zeros((K, W, spec.hll.m), jnp.int8)
        elif agg.kind == AggKind.APPROX_QUANTILE:
            state[name] = jnp.zeros((K, W, spec.qcfg.n_bins), jnp.int32)
        elif agg.kind in _TOPK_KINDS:
            # fixed-k plane of the current top values, kept sorted
            # descending; merging = concat + re-sort (see step)
            state[name] = jnp.full((K, W, agg_width(agg)), NEG_INF,
                                   jnp.float32)
        else:
            raise NotImplementedError(f"agg {agg.kind}")
    return state


ValueFn = Callable[[Mapping[str, jnp.ndarray]], jnp.ndarray]

# per-agg input: (value fn | None for COUNT(*), null-mask column key | None)
AggInput = tuple[ValueFn | None, str | None]


def build_step_fn(spec: LatticeSpec,
                  agg_inputs: list[AggInput],
                  filter_fn: ValueFn | None = None):
    """The micro-batch step, untraced (jit/shard_map applied by callers).

    step(state, watermark, key_ids i32[B], ts i32[B], valid bool[B],
         cols {name: [B]}) -> state'

    `agg_inputs[i]` is (value_fn, null_key): value_fn computes agg i's
    input column (None for COUNT(*)); null_key names a bool column in
    `cols` that is True where the input is SQL NULL (missing field).
    NULL and non-finite inputs do not contribute to COUNT(col) / SUM /
    AVG / MIN / MAX / sketches, matching SQL aggregate semantics.
    `filter_fn` is the WHERE mask. All are traced into the same jit.

    Out-of-range key ids (negative or >= n_keys) are dropped whenever
    `valid` is False for that record — the sharded wrapper
    (hstream_tpu.parallel) relies on this to mask out keys owned by other
    shards.
    """
    K, W = spec.n_keys, spec.n_slots
    n_per = spec.windows_per_record
    win = spec.window

    def step(state, watermark, key_ids, ts, valid, cols, slot_valid=None):
        # `slot_valid` (default: valid) masks the key-independent
        # slot_start update separately: the sharded wrapper passes the
        # pre-key-ownership mask here so every key shard computes the
        # SAME slot_start and the replicated out-spec is actually true.
        if slot_valid is None:
            slot_valid = valid
        if filter_fn is not None:
            f = filter_fn(cols)
            valid = valid & f
            slot_valid = slot_valid & f

        if win is None:
            starts = jnp.zeros((key_ids.shape[0], 1), jnp.int32)
            ok = valid[:, None]
            ok_slot = slot_valid[:, None]
            slots = jnp.zeros_like(starts)
        else:
            advance, size, grace = win.advance_ms, win.size_ms, win.grace_ms
            latest = ts - jnp.mod(ts, advance)
            offs = (jnp.arange(n_per, dtype=jnp.int32) * advance)[None, :]
            starts = latest[:, None] - offs                     # [B, n_per]
            late = (starts + (size + grace)) <= watermark
            in_range = ~late & (starts >= 0)
            ok = valid[:, None] & in_range
            ok_slot = slot_valid[:, None] & in_range
            slots = jnp.mod(starts // advance, W)

        flat_k = jnp.where(ok, key_ids[:, None], K).reshape(-1)  # K = OOB -> drop
        flat_s = jnp.where(ok, slots, 0).reshape(-1)
        flat_ok = ok.reshape(-1)
        flat_starts = starts.reshape(-1)

        out = dict(state)
        out["count"] = state["count"].at[flat_k, flat_s].add(
            flat_ok.astype(jnp.int32), mode="drop")
        out["slot_start"] = state["slot_start"].at[
            jnp.where(ok_slot.reshape(-1), slots.reshape(-1), W)].max(
            flat_starts, mode="drop")
        if spec.track_touched:
            out["touched"] = state["touched"].at[flat_k, flat_s].set(
                True, mode="drop")

        for i, agg in enumerate(spec.aggs):
            name = _plane_name(i, agg)
            vfn, null_key = agg_inputs[i]
            if agg.kind == AggKind.COUNT_ALL:
                continue  # reads the built-in `count` plane at finalize
            v = vfn(cols)                                        # [B]
            # input validity: not SQL NULL, and finite for float inputs
            input_ok = jnp.ones(v.shape, jnp.bool_)
            if null_key is not None:
                input_ok = input_ok & ~cols[null_key]
            if jnp.issubdtype(v.dtype, jnp.floating):
                input_ok = input_ok & jnp.isfinite(v)
            iok = flat_ok & jnp.repeat(input_ok, n_per)
            v_rep = jnp.repeat(v, n_per)
            if agg.kind == AggKind.COUNT:
                out[name] = state[name].at[flat_k, flat_s].add(
                    iok.astype(jnp.int32), mode="drop")
            elif agg.kind == AggKind.SUM:
                vals = jnp.where(iok, v_rep.astype(jnp.float32), 0.0)
                out[name] = state[name].at[flat_k, flat_s].add(vals, mode="drop")
            elif agg.kind == AggKind.AVG:
                vals = jnp.where(iok, v_rep.astype(jnp.float32), 0.0)
                out[name] = state[name].at[flat_k, flat_s].add(vals, mode="drop")
                out[name + "_n"] = state[name + "_n"].at[flat_k, flat_s].add(
                    iok.astype(jnp.int32), mode="drop")
            elif agg.kind == AggKind.MIN:
                vals = jnp.where(iok, v_rep.astype(jnp.float32), POS_INF)
                out[name] = state[name].at[flat_k, flat_s].min(vals, mode="drop")
            elif agg.kind == AggKind.MAX:
                vals = jnp.where(iok, v_rep.astype(jnp.float32), NEG_INF)
                out[name] = state[name].at[flat_k, flat_s].max(vals, mode="drop")
            elif agg.kind == AggKind.APPROX_COUNT_DISTINCT:
                reg, rank = hll_update_indices(v, spec.hll)
                reg_rep = jnp.repeat(reg, n_per)
                rank_rep = jnp.where(iok, jnp.repeat(rank, n_per), 0)
                out[name] = state[name].at[flat_k, flat_s, reg_rep].max(
                    rank_rep, mode="drop")
            elif agg.kind == AggKind.APPROX_QUANTILE:
                b_rep = jnp.repeat(quantile_bin(v, spec.qcfg), n_per)
                out[name] = state[name].at[flat_k, flat_s, b_rep].add(
                    iok.astype(jnp.int32), mode="drop")
            elif agg.kind in _TOPK_KINDS:
                out[name] = _topk_step(
                    state[name], agg, spec,
                    jnp.where(iok, v_rep.astype(jnp.float32), NEG_INF),
                    flat_k, flat_s, iok)
            else:
                raise NotImplementedError(agg.kind)
        return out

    return step


def _topk_step(plane, agg: AggSpec, spec: LatticeSpec, vals, flat_k,
               flat_s, ok):
    """Fold one batch into a TOPK plane [K, W, k].

    Batch-local top-k per (key, slot) via ONE lexicographic device sort
    (segment id asc, value desc) + segmented ranking, scattered into a
    scratch plane; then the scratch merges with the stored plane by
    concat + re-sort along the k axis — top-k of a union is a
    commutative monoid, so the fold order never matters."""
    K, W = spec.n_keys, spec.n_slots
    kk = agg_width(agg)
    seg = jnp.where(ok, flat_k * W + flat_s, K * W).astype(jnp.int32)
    sseg, sneg = jax.lax.sort((seg, -vals), num_keys=2)
    sval = -sneg
    idx = jnp.arange(sseg.shape[0], dtype=jnp.int32)
    first = jnp.concatenate(
        [jnp.ones((1,), jnp.bool_), sseg[1:] != sseg[:-1]])
    if agg.kind == AggKind.TOPK_DISTINCT:
        # count only the first record of each (segment, value) run
        newval = first | jnp.concatenate(
            [jnp.ones((1,), jnp.bool_), sval[1:] != sval[:-1]])
        c = jnp.cumsum(newval.astype(jnp.int32))
        base = jax.lax.cummax(
            jnp.where(first, c - newval.astype(jnp.int32), 0))
        rank = jnp.where(newval, c - 1 - base, kk)
    else:
        seg_start = jax.lax.cummax(jnp.where(first, idx, 0))
        rank = idx - seg_start
    keep = (rank < kk) & (sseg < K * W) & (sval > NEG_INF)
    kf = jnp.where(keep, sseg // W, K)
    sf = jnp.where(keep, sseg % W, 0)
    rf = jnp.where(keep, rank, 0)
    scratch = jnp.full((K, W, kk), NEG_INF, jnp.float32)
    scratch = scratch.at[kf, sf, rf].set(
        jnp.where(keep, sval, NEG_INF), mode="drop")
    comb = jnp.concatenate([plane, scratch], axis=-1)
    comb = -jnp.sort(-comb, axis=-1)
    if agg.kind == AggKind.TOPK_DISTINCT:
        dup = jnp.concatenate(
            [jnp.zeros(comb.shape[:-1] + (1,), jnp.bool_),
             comb[..., 1:] == comb[..., :-1]], axis=-1)
        comb = jnp.where(dup, NEG_INF, comb)
        comb = -jnp.sort(-comb, axis=-1)
    return comb[..., :kk]


# ---- packed batch transport ------------------------------------------------
#
# Host->device latency, not bandwidth, dominates ingest on real deployments
# (each transfer pays a fixed dispatch/tunnel cost), so the executor ships
# each micro-batch as ONE int32 buffer [3 + n_cols, B]:
#   row 0: key ids        row 1: ts (relative ms)
#   row 2: flag bits — bit 0 valid, bit 1+j = null mask of the j-th
#          null-tracked aggregate
#   row 3+i: the i-th needed column (f32 bitcast / i32 / bool as 0-1)
# Layout is a hashable tuple of (col_name, "f32"|"i32"|"bool").

ColLayout = tuple[tuple[str, str], ...]


def layout_tag(ctype) -> str:
    from hstream_tpu.engine.types import ColumnType

    return {ColumnType.FLOAT: "f32", ColumnType.INT: "i32",
            ColumnType.BOOL: "bool", ColumnType.STRING: "i32"}[ctype]


def pack_batch_host(capacity: int, n: int, key_ids, ts_rel, valid,
                    cols: Mapping[str, np.ndarray],
                    null_masks: list[np.ndarray | None],
                    layout: ColLayout) -> np.ndarray:
    """Assemble the packed int32 batch buffer on host (vectorized copies).
    `valid` may be None (all n records valid)."""
    buf = np.zeros((3 + len(layout), capacity), dtype=np.int32)
    buf[0, :n] = key_ids[:n]
    buf[1, :n] = ts_rel[:n]
    if valid is None:
        flags = np.ones(n, dtype=np.int32)  # bit0: valid
    else:
        flags = valid[:n].astype(np.int32)
    for j, nm in enumerate(null_masks):
        if nm is not None:
            flags |= nm[:n].astype(np.int32) << (1 + j)
    buf[2, :n] = flags
    for i, (name, tag) in enumerate(layout):
        src = cols[name]
        if tag == "f32":
            buf[3 + i, :n] = src[:n].astype(np.float32, copy=False).view(
                np.int32)
        elif tag == "bool":
            buf[3 + i, :n] = src[:n].astype(np.int32)
        else:
            buf[3 + i, :n] = src[:n]
    return buf


def unpack_batch_device(packed, layout: ColLayout, null_keys):
    """(key_ids, ts, valid, cols) from the packed buffer, traced."""
    key_ids = packed[0]
    ts = packed[1]
    flags = packed[2]
    valid = (flags & 1) != 0
    cols = {}
    for i, (name, tag) in enumerate(layout):
        row = packed[3 + i]
        if tag == "f32":
            cols[name] = jax.lax.bitcast_convert_type(row, jnp.float32)
        elif tag == "bool":
            cols[name] = row != 0
        else:
            cols[name] = row
    for j, nk in enumerate(nk for nk in null_keys if nk is not None):
        cols[nk] = ((flags >> (1 + j)) & 1) != 0
    return key_ids, ts, valid, cols


def build_step_packed(spec: LatticeSpec, agg_inputs: list[AggInput],
                      filter_fn: ValueFn | None, layout: ColLayout,
                      null_keys) -> Callable:
    """step(state, watermark, packed i32[3+n_cols, B]) -> state'."""
    base = build_step_fn(spec, agg_inputs, filter_fn)

    def step(state, watermark, packed):
        key_ids, ts, valid, cols = unpack_batch_device(packed, layout,
                                                       null_keys)
        return base(state, watermark, key_ids, ts, valid, cols)

    return step


def build_step_encoded(spec: LatticeSpec, agg_inputs: list[AggInput],
                       filter_fn: ValueFn | None, combo, cap: int,
                       null_keys) -> Callable:
    """step(state, watermark, n, bases i32[streams], words u32) -> state'
    over the bit-packed transport (engine.transport): the column decode
    is traced into the same jit as the scatter, so XLA fuses unpack
    shifts with the aggregation. Null-flag streams absent from the wire
    are constant-folded to all-False."""
    from hstream_tpu.engine import transport as tp

    base = build_step_fn(spec, agg_inputs, filter_fn)

    def step(state, watermark, n, bases, words):
        key_ids, ts, valid, cols = tp.decode_batch(words, combo, cap, n,
                                                   bases)
        for nk in null_keys:
            if nk is not None and nk not in cols:
                cols[nk] = jnp.zeros((cap,), jnp.bool_)
        return base(state, watermark, key_ids, ts, valid, cols)

    return step


def finalize_column(spec: LatticeSpec, state_col: Mapping[str, jnp.ndarray]):
    """Finalize one slot column {plane: [K, ...]} -> {out_name: [K] f32}."""
    outs = {}
    for i, agg in enumerate(spec.aggs):
        name = _plane_name(i, agg)
        if agg.kind == AggKind.COUNT_ALL:
            outs[agg.out_name] = state_col["count"].astype(jnp.float32)
        elif agg.kind == AggKind.AVG:
            denom = jnp.maximum(state_col[name + "_n"].astype(jnp.float32), 1.0)
            outs[agg.out_name] = state_col[name] / denom
        elif agg.kind == AggKind.APPROX_COUNT_DISTINCT:
            outs[agg.out_name] = hll_estimate(state_col[name], spec.hll)
        elif agg.kind == AggKind.APPROX_QUANTILE:
            outs[agg.out_name] = quantile_estimate(
                state_col[name], agg.quantile or 0.5, spec.qcfg)
        elif agg.kind == AggKind.MIN:
            outs[agg.out_name] = jnp.where(
                state_col["count"] > 0, state_col[name], 0.0)
        elif agg.kind == AggKind.MAX:
            outs[agg.out_name] = jnp.where(
                state_col["count"] > 0, state_col[name], 0.0)
        elif agg.kind in _TOPK_KINDS:
            outs[agg.out_name] = state_col[name]  # [K, k] passthrough
        else:
            outs[agg.out_name] = state_col[name].astype(jnp.float32)
    return outs


def _agg_out_rows(spec: LatticeSpec, outs):
    """Flatten finalized agg outputs into bitcast int32 rows — ONE place
    defines the row layout (width-k aggs contribute k rows); the unpack
    inverse is _unpack_agg_rows."""
    for agg in spec.aggs:
        o = outs[agg.out_name].astype(jnp.float32)
        if agg.kind in _TOPK_KINDS:
            for j in range(agg_width(agg)):
                yield jax.lax.bitcast_convert_type(o[:, j], jnp.int32)
        else:
            yield jax.lax.bitcast_convert_type(o, jnp.int32)


def _unpack_agg_rows(spec: LatticeSpec, rows2d: np.ndarray):
    """Inverse of _agg_out_rows: int32 rows -> {name: [N] or [N, k] f32}."""
    outs = {}
    row = 0
    for agg in spec.aggs:
        w = agg_width(agg)
        if agg.kind in _TOPK_KINDS:
            outs[agg.out_name] = np.stack(
                [rows2d[row + j].view(np.float32) for j in range(w)],
                axis=1)
        else:
            outs[agg.out_name] = rows2d[row].view(np.float32)
        row += w
    return outs


def pack_extract_rows(spec: LatticeSpec, count, win_start, outs):
    """Stack (count, win_start, finalized agg outputs) into ONE int32
    buffer [2 + sum(widths), K] (float outputs bitcast) so the host pays
    a single device->host fetch per drain instead of one per plane —
    host sync count, not bytes, dominates drain cost. A width-k agg
    (TOPK) contributes k rows."""
    k = count.shape[0]
    rows = [count.astype(jnp.int32),
            jnp.broadcast_to(jnp.asarray(win_start, jnp.int32), (k,))]
    rows.extend(_agg_out_rows(spec, outs))
    return jnp.stack(rows)


def stack_pow2(bufs):
    """jnp.stack with the depth padded to a power of two (zero-filled
    tail buffers). Each distinct stack depth is its own XLA program, so
    stacking raw pending-counts on the drain paths compiled one
    executable per count ever seen — found live by the RetraceGuard
    server drive (ISSUE 7). Padding converges the depths to a handful;
    callers zip the fetched stack against the UNPADDED group, and a
    zero buffer decodes as zero rows anyway (row0 col0 == 0)."""
    p = 1
    while p < len(bufs):
        p *= 2
    bufs = list(bufs)
    if p != len(bufs):
        bufs.extend([jnp.zeros_like(bufs[0])] * (p - len(bufs)))
    return jnp.stack(bufs)


def unpack_extract_rows(spec: LatticeSpec, packed: np.ndarray):
    """(count [K], win_start [K], {name: [K] or [K, width] f32}) from
    pack_extract_rows."""
    count = packed[0]
    win_start = packed[1]
    return count, win_start, _unpack_agg_rows(spec, packed[2:])


def gather_extract_batch(spec: LatticeSpec, packed: np.ndarray,
                         widx: np.ndarray, kids: np.ndarray):
    """Columnar gather over a batched extract buffer [P, 2+rows, K]:
    for the selected (window, key) pairs, return {out_name: [n] f64 or
    [n, width] f32} — the vectorized inverse of per-row _agg_row
    decoding. The fancy-index gather yields contiguous int32 vectors,
    so the f32 bitcast is a view, not a copy-per-cell."""
    outs: dict[str, np.ndarray] = {}
    row = 2
    for agg in spec.aggs:
        w = agg_width(agg)
        if agg.kind in _TOPK_KINDS:
            outs[agg.out_name] = np.stack(
                [np.ascontiguousarray(packed[widx, row + j, kids])
                 .view(np.float32) for j in range(w)], axis=1)
        else:
            outs[agg.out_name] = np.ascontiguousarray(
                packed[widx, row, kids]).view(np.float32).astype(
                np.float64)
        row += w
    return outs


def build_extract_slot(spec: LatticeSpec):
    """extract(state, slot) -> packed int32 [2+n_aggs, K] (see
    pack_extract_rows): finalized values for one slot column, fetched by
    the host in a single transfer when the watermark closes a window.
    Kept as the per-slot reference kernel (equivalence tests); the close
    path itself dispatches build_extract_reset_slots."""

    @jax.jit
    def extract(state, slot):
        col = {k: v[:, slot] for k, v in state.items()
               if k not in ("slot_start", "touched")}
        outs = finalize_column(spec, col)
        return pack_extract_rows(spec, col["count"],
                                 state["slot_start"][slot], outs)

    return extract


def build_reset_slot(spec: LatticeSpec):
    @jax.jit
    def reset(state, slot):
        out = dict(state)
        for i, agg in enumerate(spec.aggs):
            if agg.kind == AggKind.COUNT_ALL:
                continue  # no own plane; `count` below resets it
            name = _plane_name(i, agg)
            out[name] = state[name].at[:, slot].set(init_value(agg))
            if agg.kind == AggKind.AVG:
                out[name + "_n"] = state[name + "_n"].at[:, slot].set(0)
        out["count"] = state["count"].at[:, slot].set(0)
        out["touched"] = state["touched"].at[:, slot].set(False)
        out["slot_start"] = state["slot_start"].at[slot].set(EMPTY_START)
        return out

    return reset


# ---- fused multi-slot close -------------------------------------------------
#
# A close cycle may find many windows due at once (hopping windows, a
# watermark jump, a deferred-close drain). Dispatching extract+reset per
# slot costs 2 kernel launches + 1 device->host fetch PER WINDOW, and on
# a tunneled link each is a round trip — the measured gap between
# kernel_events_per_sec and end-to-end eps. The fused kernels below take
# a PADDED slot vector (entries < 0 are padding) so one dispatch covers
# every due window and the host pays ONE fetch for the whole cycle; the
# extract is vmapped over slots and the reset is folded into the same
# jit (it reads the pre-reset state, so extract values are unaffected).
#
# The one-dispatch-one-fetch economics are ENFORCED, not just
# documented: the executor drivers declare `# contract: dispatches<=N
# fetches<=M` budgets checked by the tools/analyze dispatch pass, the
# lru_cache'd factories here are the retrace pass's sanctioned
# memoization shape, and the runtime RetraceGuard (bench --smoke, CI)
# asserts zero steady-state recompiles through these kernels.


def _reset_slots_tree(spec: LatticeSpec, state, rs):
    """Reset the slot columns named by rs (int32 [P]; out-of-range
    entries drop) in every plane — shared by the fused extract+reset and
    the reset-only kernel."""
    out = dict(state)
    for i, agg in enumerate(spec.aggs):
        if agg.kind == AggKind.COUNT_ALL:
            continue  # no own plane; `count` below resets it
        name = _plane_name(i, agg)
        out[name] = state[name].at[:, rs].set(init_value(agg), mode="drop")
        if agg.kind == AggKind.AVG:
            out[name + "_n"] = state[name + "_n"].at[:, rs].set(
                0, mode="drop")
    out["count"] = state["count"].at[:, rs].set(0, mode="drop")
    out["touched"] = state["touched"].at[:, rs].set(False, mode="drop")
    out["slot_start"] = state["slot_start"].at[rs].set(
        EMPTY_START, mode="drop")
    return out


def _extract_slots_packed(spec: LatticeSpec, state, slots):
    """Vmapped extract of the slot columns named by `slots` (padding
    entries < 0 produce all-zero packed rows, so the host decode's
    count>0 filter skips them) -> packed int32 [P, 2+rows, K]."""
    valid = slots >= 0
    safe = jnp.where(valid, slots, 0)

    def one(slot):
        col = {k: v[:, slot] for k, v in state.items()
               if k not in ("slot_start", "touched")}
        outs = finalize_column(spec, col)
        return pack_extract_rows(spec, col["count"],
                                 state["slot_start"][slot], outs)

    packed = jax.vmap(one)(safe)
    return jnp.where(valid[:, None, None], packed, 0)


def build_extract_reset_slots(spec: LatticeSpec):
    """extract_and_reset(state, slots i32[P]) ->
    (state', packed i32[P, 2+rows, K]).

    One device dispatch closes every due window: the vmapped extract
    finalizes each requested slot column and the reset of those same
    slots rides in the same jit (XLA schedules both off the pre-reset
    state). Padding entries (slot < 0) extract zeros and reset nothing."""

    @jax.jit
    def extract_and_reset(state, slots):
        packed = _extract_slots_packed(spec, state, slots)
        rs = jnp.where(slots >= 0, slots, spec.n_slots)  # OOB -> drop
        return _reset_slots_tree(spec, state, rs), packed

    return extract_and_reset


def build_extract_slots(spec: LatticeSpec):
    """extract(state, slots i32[P]) -> packed i32[P, 2+rows, K]: the
    read-only half of the fused close — one dispatch serves a pull
    query / view peek over every open window."""

    @jax.jit
    def extract(state, slots):
        return _extract_slots_packed(spec, state, slots)

    return extract


def build_reset_slots(spec: LatticeSpec):
    """reset(state, slots i32[P]) -> state': batched reset without the
    extract (EMIT CHANGES mode closes emit nothing — the changelog
    already carried the final values)."""

    @jax.jit
    def reset(state, slots):
        rs = jnp.where(slots >= 0, slots, spec.n_slots)
        return _reset_slots_tree(spec, state, rs)

    return reset


def init_value(agg: AggSpec):
    if agg.kind == AggKind.MIN:
        return POS_INF
    if agg.kind in (AggKind.MAX,) + _TOPK_KINDS:
        return NEG_INF
    return 0


def pack_touched_rows(spec: LatticeSpec, n, kidx, win_start, outs,
                      max_out: int):
    """ONE int32 buffer [3 + sum(widths), max_out]: row0 col0 = n,
    row1 = key ids, row2 = win starts, rows 3+ = bitcast float agg
    outputs (width-k aggs contribute k rows)."""
    rows = [jnp.zeros((max_out,), jnp.int32).at[0].set(n),
            kidx.astype(jnp.int32), win_start.astype(jnp.int32)]
    rows.extend(_agg_out_rows(spec, outs))
    return jnp.stack(rows)


def unpack_touched_rows(spec: LatticeSpec, packed: np.ndarray):
    """(n, kidx [n], win_start [n], {name: [n] or [n, width] f32})."""
    n = int(packed[0, 0])
    outs = _unpack_agg_rows(spec, packed[3:, :n])
    return n, packed[1, :n], packed[2, :n], outs


def build_extract_touched(spec: LatticeSpec, max_out: int):
    """Changelog extraction for EMIT CHANGES: all (key, window) pairs
    touched since the last call, with finalized current values.

    extract(state) -> (state with touched cleared,
                       packed int32 [3+n_aggs, max_out] — see
                       pack_touched_rows)

    Deviation from the reference (documented): the reference emits one
    change per input record (TimeWindowedStream.hs:101); a batched engine
    emits one change per touched (key, window) per micro-batch."""

    @jax.jit
    def extract(state):
        mask = state["touched"]
        n = jnp.sum(mask.astype(jnp.int32))
        kidx, sidx = jnp.nonzero(mask, size=max_out, fill_value=0)
        valid = jnp.arange(max_out) < n
        col = {k: v[kidx, sidx] for k, v in state.items()
               if k not in ("slot_start", "touched")}
        outs = finalize_column(spec, col)
        win_start = jnp.where(valid, state["slot_start"][sidx], 0)
        out_state = dict(state)
        out_state["touched"] = jnp.zeros_like(mask)
        return out_state, pack_touched_rows(spec, n, kidx, win_start,
                                            outs, max_out)

    return extract


def plane_merge_kinds(spec: LatticeSpec) -> dict[str, str]:
    """Monoid merge op per state plane ("sum" | "min" | "max").

    Every accumulator is a commutative monoid, so partial lattices from
    different chips (or a restored checkpoint plus fresh state) combine
    exactly with these elementwise ops. `touched` merges with max (logical
    or); `slot_start` with max (EMPTY_START is the identity)."""
    kinds = {"count": "sum", "touched": "max", "slot_start": "max"}
    for i, agg in enumerate(spec.aggs):
        name = _plane_name(i, agg)
        if agg.kind == AggKind.COUNT_ALL:
            continue  # no own plane
        if agg.kind == AggKind.MIN:
            kinds[name] = "min"
        elif agg.kind in (AggKind.MAX, AggKind.APPROX_COUNT_DISTINCT):
            kinds[name] = "max"
        elif agg.kind in _TOPK_KINDS:
            # NOT elementwise: merging two top-k planes needs
            # concat+sort; sharded execution rejects these specs
            kinds[name] = "topk"
        else:
            kinds[name] = "sum"
            if agg.kind == AggKind.AVG:
                kinds[name + "_n"] = "sum"
    return kinds


def compile_agg_inputs(spec: LatticeSpec, schema) -> tuple[
        list[AggInput], tuple[str | None, ...]]:
    """Device value-fns + null-mask column keys for each aggregate."""
    from hstream_tpu.engine.expr import compile_device

    agg_inputs: list[AggInput] = []
    null_keys: list[str | None] = []
    for i, agg in enumerate(spec.aggs):
        if agg.input is None:
            agg_inputs.append((None, None))
            null_keys.append(None)
        else:
            key = f"__null_a{i}"
            agg_inputs.append((compile_device(agg.input, schema), key))
            null_keys.append(key)
    return agg_inputs, tuple(null_keys)


class CompiledLattice(NamedTuple):
    step: Callable
    extract_slot: Callable      # per-slot reference kernels (tests)
    reset_slot: Callable
    extract_reset_slots: Callable  # fused multi-slot close (one dispatch)
    extract_slots: Callable        # batched read-only extract (peek)
    reset_slots: Callable          # batched reset (EMIT CHANGES closes)
    extract_touched: Callable
    null_keys: tuple[str | None, ...]  # per agg: the __null_a{i} cols key


@functools.lru_cache(maxsize=512)
def compiled(spec: LatticeSpec, schema, filter_expr, max_out: int,
             layout: ColLayout) -> CompiledLattice:
    """Shared, cached compilation of all lattice functions for a given
    (spec, schema, filter, layout) — executors with identical shapes reuse
    the same jitted callables (and therefore the same XLA executables).
    Requires expressions with string literals pre-encoded
    (expr.encode_strings)."""
    from hstream_tpu.engine.expr import compile_device

    agg_inputs, null_keys = compile_agg_inputs(spec, schema)
    filter_fn = compile_device(filter_expr, schema) if filter_expr is not None \
        else None
    return CompiledLattice(
        step=jax.jit(build_step_packed(spec, agg_inputs, filter_fn,
                                       layout, null_keys)),
        extract_slot=build_extract_slot(spec),
        reset_slot=build_reset_slot(spec),
        extract_reset_slots=build_extract_reset_slots(spec),
        extract_slots=build_extract_slots(spec),
        reset_slots=build_reset_slots(spec),
        extract_touched=build_extract_touched(spec, max_out),
        null_keys=null_keys,
    )


@functools.lru_cache(maxsize=2048)
def compiled_encoded_step(spec: LatticeSpec, schema, filter_expr,
                          combo, cap: int, *,
                          donate_words: bool = False) -> Callable:
    """Cached jit of the v2-transport step for one encoding combo. The
    state argument is donated: steady-state ingest re-uses the lattice
    buffers in place instead of allocating a fresh copy per micro-batch.
    donate_words=True additionally donates the uploaded wire buffer (arg
    4) — the ingest pipeline uses each staged buffer exactly once, so
    donating it recycles the device staging slot for the next upload;
    callers that re-dispatch one staged batch (kernel microbenchmarks)
    must keep the default."""
    from hstream_tpu.engine.expr import compile_device

    agg_inputs, null_keys = compile_agg_inputs(spec, schema)
    filter_fn = compile_device(filter_expr, schema) if filter_expr is not None \
        else None
    # donation is a TPU/GPU optimization; CPU (the test backend) ignores
    # it with a warning per call, so only request it where it helps
    donate: tuple[int, ...] = ()
    if jax.default_backend() != "cpu":
        donate = (0, 4) if donate_words else (0,)
    return jax.jit(build_step_encoded(spec, agg_inputs, filter_fn, combo,
                                      cap, null_keys),
                   donate_argnums=donate)


# ---- interval-join lattice kernels ------------------------------------------
#
# The TPU analogue of the reference's timestamped two-sided KV stores
# (Stream.hs:267-300 joinStreamProcessor): each join side is a device-
# resident flat store of (key code, ts, packed columns) kept sorted by
# (code, ts), and one fused jitted kernel per micro-batch
#   * probes the OTHER side over each record's within-interval span
#     [ts - within, ts + within] (a segmented two-sided bound over the
#     sorted store, computed by a stable merge-rank — see
#     _join_bounds), emitting matched pairs into ONE padded buffer, and
#   * inserts the (pre-sorted) batch into THIS side's store with one
#     2-key merge sort.
# Watermark eviction is a separate vmapped kernel over both sides
# (join_evict), dispatched by the host when retention advances; it also
# carries the epoch-rebase delta so the int32 relative-time space never
# overflows (the device restatement of _FlatIntervalStore's span
# guard — rebase instead of abort).
#
# Everything is int32 (no x64 dependence): ts is milliseconds relative
# to a host-managed join epoch, codes are the executor's dense join-key
# codes, column values are f32-bitcast/i32/bool/dict-id int32 rows, and
# per-entry null/present bits pack into one flags word (2 bits per
# stored column). One dispatch + one D2H fetch (the match buffer) per
# micro-batch, regardless of match count — match widths share compiled
# shapes via the same pow2 padding trick as the fused window close.
#
# Batch layout (int32 [4 + n_cols, bcap], host-packed, sorted by
# (code, ts)): row 0 code, row 1 ts_rel, row 2 inner key id, row 3
# flags, rows 4+ packed column values.
#
# Match buffer (int32 [5 + n_cols_mine + n_cols_other, match_cap]):
# row 0 header ([0] = true match total — may exceed match_cap, the
# host then re-probes at the next pow2 width), row 1 inner key id,
# row 2 joined ts (max of the pair, relative), row 3 probe-side flags,
# row 4 stored-side flags, rows 5+ probe-side then stored-side columns.

JOIN_SENT_CODE = (1 << 22)  # code sentinel: empty/evicted slots (> any
                            # live code — the executor compacts at 2^22)
JOIN_MAX_COLS = 14          # 2 bits (null, present) per column in one
                            # int32 flags word


def init_join_store(cap: int, n_cols: int) -> dict[str, jnp.ndarray]:
    """One empty join side: all slots carry the code sentinel."""
    return {
        "code": jnp.full((cap,), JOIN_SENT_CODE, jnp.int32),
        "ts": jnp.zeros((cap,), jnp.int32),
        "flags": jnp.zeros((cap,), jnp.int32),
        "cols": jnp.zeros((n_cols, cap), jnp.int32),
    }


def _join_bounds(store_code, store_ts, qcode, lo_ts, hi_ts):
    """Vectorized [lower, upper) bounds of each query's (code, ts)
    span in a store sorted by (code, ts) — int32-safe searchsorted over
    a 2-key space. ONE stable 3-key sort ranks both query sets among
    the store entries: a query landing at final position p with k
    queries (of either set) before it has exactly p - k store entries
    before it, which IS its bound. The tie-break tag orders lo-queries
    BEFORE equal-key store entries (lower bound) and hi-queries AFTER
    them (upper bound)."""
    cap = store_code.shape[0]
    bcap = qcode.shape[0]
    codes = jnp.concatenate([store_code, qcode, qcode])
    tss = jnp.concatenate([store_ts, lo_ts, hi_ts])
    tags = jnp.concatenate([jnp.ones((cap,), jnp.int32),
                            jnp.zeros((bcap,), jnp.int32),
                            jnp.full((bcap,), 2, jnp.int32)])
    pay = jnp.concatenate([jnp.full((cap,), 2 * bcap, jnp.int32),
                           jnp.arange(bcap, dtype=jnp.int32),
                           bcap + jnp.arange(bcap, dtype=jnp.int32)])
    _, _, _, spay = jax.lax.sort((codes, tss, tags, pay), num_keys=3)
    pos = jnp.arange(cap + 2 * bcap, dtype=jnp.int32)
    is_q = spay < 2 * bcap
    k = jnp.cumsum(is_q.astype(jnp.int32)) - 1
    bounds = jnp.zeros((2 * bcap,), jnp.int32).at[
        jnp.where(is_q, spay, 2 * bcap)].set(pos - k, mode="drop")
    return bounds[:bcap], bounds[bcap:]


def _join_match_arrays(other, batch, n, within, cutoff, bcap: int,
                       match_cap: int, owned=None):
    """Shared probe core: expand the per-record [lower, upper) spans
    into padded match index arrays. Returns (total, rec, oidx, mvalid,
    jts) — rec indexes the probing batch, oidx the probed store."""
    cap = other["code"].shape[0]
    bcode = batch[0]
    bts = batch[1]
    bvalid = (jnp.arange(bcap) < n) & (bcode < JOIN_SENT_CODE)
    if owned is not None:
        bvalid = bvalid & owned
    qcode = jnp.where(bvalid, bcode, JOIN_SENT_CODE)
    lo_i, hi_i = _join_bounds(other["code"], other["ts"], qcode,
                              jnp.maximum(bts - within, cutoff),
                              bts + within)
    cnt = jnp.where(bvalid, jnp.maximum(hi_i - lo_i, 0), 0)
    ccnt = jnp.cumsum(cnt)
    total = ccnt[-1]
    j = jnp.arange(match_cap, dtype=jnp.int32)
    rec = jnp.clip(jnp.searchsorted(ccnt, j, side="right"), 0, bcap - 1)
    mvalid = j < jnp.minimum(total, match_cap)
    oidx = lo_i[rec] + (j - (ccnt[rec] - cnt[rec]))
    oidx = jnp.where(mvalid, jnp.clip(oidx, 0, cap - 1), 0)
    jts = jnp.where(mvalid, jnp.maximum(bts[rec], other["ts"][oidx]), 0)
    return total, rec, oidx, mvalid, jts


def _join_probe(other, batch, n, within, cutoff, bcap: int,
                match_cap: int, n_cols_mine: int, owned=None):
    """Probe `other` with the batch; emit the packed match buffer (see
    module comment). `cutoff` masks entries past retention out of the
    probe (the lower bound is max(ts - within, cutoff)): the host
    reference prunes its stores on every watermark advance, so the
    device store — which evicts lazily, for capacity only — must hide
    expired entries from matches to stay equivalent. `owned`
    (bool[bcap] or None) additionally masks which batch records this
    shard probes/inserts (key-sharded mirror)."""
    total, rec, oidx, mvalid, jts = _join_match_arrays(
        other, batch, n, within, cutoff, bcap, match_cap, owned)
    header = jnp.zeros((match_cap,), jnp.int32).at[0].set(total)
    rows = [header,
            jnp.where(mvalid, batch[2][rec], 0),                 # kid
            jts,
            jnp.where(mvalid, batch[3][rec], 0),                 # my flags
            jnp.where(mvalid, other["flags"][oidx], 0)]
    mcols = jnp.where(mvalid[None, :], batch[4:4 + n_cols_mine][:, rec], 0)
    ocols = jnp.where(mvalid[None, :], other["cols"][:, oidx], 0)
    return jnp.concatenate([jnp.stack(rows), mcols, ocols], axis=0)


def _join_insert(mine, batch, n, bcap: int, n_cols: int, owned=None):
    """Merge the (pre-sorted) batch into a sorted store: one stable
    2-key sort of the concatenation; overflow never truncates live
    entries because the host checks capacity before dispatching."""
    cap = mine["code"].shape[0]
    bcode = batch[0]
    bvalid = (jnp.arange(bcap) < n) & (bcode < JOIN_SENT_CODE)
    if owned is not None:
        bvalid = bvalid & owned
    code = jnp.concatenate(
        [mine["code"], jnp.where(bvalid, bcode, JOIN_SENT_CODE)])
    ts = jnp.concatenate([mine["ts"], batch[1]])
    idx = jnp.arange(cap + bcap, dtype=jnp.int32)
    scode, sts, order = jax.lax.sort((code, ts, idx), num_keys=2)
    order = order[:cap]
    flags = jnp.concatenate([mine["flags"], batch[3]])[order]
    cols = jnp.concatenate([mine["cols"], batch[4:4 + n_cols]],
                           axis=1)[:, order]
    return {"code": scode[:cap], "ts": sts[:cap], "flags": flags,
            "cols": cols}


@functools.lru_cache(maxsize=256)
def join_probe_insert(cap: int, bcap: int, match_cap: int,
                      n_cols_mine: int, n_cols_other: int):
    """The fused per-micro-batch kernel: probe the other side, insert
    into mine — ONE device dispatch; the match buffer is the one D2H
    fetch. (state_mine, state_other, batch, n, within, cutoff) ->
    (state_mine', packed matches)."""

    @jax.jit
    def probe_insert(mine, other, batch, n, within, cutoff):
        packed = _join_probe(other, batch, n, within, cutoff, bcap,
                             match_cap, n_cols_mine)
        return _join_insert(mine, batch, n, bcap, n_cols_mine), packed

    return probe_insert


@functools.lru_cache(maxsize=256)
def join_probe_only(cap: int, bcap: int, match_cap: int,
                    n_cols_mine: int, n_cols_other: int):
    """Probe without insert: the match-overflow redo path (the batch is
    already inserted; the other side is unchanged, so re-probing at a
    wider match_cap is exact)."""

    @jax.jit
    def probe(other, batch, n, within, cutoff):
        return _join_probe(other, batch, n, within, cutoff, bcap,
                           match_cap, n_cols_mine)

    return probe


def _join_match_feed(other, batch, n, within, cutoff, bcap: int,
                     match_cap: int, feed_plan, nulls_plan,
                     filter_nulls, owned=None):
    """Probe + inner-feed core shared by the fused single-chip kernel
    and the key-sharded mirror (parallel.ShardedJoinLattice): expand
    the match spans and resolve every inner-step column straight from
    the match sources. Returns (total, kid, jts_rel, valid, cols) —
    `cols` includes the __null_a{i} masks, `valid` has filter-NULL
    records already masked out. `owned` (bool[bcap] or None) restricts
    which batch records this shard probes."""
    total, rec, oidx, mvalid, jts = _join_match_arrays(
        other, batch, n, within, cutoff, bcap, match_cap, owned)
    mflags = batch[3][rec]
    oflags = other["flags"][oidx]

    def lpres_of(src, jm, jo):
        # which physical side is the SQL left side: "both" = the
        # probing batch, "both_o" = the probed store
        if src == "both":
            return ((mflags >> (2 * jm + 1)) & 1) != 0
        return ((oflags >> (2 * jo + 1)) & 1) != 0

    def null_bit(src, jm, jo):
        mnull = (((mflags >> (2 * jm)) & 1) != 0 if jm >= 0
                 else None)
        onull = (((oflags >> (2 * jo)) & 1) != 0 if jo >= 0
                 else None)
        if src == "m":
            return mnull
        if src == "o":
            return onull
        left, right = ((mnull, onull) if src == "both"
                       else (onull, mnull))
        return jnp.where(lpres_of(src, jm, jo), left, right)

    def raw_val(src, jm, jo):
        mv = batch[4 + jm][rec] if jm >= 0 else 0
        ov = other["cols"][jo][oidx] if jo >= 0 else 0
        if src == "m":
            return mv
        if src == "o":
            return ov
        left, right = (mv, ov) if src == "both" else (ov, mv)
        return jnp.where(lpres_of(src, jm, jo), left, right)

    cols = {}
    for name, tag, src, jm, jo in feed_plan:
        raw = raw_val(src, jm, jo)
        if tag == "f32":
            cols[name] = jax.lax.bitcast_convert_type(raw, jnp.float32)
        elif tag == "bool":
            cols[name] = raw != 0
        else:
            cols[name] = raw
    for null_key, refs in nulls_plan:
        m = jnp.zeros((match_cap,), jnp.bool_)
        for src, jm, jo in refs:
            m = m | null_bit(src, jm, jo)
        cols[null_key] = m
    valid = mvalid
    for src, jm, jo in filter_nulls:
        valid = valid & ~null_bit(src, jm, jo)
    kid = jnp.where(mvalid, batch[2][rec], 0)
    return total, kid, jts, valid, cols


@functools.lru_cache(maxsize=256)
def join_probe_insert_step(cap: int, bcap: int, match_cap: int,
                           n_cols_mine: int, n_cols_other: int,
                           inner_spec: "LatticeSpec", schema,
                           filter_expr, feed_plan, nulls_plan,
                           filter_nulls):
    """The FULLY fused interval-join kernel: probe the other side,
    insert into mine, and scatter the matched pairs straight into the
    downstream aggregate lattice — matches never leave the device, so
    the per-micro-batch D2H cost drops to zero (the changelog extract
    is the only remaining fetch, already batched/deferred).

    `feed_plan` maps the inner step's needed columns onto match
    sources, one hashable entry per column:
        (name, tag, src, j_mine, j_other)
    src "m" gathers from the probing batch, "o" from the probed store,
    "both" resolves per match by the LEFT side's present bit (bare-name
    left precedence; j_mine indexes my side's layout, j_other the
    other's — which physical side is "left" is baked into the plan by
    the caller). `nulls_plan` builds each aggregate's __null_a{i}
    column as the OR of its referenced columns' null bits, and
    `filter_nulls` masks records whose WHERE columns are NULL out of
    `valid` (SQL: NULL predicate is not-true).

    (mine, other, batch, n, within, cutoff, inner_state, wm_rel,
     ts_off) -> (mine', inner_state', total_matches i32)
    """
    agg_inputs, _null_keys = compile_agg_inputs(inner_spec, schema)
    from hstream_tpu.engine.expr import compile_device

    filter_fn = (compile_device(filter_expr, schema)
                 if filter_expr is not None else None)
    base_step = build_step_fn(inner_spec, agg_inputs, filter_fn)

    @jax.jit
    def probe_insert_step(mine, other, batch, n, within, cutoff,
                          inner_state, wm_rel, ts_off):
        total, kid, jts, valid, cols = _join_match_feed(
            other, batch, n, within, cutoff, bcap, match_cap,
            feed_plan, nulls_plan, filter_nulls)
        ts_inner = jts + ts_off
        new_inner = base_step(inner_state, wm_rel, kid, ts_inner,
                              valid, cols)
        new_mine = _join_insert(mine, batch, n, bcap, n_cols_mine)
        return new_mine, new_inner, total

    return probe_insert_step


@functools.lru_cache(maxsize=256)
def join_evict(cap: int, n_cols_l: int, n_cols_r: int):
    """Vmapped two-sided eviction + epoch rebase: drop entries past the
    retention cutoff from BOTH stores and shift surviving timestamps by
    -delta (0 outside a rebase), in one dispatch. The (code, ts) core
    compaction is vmapped over the side axis; the per-side column
    gathers ride the same jit. Returns (left', right', live counts
    i32[2]) — the count fetch is the only extra transfer eviction
    costs, and it is rare."""

    def _core(code, ts, cutoff, delta):
        alive = (code < JOIN_SENT_CODE) & (ts >= cutoff)
        code2 = jnp.where(alive, code, JOIN_SENT_CODE)
        ts2 = jnp.where(alive, ts - delta, 0)
        idx = jnp.arange(cap, dtype=jnp.int32)
        scode, sts, order = jax.lax.sort((code2, ts2, idx), num_keys=2)
        return scode, sts, order, jnp.sum(alive.astype(jnp.int32))

    @jax.jit
    def evict(left, right, cutoff, delta):
        code = jnp.stack([left["code"], right["code"]])
        ts = jnp.stack([left["ts"], right["ts"]])
        scode, sts, order, n = jax.vmap(
            _core, in_axes=(0, 0, None, None))(code, ts, cutoff, delta)
        out = []
        for s, st in enumerate((left, right)):
            out.append({"code": scode[s], "ts": sts[s],
                        "flags": st["flags"][order[s]],
                        "cols": st["cols"][:, order[s]]})
        return out[0], out[1], n

    return evict


def unpack_join_matches(packed: np.ndarray, n_cols_mine: int):
    """(total, kid, jts_rel, my_flags, other_flags, my_cols, other_cols)
    from a fetched match buffer; arrays sliced to the in-buffer match
    count (total may exceed it — the caller re-probes wider)."""
    total = int(packed[0, 0])
    m = min(total, packed.shape[1])
    return (total, packed[1, :m], packed[2, :m], packed[3, :m],
            packed[4, :m], packed[5:5 + n_cols_mine, :m],
            packed[5 + n_cols_mine:, :m])


def pad_slots(slots) -> np.ndarray:
    """Slot-index vector padded (with -1) to a power of two, so cycles
    of varying width share a handful of compiled shapes instead of one
    XLA executable per distinct count — shared by the fused window
    close, batched peek, and the session extract path."""
    p = 1
    while p < len(slots):
        p *= 2
    out = np.full(p, -1, np.int32)
    out[:len(slots)] = slots
    return out


# ---- session lattice kernels -------------------------------------------------
#
# The TPU restatement of the reference's SessionStore + merge-on-overlap
# loop (SessionWindowedStream.hs:84-118, hstream-processing SessionWindows):
# open sessions live in a device-resident ARENA of (key code, t0, t1,
# acc planes) kept sorted by (code, t0), and each micro-batch is ONE
# fused dispatch that
#   1. sorts (arena entries ∪ batch records) by (code, start) with one
#      stable `lax.sort` — a record is a degenerate session [ts, ts];
#   2. runs a SEGMENTED SCAN over the sorted sequence: a chain breaks at
#      a key change or where start > running-max(end) + gap. Because
#      merging only ever grows intervals, the sorted sweep's chains are
#      exactly the fixpoint of the reference's sequential merge-on-
#      overlap (interval clustering is confluent), and every accumulator
#      is a commutative monoid, so folding a whole chain is exact;
#   3. scatters each chain into a fresh compacted arena slot (merge and
#      compaction are the same scatter) — per-record values land via
#      the same masked monoid updates as the window lattice step.
# Closed sessions are dropped lazily: the host passes the close cutoff
# of its last close cycle and the kernel retires entries with
# t1 <= cutoff before the sort (eviction rides the merge dispatch).
# The step fetches NOTHING — the per-batch D2H cost of the session path
# is zero; the close extract (below) is the only fetch and is dispatched
# per close cycle, pow2-padded like the fused window close.
#
# The HOST keeps an exact interval mirror (code, t0, t1 — no accs) of
# the arena, updated with the numpy twin of the same sort+scan: the
# mirror decides late-record drops, close cycles, arena capacity, and
# slot indices without ever syncing the device. All times are int32 ms
# relative to a host-managed epoch (rebase delta rides the step).

SESSION_SENT_CODE = JOIN_SENT_CODE  # empty/evicted arena slots
_SESSION_NEG = -(1 << 30)           # safe "minus infinity" for the scan


@dataclass(frozen=True)
class SessionSpec:
    """Static configuration the session kernels are specialized on."""

    aggs: tuple[AggSpec, ...]
    hll: HLLConfig = HLLConfig()
    qcfg: QuantileConfig = QuantileConfig()


def session_plane_names(spec: SessionSpec) -> list[str]:
    """Canonical plane name per agg index: aggregates with the same
    (kind, input) share ONE arena plane — p50 + p99 over one column
    keep a single histogram; only the extract-time estimate differs.
    The first such agg owns the plane; kernels skip non-owners so
    additive planes never double-count."""
    seen: dict = {}
    out: list[str] = []
    for i, agg in enumerate(spec.aggs):
        key = (agg.kind, agg.input)
        name = seen.get(key)
        if name is None:
            name = _plane_name(i, agg)
            seen[key] = name
        out.append(name)
    return out


def session_plane_np(spec: SessionSpec, cap: int) -> dict[str, np.ndarray]:
    """Host-side (numpy) empty arena planes — the migration path fills
    these and device_puts once, with no device round trip."""
    arena: dict[str, np.ndarray] = {
        "code": np.full(cap, SESSION_SENT_CODE, np.int32),
        "t0": np.zeros(cap, np.int32),
        "t1": np.zeros(cap, np.int32),
    }
    for name, agg in zip(session_plane_names(spec), spec.aggs):
        if name in arena:
            continue  # aliased to an earlier same-(kind, input) agg
        if agg.kind in (AggKind.COUNT_ALL, AggKind.COUNT):
            arena[name] = np.zeros(cap, np.int32)
        elif agg.kind == AggKind.SUM:
            arena[name] = np.zeros(cap, np.float32)
        elif agg.kind == AggKind.AVG:
            arena[name] = np.zeros(cap, np.float32)
            arena[name + "_n"] = np.zeros(cap, np.int32)
        elif agg.kind == AggKind.MIN:
            arena[name] = np.full(cap, np.inf, np.float32)
        elif agg.kind == AggKind.MAX:
            arena[name] = np.full(cap, -np.inf, np.float32)
        elif agg.kind == AggKind.APPROX_COUNT_DISTINCT:
            arena[name] = np.zeros((cap, spec.hll.m), np.int8)
        elif agg.kind == AggKind.APPROX_QUANTILE:
            arena[name] = np.zeros((cap, spec.qcfg.n_bins), np.int32)
        else:
            raise NotImplementedError(f"session agg {agg.kind}")
    return arena


def grow_session_arena(spec: SessionSpec, arena: dict, new_cap: int
                       ) -> dict[str, jnp.ndarray]:
    """Pad every arena plane to new_cap (identity values in the tail)."""
    fresh = init_session_arena(spec, new_cap)
    return {k: fresh[k].at[:v.shape[0]].set(v) for k, v in arena.items()}


def init_session_arena(spec, cap: int) -> dict[str, jnp.ndarray]:
    """One empty session arena on device. Derives from session_plane_np
    so the per-AggKind dtype/identity table lives in ONE place (a
    migration/arena mismatch would corrupt state only on the rare
    activation-with-live-sessions path)."""
    return {k: jnp.asarray(v)
            for k, v in session_plane_np(spec, cap).items()}


def _session_chain_slots(code_all, start_all, end_all, gap, cap):
    """The shared sort + segmented-scan core: one stable lax.sort by
    (code, start, end), then a segmented running-max-of-end scan whose
    breaks (key change, or start past running end + gap) are the merged
    session chains. Returns per-ORIGIN destination slots: dest[i] is the
    compacted chain slot of concat-domain entry i (cap = dropped)."""
    m = code_all.shape[0]
    idx = jnp.arange(m, dtype=jnp.int32)
    scode, sstart, send, sidx = jax.lax.sort(
        (code_all, start_all, end_all, idx), num_keys=3)
    newrun = jnp.concatenate(
        [jnp.ones((1,), jnp.bool_), scode[1:] != scode[:-1]])

    def comb(a, b):
        fa, ma = a
        fb, mb = b
        return fa | fb, jnp.where(fb, mb, jnp.maximum(ma, mb))

    _, runmax = jax.lax.associative_scan(comb, (newrun, send))
    prev_end = jnp.concatenate(
        [jnp.full((1,), _SESSION_NEG, jnp.int32), runmax[:-1]])
    brk = newrun | (sstart > prev_end + gap)
    cid = jnp.cumsum(brk.astype(jnp.int32)) - 1
    live = scode < SESSION_SENT_CODE  # sentinels sort last
    slot = jnp.where(live, cid, cap)
    # scatter destinations back to the concat (origin) domain
    return jnp.zeros((m,), jnp.int32).at[sidx].set(slot)


@functools.lru_cache(maxsize=256)
def session_step_kernel(spec, schema, layout: ColLayout, cap: int,
                        bcap: int):
    """The fused per-micro-batch session kernel — ONE dispatch, ZERO
    fetches: (arena, packed i32[3+n_cols, bcap], gap, close_cut, delta)
    -> arena'. `close_cut` retires already-closed entries (t1 <= cut)
    before the merge; `delta` shifts arena times on an epoch rebase.
    Late-record drops are decided by the HOST mirror before packing, so
    every packed record participates."""
    agg_inputs, null_keys = compile_agg_inputs(spec, schema)

    @jax.jit
    def step(arena, packed, gap, close_cut, delta):
        codes_b, ts_b, valid, cols = unpack_batch_device(
            packed, layout, null_keys)
        acode = arena["code"]
        alive = (acode < SESSION_SENT_CODE) & (arena["t1"] > close_cut)
        acode = jnp.where(alive, acode, SESSION_SENT_CODE)
        at0 = jnp.where(alive, arena["t0"] - delta, 0)
        at1 = jnp.where(alive, arena["t1"] - delta, 0)
        bcode = jnp.where(valid, codes_b, SESSION_SENT_CODE)
        dest = _session_chain_slots(
            jnp.concatenate([acode, bcode]),
            jnp.concatenate([at0, ts_b]),
            jnp.concatenate([at1, ts_b]), gap, cap)
        da, db = dest[:cap], dest[cap:]

        out = {
            "code": jnp.full((cap,), SESSION_SENT_CODE, jnp.int32)
            .at[da].min(acode, mode="drop")
            .at[db].min(bcode, mode="drop"),
            "t0": jnp.full((cap,), np.iinfo(np.int32).max, jnp.int32)
            .at[da].min(at0, mode="drop")
            .at[db].min(ts_b, mode="drop"),
            "t1": jnp.full((cap,), _SESSION_NEG, jnp.int32)
            .at[da].max(at1, mode="drop")
            .at[db].max(ts_b, mode="drop"),
        }
        empty = out["code"] >= SESSION_SENT_CODE
        out["t0"] = jnp.where(empty, 0, out["t0"])
        out["t1"] = jnp.where(empty, 0, out["t1"])

        done: set[str] = set()
        for i, (name, agg) in enumerate(zip(session_plane_names(spec),
                                            spec.aggs)):
            if name in done:
                continue  # aliased plane: the owner already updated it
            done.add(name)
            vfn, null_key = agg_inputs[i]
            if agg.kind == AggKind.COUNT_ALL:
                out[name] = jnp.zeros((cap,), jnp.int32) \
                    .at[da].add(arena[name], mode="drop") \
                    .at[db].add(valid.astype(jnp.int32), mode="drop")
                continue
            v = vfn(cols)
            input_ok = valid
            if null_key is not None:
                input_ok = input_ok & ~cols[null_key]
            if jnp.issubdtype(v.dtype, jnp.floating):
                input_ok = input_ok & jnp.isfinite(v)
            vf = v.astype(jnp.float32)
            if agg.kind == AggKind.COUNT:
                out[name] = jnp.zeros((cap,), jnp.int32) \
                    .at[da].add(arena[name], mode="drop") \
                    .at[db].add(input_ok.astype(jnp.int32), mode="drop")
            elif agg.kind == AggKind.SUM:
                out[name] = jnp.zeros((cap,), jnp.float32) \
                    .at[da].add(arena[name], mode="drop") \
                    .at[db].add(jnp.where(input_ok, vf, 0.0), mode="drop")
            elif agg.kind == AggKind.AVG:
                out[name] = jnp.zeros((cap,), jnp.float32) \
                    .at[da].add(arena[name], mode="drop") \
                    .at[db].add(jnp.where(input_ok, vf, 0.0), mode="drop")
                out[name + "_n"] = jnp.zeros((cap,), jnp.int32) \
                    .at[da].add(arena[name + "_n"], mode="drop") \
                    .at[db].add(input_ok.astype(jnp.int32), mode="drop")
            elif agg.kind == AggKind.MIN:
                out[name] = jnp.full((cap,), POS_INF, jnp.float32) \
                    .at[da].min(arena[name], mode="drop") \
                    .at[db].min(jnp.where(input_ok, vf, POS_INF),
                                mode="drop")
            elif agg.kind == AggKind.MAX:
                out[name] = jnp.full((cap,), NEG_INF, jnp.float32) \
                    .at[da].max(arena[name], mode="drop") \
                    .at[db].max(jnp.where(input_ok, vf, NEG_INF),
                                mode="drop")
            elif agg.kind == AggKind.APPROX_COUNT_DISTINCT:
                reg, rank = hll_update_indices(vf, spec.hll)
                out[name] = jnp.zeros((cap, spec.hll.m), jnp.int8) \
                    .at[da].max(arena[name], mode="drop") \
                    .at[db, reg].max(jnp.where(input_ok, rank, 0),
                                     mode="drop")
            elif agg.kind == AggKind.APPROX_QUANTILE:
                b = quantile_bin(vf, spec.qcfg)
                out[name] = jnp.zeros((cap, spec.qcfg.n_bins), jnp.int32) \
                    .at[da].add(arena[name], mode="drop") \
                    .at[db, b].add(input_ok.astype(jnp.int32),
                                   mode="drop")
            else:
                raise NotImplementedError(f"session agg {agg.kind}")
        return out

    return step


@functools.lru_cache(maxsize=256)
def session_merge_kernel(spec, cap: int, scap: int):
    """The segment-mode session kernel: the host pre-reduces the batch's
    rows into per-SEGMENT plane contributions (the reference host path's
    vectorized reduceat/add.at machinery — segments are the batch's own
    gap-chains, so the pre-merge is exact), and this kernel merges the
    segment arena into the open-session arena: ONE dispatch running the
    same sort + segmented scan over cap + scap entries, then row-level
    monoid scatters per plane. Chosen on backends where per-record
    device scatters lose to the host's vectorized reduction (CPU); the
    record-mode step (session_step_kernel) stays the wire-frugal
    default for real accelerators.

    (arena, seg {same planes, [scap]}, gap, close_cut, delta) -> arena'
    """

    @jax.jit
    def merge(arena, seg, gap, close_cut, delta):
        acode = arena["code"]
        alive = (acode < SESSION_SENT_CODE) & (arena["t1"] > close_cut)
        acode = jnp.where(alive, acode, SESSION_SENT_CODE)
        at0 = jnp.where(alive, arena["t0"] - delta, 0)
        at1 = jnp.where(alive, arena["t1"] - delta, 0)
        dest = _session_chain_slots(
            jnp.concatenate([acode, seg["code"]]),
            jnp.concatenate([at0, seg["t0"]]),
            jnp.concatenate([at1, seg["t1"]]), gap, cap)
        da, db = dest[:cap], dest[cap:]
        out = {
            "code": jnp.full((cap,), SESSION_SENT_CODE, jnp.int32)
            .at[da].min(acode, mode="drop")
            .at[db].min(seg["code"], mode="drop"),
            "t0": jnp.full((cap,), np.iinfo(np.int32).max, jnp.int32)
            .at[da].min(at0, mode="drop")
            .at[db].min(seg["t0"], mode="drop"),
            "t1": jnp.full((cap,), _SESSION_NEG, jnp.int32)
            .at[da].max(at1, mode="drop")
            .at[db].max(seg["t1"], mode="drop"),
        }
        empty = out["code"] >= SESSION_SENT_CODE
        out["t0"] = jnp.where(empty, 0, out["t0"])
        out["t1"] = jnp.where(empty, 0, out["t1"])
        done: set[str] = set()
        for name, agg in zip(session_plane_names(spec), spec.aggs):
            if name in done:
                continue  # aliased plane: the owner already merged it
            done.add(name)
            names = [name] if agg.kind != AggKind.AVG \
                else [name, name + "_n"]
            for nm in names:
                plane = arena[nm]
                if agg.kind == AggKind.MIN:
                    out[nm] = jnp.full((cap,), POS_INF, jnp.float32) \
                        .at[da].min(plane, mode="drop") \
                        .at[db].min(seg[nm], mode="drop")
                elif agg.kind == AggKind.MAX:
                    out[nm] = jnp.full((cap,), NEG_INF, jnp.float32) \
                        .at[da].max(plane, mode="drop") \
                        .at[db].max(seg[nm], mode="drop")
                elif agg.kind == AggKind.APPROX_COUNT_DISTINCT:
                    out[nm] = jnp.zeros(plane.shape, plane.dtype) \
                        .at[da].max(plane, mode="drop") \
                        .at[db].max(seg[nm], mode="drop")
                else:  # counts / sums / histograms: additive
                    out[nm] = jnp.zeros(plane.shape, plane.dtype) \
                        .at[da].add(plane, mode="drop") \
                        .at[db].add(seg[nm], mode="drop")
        return out

    return merge


@functools.lru_cache(maxsize=256)
def session_extract_kernel(spec, cap: int, pcap: int):
    """Read-only extract of the arena slots named by `slots` (pow2-
    padded, entries < 0 extract zeros): finalize every acc plane on
    device and pack into ONE int32 buffer [1 + n_aggs, pcap] — row 0 is
    the slot's code (host mirror cross-check), counts/HLL rows are i32,
    float rows f32-bitcast. One dispatch + one fetch serves a whole
    close cycle or peek, exactly like the fused window close."""

    @jax.jit
    def extract(arena, slots):
        ok = slots >= 0
        at = jnp.where(ok, slots, 0)
        rows = [jnp.where(ok, arena["code"][at], SESSION_SENT_CODE)]
        for name, agg in zip(session_plane_names(spec), spec.aggs):
            if agg.kind in (AggKind.COUNT_ALL, AggKind.COUNT):
                rows.append(jnp.where(ok, arena[name][at], 0))
                continue
            if agg.kind == AggKind.AVG:
                v = arena[name][at] / jnp.maximum(
                    arena[name + "_n"][at].astype(jnp.float32), 1.0)
            elif agg.kind == AggKind.MIN:
                v = arena[name][at]
                v = jnp.where(v == POS_INF, 0.0, v)
            elif agg.kind == AggKind.MAX:
                v = arena[name][at]
                v = jnp.where(v == NEG_INF, 0.0, v)
            elif agg.kind == AggKind.APPROX_COUNT_DISTINCT:
                est = hll_estimate(arena[name][at], spec.hll)
                rows.append(jnp.where(
                    ok, jnp.rint(est).astype(jnp.int32), 0))
                continue
            elif agg.kind == AggKind.APPROX_QUANTILE:
                hist = arena[name][at]
                est = quantile_estimate(hist, agg.quantile or 0.5,
                                        spec.qcfg)
                # an all-NULL-input session has an empty histogram:
                # the estimator's max(total, 1) target would read the
                # LAST bin; the host reference emits 0.0
                v = jnp.where(jnp.sum(hist, axis=-1) > 0, est, 0.0)
            else:
                v = arena[name][at].astype(jnp.float32)
            rows.append(jax.lax.bitcast_convert_type(
                jnp.where(ok, v, 0.0), jnp.int32))
        return jnp.stack(rows)

    return extract


@functools.lru_cache(maxsize=64)
def session_remap_kernel(cap: int, lcap: int):
    """Code-space compaction: live arena codes gather a dense, ORDER-
    PRESERVING new code through the pow2-padded LUT (codes >= lcap —
    including the sentinel — pass through), so the arena stays (code,
    t0)-sorted across the remap. One dispatch, no fetch."""

    @jax.jit
    def remap(arena, lut):
        code = arena["code"]
        out = dict(arena)
        out["code"] = jnp.where(code < lcap,
                                lut[jnp.clip(code, 0, lcap - 1)], code)
        return out

    return remap


@jax.jit
def rebase(state, delta):
    """Shift device-relative time by -delta (host re-anchored the epoch)."""
    out = dict(state)
    occupied = state["slot_start"] != EMPTY_START
    out["slot_start"] = jnp.where(
        occupied, state["slot_start"] - delta, state["slot_start"])
    return out


def grow_keys(state: dict[str, jnp.ndarray], spec: LatticeSpec,
              new_n_keys: int) -> dict[str, jnp.ndarray]:
    """Pad every keyed plane from K to new_n_keys (host, rare)."""
    old = spec.n_keys
    extra = new_n_keys - old
    out = {}
    for k, v in state.items():
        if k == "slot_start":
            out[k] = v
            continue
        pad_width = [(0, extra)] + [(0, 0)] * (v.ndim - 1)
        if k.endswith("_min"):
            out[k] = jnp.pad(v, pad_width, constant_values=np.float32(np.inf))
        elif k.endswith(("_max", "_topk", "_topk_distinct")):
            out[k] = jnp.pad(v, pad_width, constant_values=np.float32(-np.inf))
        else:
            out[k] = jnp.pad(v, pad_width)
    return out
