"""ctypes binding for the native wire-encode kernels (cpp/encode.cpp).

Build-on-demand like the native store (store/build.py); `load()` returns
None when no toolchain is available and the transport falls back to its
pure-numpy packer.
"""

from __future__ import annotations

import ctypes as C
import os
import threading

from hstream_tpu.common.nativebuild import build_so

_DIR = os.path.dirname(os.path.abspath(__file__))
SRC = os.path.join(_DIR, "cpp", "encode.cpp")
SO = os.path.join(_DIR, "cpp", "libencode.so")

_lock = threading.Lock()
_lib: C.CDLL | None = None
_tried = False

_i64 = C.c_int64
_p_i64 = C.POINTER(C.c_int64)
_p_i32 = C.POINTER(C.c_int32)
_p_u32 = C.POINTER(C.c_uint32)
_p_u8 = C.POINTER(C.c_uint8)
_p_f32 = C.POINTER(C.c_float)


def load() -> C.CDLL | None:
    """The native codec library, built on first use; None if unbuildable."""
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        try:
            lib = C.CDLL(build_so(SRC, SO, opt="-O3"))
        except Exception:
            return None
        lib.enc_pack_i64.argtypes = [_p_i64, _i64, _i64, C.c_int,
                                     _p_u32, _i64]
        lib.enc_pack_i32.argtypes = [_p_i32, _i64, _i64, C.c_int,
                                     _p_u32, _i64]
        lib.enc_pack_diff_i64.argtypes = [_p_i64, _i64, C.c_int,
                                          _p_u32, _i64]
        lib.enc_pack_bool.argtypes = [_p_u8, _i64, _p_u32, _i64]
        lib.enc_minmax_i64.argtypes = [_p_i64, _i64, _p_i64, _p_i64]
        lib.enc_minmax_i32.argtypes = [_p_i32, _i64, _p_i64, _p_i64]
        lib.enc_diff_stats_i64.argtypes = [_p_i64, _i64, _p_i64]
        lib.enc_diff_stats_i64.restype = C.c_int32
        lib.enc_quantize_f32.argtypes = [_p_f32, _i64, C.c_float,
                                         C.c_float, _i64, _p_i32,
                                         _p_i64, _p_i64]
        lib.enc_quantize_f32.restype = C.c_int32
        _lib = lib
        return _lib
