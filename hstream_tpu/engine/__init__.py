"""The TPU continuous-query engine.

The reference executes queries as a per-record interpreted processor DAG
(hstream-processing Processor.hs:282-297 — `forward` walks a HashMap of
closures record by record). The idiomatic TPU design inverts this:

  * records are staged into fixed-capacity **columnar micro-batches**
  * the whole query (filter -> project -> window assignment -> grouped
    aggregation) is **compiled once with jax.jit** into a step function
  * aggregate state lives on device as a dense **lattice**
    `[keys, window-slots, accumulators]`; per-batch updates are
    scatter-adds/mins/maxes that XLA fuses into a handful of kernels
  * window close is driven by a host-side watermark; closing extracts and
    resets one slot column — off the hot path
  * all accumulators are commutative monoids (count/sum/min/max/HLL
    registers/histogram bins), so multi-chip scaling is data-parallel
    sharding of batches with a merge collective at window close
    (see hstream_tpu.parallel)

Timestamps on device are int32 milliseconds relative to a per-query epoch
(int64 is unavailable without x64); the epoch is rebased on host when the
stream outlives the int32 range.
"""

from hstream_tpu.engine.types import ColumnType, Schema, HostBatch
from hstream_tpu.engine.window import TumblingWindow, HoppingWindow, SessionWindow
from hstream_tpu.engine.plan import (
    AggKind,
    AggSpec,
    PlanNode,
    SourceNode,
    FilterNode,
    ProjectNode,
    AggregateNode,
    JoinNode,
    SinkNode,
)
from hstream_tpu.engine.executor import QueryExecutor

__all__ = [
    "ColumnType",
    "Schema",
    "HostBatch",
    "TumblingWindow",
    "HoppingWindow",
    "SessionWindow",
    "AggKind",
    "AggSpec",
    "PlanNode",
    "SourceNode",
    "FilterNode",
    "ProjectNode",
    "AggregateNode",
    "JoinNode",
    "SinkNode",
    "QueryExecutor",
]
