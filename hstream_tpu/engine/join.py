"""Stream-stream interval JOIN execution.

Reference semantics (hstream-processing Stream.hs:222-300 /
joinStreamProcessor): each record is inserted into its side's
timestamped KV store, then probed against the other side's store over
[ts - within, ts + within]; matching pairs (equal join key) emit a
joined record whose fields are the union of both sides qualified by
stream name (genJoiner, Internal/Codegen.hs:62-67) and whose timestamp
is max(ts1, ts2). The joined stream feeds the rest of the plan
(filter -> window aggregate -> ...), exactly like the reference's
merged-stream task DAG (Codegen.hs:253-266).

Design: two execution paths with identical semantics.

  * Device path (the hot one): both sides live as device-resident
    sorted stores (engine.lattice interval-join kernels) and every
    micro-batch is ONE fused probe+insert dispatch plus ONE
    device->host fetch of the packed match buffer; watermark eviction
    is a vmapped two-sided compaction kernel and the int32 relative
    time space rebases on the shared join epoch instead of aborting.
    Matched pairs feed the inner aggregate columnar (optionally
    coalesced across micro-batches), so no joined-row dicts ever
    materialize. Activated once the columnar fast path is planned
    (`_plan_fast`); `use_device_join=False` forces the host path.
  * Host path (the equivalence reference): `_FlatIntervalStore` per
    side — flat sorted arrays probed with one searchsorted pair per
    batch, the batch restatement of the reference's per-record ordered
    map walk. Also serves plans the fast path cannot columnarize.

Join state is pruned by within + downstream grace, bounding memory
where the reference's in-memory store grows forever.
"""

from __future__ import annotations

from dataclasses import replace
from typing import Any, Mapping, Sequence

import numpy as np

from hstream_tpu.common.errors import SQLCodegenError
from hstream_tpu.common.logger import get_logger
from hstream_tpu.common.tracing import kernel_family
from hstream_tpu.engine.expr import BinOp, Col, Expr, eval_host
from hstream_tpu.engine.plan import AggregateNode
from hstream_tpu.engine.statestore import LastValueStore
from hstream_tpu.engine.types import canon_key, round_up_pow2
from hstream_tpu.engine.window import DEFAULT_GRACE_MS

log = get_logger("join")

_MISS = object()  # row.get sentinel: "field absent", distinct from None


def split_on_condition(on: Expr, left_streams: set[str],
                       right_streams: set[str]) -> tuple[list[Expr],
                                                         list[Expr]]:
    """Decompose `ON a.k1 = b.k2 [AND ...]` into per-side key-selector
    expression lists (evaluated over each side's RAW rows, so
    qualification is stripped). The reference's key selectors are
    functions of one side's record (Stream.hs:224-230)."""
    eqs: list[tuple[Expr, Expr]] = []

    def walk(e: Expr) -> None:
        if isinstance(e, BinOp) and e.op == "AND":
            walk(e.left)
            walk(e.right)
        elif isinstance(e, BinOp) and e.op == "=":
            eqs.append((e.left, e.right))
        else:
            raise SQLCodegenError(
                "JOIN ON must be a conjunction of equality comparisons")

    walk(on)

    def side_of(e: Expr) -> str:
        streams = set()

        def scan(x: Expr) -> None:
            if isinstance(x, Col):
                streams.add(x.stream)
            elif isinstance(x, BinOp):
                scan(x.left)
                scan(x.right)
            elif hasattr(x, "operand"):
                scan(x.operand)

        scan(e)
        named = {s for s in streams if s is not None}
        if named <= left_streams and named:
            return "l"
        if named <= right_streams and named:
            return "r"
        if not named:
            raise SQLCodegenError(
                "JOIN ON columns must be stream-qualified (s.col)")
        raise SQLCodegenError(
            f"JOIN ON side mixes streams {sorted(named)}")

    def strip(e: Expr) -> Expr:
        if isinstance(e, Col):
            return Col(e.name)
        if isinstance(e, BinOp):
            return BinOp(e.op, strip(e.left), strip(e.right))
        if hasattr(e, "operand"):
            return type(e)(e.op, strip(e.operand))
        return e

    lks: list[Expr] = []
    rks: list[Expr] = []
    for a, b in eqs:
        sa, sb = side_of(a), side_of(b)
        if sa == sb:
            raise SQLCodegenError("JOIN ON equality must relate both sides")
        if sa == "l":
            lks.append(strip(a))
            rks.append(strip(b))
        else:
            lks.append(strip(b))
            rks.append(strip(a))
    return lks, rks


class _JoinBase:
    """Shared plumbing of both join executors: alias/side routing, ON
    key split, joined-row construction, and the inner (downstream)
    executor lifecycle."""

    def __init__(self, plan, *, initial_keys: int = 1024,
                 batch_capacity: int = 4096, mesh=None,
                 data_axis: str = "data", key_axis: str = "key"):
        join = plan.join
        self.plan = plan
        # mesh-sharded execution: a mesh whose key axis has >1 devices
        # key-shards BOTH side stores (code % n_shards owns the entry)
        # and the inner aggregate; without one the join runs single-chip
        self.mesh = mesh
        self.data_axis = data_axis
        self.key_axis = key_axis
        self.left_name = plan.source
        self.right_name = join.right.name
        if self.right_name == self.left_name:
            raise SQLCodegenError("self-join needs distinct streams")
        self.join_type = join.join_type
        if self.join_type not in ("INNER", "JOIN"):
            raise SQLCodegenError(
                f"{self.join_type} JOIN not supported (INNER only, like "
                "the reference's RJoinInner path)")
        self._aliases = {self.left_name: "l", self.right_name: "r"}
        left_al = {self.left_name}
        right_al = {self.right_name}
        la = getattr(plan, "source_alias", None)
        if la:
            self._aliases[la] = "l"
            left_al.add(la)
        if join.right.alias:
            self._aliases[join.right.alias] = "r"
            right_al.add(join.right.alias)
        self.left_keys, self.right_keys = split_on_condition(
            join.on, left_al, right_al)
        self._inner = None
        self._inner_plan = replace(plan, join=None)
        self._initial_keys = initial_keys
        self._batch_capacity = batch_capacity
        # deferred-change tuning proxied onto the (lazily created) inner
        # executor, so the server's _tune_executor and bench harnesses
        # treat a join exactly like a plain aggregate: the downstream
        # changelog extraction pipelines/batches instead of serializing
        # the join's compute loop with one D2H fetch per micro-batch
        self.emit_changes = bool(getattr(plan, "emit_changes", False))
        self.supports_deferred_changes = True
        self._inner_tuning: dict[str, object] = {}
        # observability plane (ISSUE 13): per-family dispatch observer
        # for the probe kernel (the inner aggregate carries its own)
        self.dispatch_observer = None   # callable (family, seconds)

    def _side_of(self, stream: str | None) -> str:
        if stream is None:
            raise SQLCodegenError(
                f"{type(self).__name__}.process requires stream=<name or "
                "alias>: a join consumes two streams and must know each "
                "batch's origin")
        side = self._aliases.get(stream)
        if side is None:
            raise SQLCodegenError(
                f"stream {stream!r} is not part of this join")
        return side

    def _joined_row(self, lrow: Mapping[str, Any],
                    rrow: Mapping[str, Any]) -> dict[str, Any]:
        """Union of both sides, stream-qualified (genJoiner); bare names
        kept as a convenience with left precedence."""
        out = {}
        for f, v in lrow.items():
            out[f"{self.left_name}.{f}"] = v
        for f, v in rrow.items():
            out[f"{self.right_name}.{f}"] = v
        for f, v in rrow.items():
            out.setdefault(f, v)
        for f, v in lrow.items():
            out[f] = v
        return out

    def _key(self, exprs: list[Expr], row: Mapping[str, Any]):
        try:
            vals = tuple(eval_host(e, row) for e in exprs)
        except (TypeError, KeyError):
            return None
        if any(v is None for v in vals):
            return None
        return canon_key(vals)

    def _inner_process(self, joined, jts):
        if self._inner is None:
            from hstream_tpu.sql.codegen import make_executor

            self._inner = make_executor(
                self._inner_plan, sample_rows=joined,
                initial_keys=self._initial_keys,
                batch_capacity=self._batch_capacity,
                mesh=self.mesh)
            self._apply_inner_tuning()
        return self._inner.process(joined, jts)

    def _apply_inner_tuning(self) -> None:
        inner = self._inner
        if inner is None or not getattr(inner, "supports_deferred_changes",
                                        False):
            return
        for k, v in self._inner_tuning.items():
            setattr(inner, k, v)

    def _proxy_tuning(self, name: str, value) -> None:
        self._inner_tuning[name] = value
        self._apply_inner_tuning()

    # change-drain knobs ride through to the inner executor (set before
    # OR after its lazy creation); reads fall back to the pending value
    @property
    def defer_change_decode(self) -> bool:
        return bool(self._inner_tuning.get("defer_change_decode", False))

    @defer_change_decode.setter
    def defer_change_decode(self, v: bool) -> None:
        self._proxy_tuning("defer_change_decode", bool(v))

    @property
    def change_drain_depth(self) -> int:
        return int(self._inner_tuning.get("change_drain_depth", 1))

    @change_drain_depth.setter
    def change_drain_depth(self, v: int) -> None:
        self._proxy_tuning("change_drain_depth", int(v))

    @property
    def async_change_drain(self) -> bool:
        return bool(self._inner_tuning.get("async_change_drain", False))

    @async_change_drain.setter
    def async_change_drain(self, v: bool) -> None:
        self._proxy_tuning("async_change_drain", bool(v))

    # ---- drains (API parity with QueryExecutor) ----------------------------

    def flush_changes(self) -> list[dict[str, Any]]:
        """Deliver every lagging emission: coalesced match rows staged
        for the inner step first, then the inner executor's deferred
        changelog extracts — the same barrier QueryExecutor exposes.
        A lone columnar change batch rides through unmaterialized."""
        from hstream_tpu.common.columnar import extend_rows

        rows = (self.flush_staged()
                if hasattr(self, "flush_staged") else [])
        inner = self._inner
        if inner is not None and hasattr(inner, "flush_changes"):
            rows = extend_rows(rows, inner.flush_changes())
        return rows if rows is not None else []

    def has_pending_changes(self) -> bool:
        if getattr(self, "_staged_n", 0):
            return True
        if getattr(self, "_pending_matches", None):
            return True
        inner = self._inner
        if inner is None:
            return False
        hp = getattr(inner, "has_pending_changes", None)
        if hp is not None:
            return bool(hp())
        return bool(getattr(inner, "_pending_changes", None))

    def peek(self) -> list[dict[str, Any]]:
        return [] if self._inner is None else self._inner.peek()

    # contract: dispatches<=0 fetches<=0
    def read_version(self) -> tuple | None:
        """Read-cache validity key (ISSUE 20): peek() serves the inner
        aggregate's state, so the version IS the inner's — prefixed
        pre-creation so an empty join caches too. None (inner without
        versioning) disables caching for this executor."""
        inner = self._inner
        if inner is None:
            return ("join-empty", id(self))
        fn = getattr(inner, "read_version", None)
        return None if fn is None else fn()

    # contract: dispatches<=0 fetches<=0
    def live_min_win_end(self) -> int | None:
        """Smallest live winEnd of the inner aggregate (ISSUE 20
        closed-only fast path); None = no live window could emit one."""
        fn = getattr(self._inner, "live_min_win_end", None)
        return None if fn is None else fn()

    def close_due_windows(self) -> list[dict[str, Any]]:
        if self._inner is None or not hasattr(self._inner,
                                              "close_due_windows"):
            return []
        return self._inner.close_due_windows()

    # contract: dispatches<=0 fetches<=1
    def block_until_ready(self) -> None:
        if self._inner is not None and hasattr(self._inner,
                                               "block_until_ready"):
            self._inner.block_until_ready()

    # contract: dispatches<=0 fetches<=0
    def device_plane_bytes(self) -> dict[str, int]:
        """Device bytes of the inner aggregate's planes, "agg."-
        prefixed (JoinExecutor extends this with its device stores) —
        nbytes metadata reads only (ISSUE 18)."""
        fn = getattr(self._inner, "device_plane_bytes", None)
        if fn is None:
            return {}
        return {f"agg.{k}": v for k, v in fn().items()}


class TableJoinExecutor(_JoinBase):
    """Executes `SELECT ... FROM l INNER JOIN TABLE(r) ON ...`.

    Reference semantics (Stream.hs:302-344, joinStreamTable): the right
    side is a TABLE — the latest row per join key of a changelog stream.
    Stream records probe the table and emit one joined row when the key
    is present; table records only update state (no retroactive
    emission). State is bounded by the table's key cardinality.
    """

    def __init__(self, plan, *, initial_keys: int = 1024,
                 batch_capacity: int = 4096):
        super().__init__(plan, initial_keys=initial_keys,
                         batch_capacity=batch_capacity)
        # the keyed last-value table (engine.statestore.LastValueStore)
        self._table = LastValueStore()

    @property
    def table(self) -> dict:
        """key -> (ts, row) view of the last-value table (snapshots,
        introspection)."""
        return self._table.data

    def process(self, rows: Sequence[Mapping[str, Any]],
                ts_ms: Sequence[int], stream: str | None = None
                ) -> list[dict[str, Any]]:
        side = self._side_of(stream)
        if side == "r":
            for row, ts in zip(rows, ts_ms):
                key = self._key(self.right_keys, row)
                if key is None:
                    continue
                self._table.update(key, int(ts), row)
            return []
        joined: list[dict[str, Any]] = []
        jts: list[int] = []
        for row, ts in zip(rows, ts_ms):
            key = self._key(self.left_keys, row)
            if key is None:
                continue
            match = self._table.lookup(key)
            if match is None:
                continue  # INNER: stream rows without a table row drop
            joined.append(self._joined_row(row, match))
            jts.append(int(ts))
        if not joined:
            return []
        return self._inner_process(joined, jts)


class _FlatIntervalStore:
    """One side of the interval join as flat sorted arrays.

    Rows live in arrays sorted by a composite (key code, ts) int64 —
    code * 2^41 + (ts - t0) — so a WHOLE batch probes with one
    searchsorted pair and inserts with one np.insert: no per-key Python.
    The reference walks a per-record ordered map instead
    (Processing/Store.hs tksPut/tksRange); this is that store's batch
    restatement. Key codes are dense ints owned by the executor
    (shared across both sides so probes and inserts agree).
    """

    TS_BITS = 41                     # ~69 years of ms offsets
    SPAN = 1 << TS_BITS

    def __init__(self, key_rev: list):
        self.code = np.empty(0, np.int64)
        self.ts = np.empty(0, np.int64)
        self.comp = np.empty(0, np.int64)
        self.rows = np.empty(0, object)
        self.t0: int | None = None
        self.key_rev = key_rev       # shared code -> canon key (executor)

    def __len__(self) -> int:
        return len(self.code)

    def _rebase(self, t0: int) -> None:
        self.t0 = t0
        self.comp = self.code * self.SPAN + (self.ts - t0)

    def insert_sorted(self, code: np.ndarray, ts: np.ndarray,
                      rows: np.ndarray) -> None:
        """Insert a batch already sorted by (code, ts)."""
        if len(code) == 0:
            return
        mn = int(ts.min())
        new_t0 = mn if self.t0 is None else min(mn, self.t0)
        hi = int(ts.max())
        if len(self.ts):
            hi = max(hi, int(self.ts.max()))
        if hi - new_t0 >= self.SPAN:
            # an offset past 2^41 ms (~69 years) would overflow into a
            # neighboring code's composite range and silently corrupt
            # probes — loud failure beats wrong join results. Checked
            # over existing AND incoming rows: a rebase to an older t0
            # shifts every resident row's offset too.
            raise SQLCodegenError(
                "join record timestamps span more than 2^41 ms; "
                "timestamps must be epoch milliseconds")
        if self.t0 is None or new_t0 < self.t0:
            self._rebase(new_t0)
        bcomp = code * self.SPAN + (ts - self.t0)
        if len(self.comp) == 0:
            self.code, self.ts, self.comp = code, ts, bcomp
            self.rows = rows
            return
        idx = np.searchsorted(self.comp, bcomp)
        self.code = np.insert(self.code, idx, code)
        self.ts = np.insert(self.ts, idx, ts)
        self.comp = np.insert(self.comp, idx, bcomp)
        self.rows = np.insert(self.rows, idx, rows)

    def probe(self, code: np.ndarray, lo_ts: np.ndarray,
              hi_ts: np.ndarray) -> tuple[np.ndarray, np.ndarray] | None:
        """Per query i: [start, end) indices of rows with this code and
        lo_ts[i] <= ts <= hi_ts[i]."""
        if len(self.comp) == 0:
            return None
        lo = np.clip(lo_ts - self.t0, 0, self.SPAN - 1)
        hi = np.clip(hi_ts - self.t0, -1, self.SPAN - 1)
        lo_i = np.searchsorted(self.comp, code * self.SPAN + lo, "left")
        hi_i = np.searchsorted(self.comp, code * self.SPAN + hi, "right")
        return lo_i, np.maximum(hi_i, lo_i)

    def prune(self, min_ts: int) -> None:
        keep = self.ts >= min_ts
        if not keep.all():
            self.code = self.code[keep]
            self.ts = self.ts[keep]
            self.comp = self.comp[keep]
            self.rows = self.rows[keep]

    def remap_codes(self, new_of_old: np.ndarray,
                    resort: bool = False) -> None:
        """Apply a code compaction. A dense remap preserves sorted
        order; a shard-class-preserving remap (sharded device mode)
        does not, so ``resort`` re-sorts by the new composite."""
        self.code = new_of_old[self.code]
        if self.t0 is None:
            return
        self.comp = self.code * self.SPAN + (self.ts - self.t0)
        if resort and len(self.comp):
            order = np.argsort(self.comp, kind="stable")
            self.code = self.code[order]
            self.ts = self.ts[order]
            self.comp = self.comp[order]
            self.rows = self.rows[order]

    @property
    def by_key(self) -> dict:
        """key tuple -> (ts list, rows list) view (snapshots; same shape
        TimestampedKVStore exposes, so the blob format is unchanged)."""
        out: dict[tuple, tuple[list, list]] = {}
        for i in range(len(self.code)):
            key = self.key_rev[int(self.code[i])]
            tss, rows = out.setdefault(key, ([], []))
            tss.append(int(self.ts[i]))
            rows.append(self.rows[i])
        return out


class JoinExecutor(_JoinBase):
    """Executes `SELECT ... FROM l [INNER|LEFT] JOIN r WITHIN(...) ON ...`.

    API: process(rows, ts_ms, stream=<source name or alias>) — the task
    runtime feeds records from BOTH streams through the one executor,
    tagging each batch with its origin (the reference merges both
    sources into one task, Codegen.hs:250-266). Joined rows feed the
    inner (aggregate/stateless) executor built over the joined schema.
    """

    # the task runtime may feed columnar batches straight through
    # process_columnar (no row materialization on the server path)
    supports_columnar_join = True

    def __init__(self, plan, *, initial_keys: int = 1024,
                 batch_capacity: int = 4096, mesh=None,
                 data_axis: str = "data", key_axis: str = "key"):
        super().__init__(plan, initial_keys=initial_keys,
                         batch_capacity=batch_capacity, mesh=mesh,
                         data_axis=data_axis, key_axis=key_axis)
        join = plan.join
        self.within = join.within.ms

        # retention: a future in-grace record can probe back `within`;
        # grace defaults to the downstream window's (or the SQL default)
        node = plan.node
        grace = DEFAULT_GRACE_MS
        if isinstance(node, AggregateNode) and node.window is not None:
            grace = node.window.grace_ms
        self.retention_ms = self.within + grace

        # shared join-key code space across both sides
        self._jcode: dict[tuple, int] = {}
        self._jcode_rev: list[tuple] = []
        self._kid_lut = np.full(1024, -1, np.int32)  # code -> inner key id
        self._stores = {"l": _FlatIntervalStore(self._jcode_rev),
                        "r": _FlatIntervalStore(self._jcode_rev)}
        self.watermark: int = -1
        # fast-path plumbing (computed lazily once the inner executor
        # and both sides' observed fields exist)
        self._fields = {"l": set(), "r": set()}
        self._fast: dict | None = None   # None = unknown yet
        # opt-in: accumulate this many matched rows before stepping the
        # inner executor — on a real link every step dispatch pays a
        # round trip, so small probe batches must coalesce (the same
        # lever as the ingest pipeline's staged caps). Emission then
        # lags by the coalesce horizon; callers flush via flush_staged.
        self.coalesce_rows = 0
        self._staged: list[tuple] = []   # (key_ids, jts, cols, nulls)
        self._staged_n = 0
        # Device-resident join: once the columnar fast path is planned,
        # both sides migrate into device stores and each micro-batch
        # becomes ONE fused probe+insert dispatch + ONE fetch of the
        # packed match buffer (engine.lattice interval-join kernels).
        # use_device_join=False pins the host reference path.
        self.use_device_join = True
        self._dev: dict | None = None
        # >1 defers match-buffer fetches: buffers stack into one
        # batched D2H transfer every `depth` micro-batches, so the
        # round trip amortizes (emission then lags; flush_staged is
        # the barrier). The fused close's deferred-fetch idiom.
        self.match_drain_depth = 1
        self._pending_matches: list[tuple] = []
        # probe-path dispatch accounting: the device-join contract is
        # ONE probe dispatch per micro-batch (and fetches <= batches);
        # tests and bench assert probe_dispatches == probe_batches
        self.join_stats = {
            "probe_batches": 0, "probe_dispatches": 0,
            "probe_fetches": 0, "match_redispatches": 0,
            "evict_dispatches": 0, "rebase_dispatches": 0,
            "store_grows": 0, "fused_batches": 0,
        }
        # device activations that failed and degraded (permanently, for
        # this executor) to the retained host reference path; the query
        # task mirrors deltas into the device_path_fallbacks counter
        self.device_fallbacks = 0
        # dispatches that ran under shard_map (probe/fused/evict); the
        # query task mirrors deltas into the sharded_dispatches family
        self._sharded_dispatches = 0

    @property
    def sharded_dispatches(self) -> int:
        """Sharded device dispatches, join probe plane + the inner
        aggregate's own (step/extract) — the per-query counter the
        stats plane exposes."""
        return self._sharded_dispatches + int(getattr(
            self._inner, "sharded_dispatches", 0) or 0)

    # ---- device cost plane (ISSUE 18) --------------------------------------

    # contract: dispatches<=0 fetches<=0
    def _device_values(self):
        """Live device values of the probe plane — the fence/measure
        target of the device-time sampler (late-bound: stores and the
        inner state are REPLACED by every probe/fused dispatch)."""
        dev = self._dev
        if dev is None:
            return ()
        vals = [dev["stores"]["l"], dev["stores"]["r"]]
        inner_state = getattr(self._inner, "state", None)
        if inner_state is not None:
            vals.append(inner_state)
        return vals

    # contract: dispatches<=0 fetches<=0
    def device_plane_bytes(self) -> dict[str, int]:
        """Exact per-plane device bytes: both sides' interval stores
        ("l."/"r."-prefixed) plus the inner aggregate's lattice planes
        ("agg."-prefixed) — nbytes metadata reads, zero dispatches."""
        from hstream_tpu.stats.devicecost import plane_bytes

        out = super().device_plane_bytes()
        dev = self._dev
        if dev is not None:
            for side in ("l", "r"):
                for k, v in plane_bytes(dev["stores"][side]).items():
                    out[f"{side}.{k}"] = v
        return out

    # ---- ingest ------------------------------------------------------------
    #
    # Batched: the per-record reference loop (insert my side, probe the
    # other side over [ts-within, ts+within], Stream.hs:238-300) is
    # restated as: group the batch by join key, batch-append each group
    # to my side's store, then probe the other side with ONE
    # searchsorted pair per group (the other side never changes during
    # the batch, so insert/probe need no interleaving). Matched pairs
    # feed the inner aggregate COLUMNAR (key ids broadcast per group
    # when the GROUP BY key is the join key) — no joined-row dicts on
    # the steady path.

    def process(self, rows: Sequence[Mapping[str, Any]],
                ts_ms: Sequence[int], stream: str | None = None
                ) -> list[dict[str, Any]]:
        side = self._side_of(stream)
        mine = self._stores[side]
        other = self._stores["r" if side == "l" else "l"]
        my_keys = self.left_keys if side == "l" else self.right_keys
        n = len(rows)
        out: list[dict[str, Any]] = []
        if n:
            if rows[0]:
                self._fields[side].update(rows[0])
            ts = np.asarray(ts_ms, np.int64)
            codes = self._batch_codes(my_keys, rows)       # -1 = no key
            keep = codes >= 0
            if not keep.all():
                kidx = np.nonzero(keep)[0]
                codes = codes[kidx]
                bts = ts[kidx]
            else:
                kidx = None
                bts = ts
            if len(codes):
                order = np.lexsort((bts, codes))
                codes = codes[order]
                bts = bts[order]
                ridx = order if kidx is None else kidx[order]
                if self._device_ready():
                    lay = self._dev["lay"][side]
                    flags, vals = self._encode_join_cols(
                        lay, [rows[j] for j in ridx.tolist()])
                    out = self._device_batch(side, codes, bts, flags,
                                             vals)
                else:
                    out = self._host_batch(side, mine, other, codes,
                                           bts, rows, ridx)
        self._advance_watermark(max((int(t) for t in ts_ms),
                                    default=self.watermark))
        return out

    def process_columnar(self, ts_ms, cols: Mapping[str, np.ndarray],
                         nulls: Mapping[str, np.ndarray] | None = None,
                         *, stream: str | None = None
                         ) -> list[dict[str, Any]]:
        """Columnar twin of process(): int64 absolute-ms timestamps plus
        named numpy columns (str/object arrays for strings; a null-mask
        cell means the field is ABSENT from that record, like the
        per-record decode's dropped keys). On the device path the batch
        packs straight from the columns — vectorized key encode, no
        per-row Python at all; until the device path activates (or on
        the host reference path) rows materialize once and take the row
        path, so semantics are identical."""
        n = len(ts_ms)
        if n == 0:
            return []
        side = self._side_of(stream)
        self._fields[side].update(cols.keys())
        ts = np.asarray(ts_ms, np.int64)
        out: list[dict[str, Any]] = []
        enc = None
        if self._device_ready():
            my_keys = (self.left_keys if side == "l"
                       else self.right_keys)
            enc = self._columnar_batch(side, my_keys, ts, cols, nulls)
        if enc is not None:
            codes, bts, flags, vals = enc
            if len(codes):
                out = self._device_batch(side, codes, bts, flags, vals)
            self._advance_watermark(int(ts.max()))
            return out
        # fallback: materialize rows once (pre-activation, non-Col ON
        # keys, or untyped columns) and run the row path
        return self.process(self._rows_from_cols(cols, nulls, n),
                            ts.tolist(), stream=stream)

    def _advance_watermark(self, new_wm: int) -> None:
        if new_wm <= self.watermark:
            return
        self.watermark = new_wm
        cutoff = self.watermark - self.retention_ms
        if cutoff > 0:
            if self._dev is not None:
                self._maybe_evict(cutoff)
            else:
                self._stores["l"].prune(cutoff)
                self._stores["r"].prune(cutoff)

    def _host_batch(self, side, mine, other, codes, bts, rows,
                    ridx) -> list[dict[str, Any]]:
        """The host reference path: batch searchsorted probe over the
        flat sorted stores (see _FlatIntervalStore)."""
        brows = np.empty(len(ridx), object)
        for i, j in enumerate(ridx.tolist()):
            brows[i] = dict(rows[j])
        # probe the other side BEFORE inserting: the reference
        # loop probes only the opposite store, which this batch
        # never mutates, so insert/probe need no interleaving
        pr = other.probe(codes, bts - self.within, bts + self.within)
        mine.insert_sorted(codes, bts, brows)
        if pr is None:
            return []
        lo_i, hi_i = pr
        cnt = hi_i - lo_i
        tot = int(cnt.sum())
        if not tot:
            return []
        start = np.cumsum(cnt) - cnt
        oidx = (np.arange(tot, dtype=np.int64)
                - np.repeat(start, cnt)
                + np.repeat(lo_i, cnt))
        rep = np.repeat(np.arange(len(codes)), cnt)
        jts = np.maximum(bts[rep], other.ts[oidx])
        return self._emit_matches(side, brows, rep, codes[rep], other,
                                  oidx, jts)

    def _batch_codes(self, my_keys, rows) -> np.ndarray:
        """Dense join-key code per row (-1 = null key, skipped). One
        shared code space for both sides; compacted when it outgrows
        the composite-key budget."""
        # compact BEFORE encoding so this batch's fresh keys get live
        # codes (compacting afterwards would remap them to -1 and drop
        # the rows)
        if len(self._jcode_rev) + len(rows) >= (1 << 22) - 1:
            self._compact_codes()
            if len(self._jcode_rev) + len(rows) >= (1 << 22) - 1:
                raise SQLCodegenError(
                    "join key cardinality within the retention window "
                    f"exceeds {1 << 22} distinct keys")
        jcode = self._jcode
        rev = self._jcode_rev
        out = np.empty(len(rows), np.int64)

        def code_of(k) -> int:
            c = jcode.get(k)
            if c is None:
                c = len(rev)
                jcode[k] = c
                rev.append(k)
            return c

        if all(isinstance(e, Col) for e in my_keys):
            names = [e.name for e in my_keys]
            if len(names) == 1:
                nm = names[0]
                for i, r in enumerate(rows):
                    v = r.get(nm)
                    out[i] = -1 if v is None else code_of(canon_key((v,)))
            else:
                for i, r in enumerate(rows):
                    vals = tuple(r.get(c) for c in names)
                    out[i] = (-1 if any(v is None for v in vals)
                              else code_of(canon_key(vals)))
        else:
            for i, r in enumerate(rows):
                k = self._key(my_keys, r)
                out[i] = -1 if k is None else code_of(k)
        return out

    # contract: dispatches<=0 fetches<=1
    def _compact_codes(self) -> None:
        """Code-space compaction: keep only codes still live in either
        store (retention bounds them), reassign codes, remap stores +
        shadows + lut + dict.

        Device mode fetches BOTH sides' code planes in one stacked
        transfer (they share cap): hstream-analyze's dispatch pass
        caught the original per-side fetch loop — two round trips on
        the ingest path every time the code space filled.

        Single-chip remaps densely in sorted order (store order is
        preserved). Sharded mode must keep every code's shard
        residence, so the remap is residue-class-preserving (new =
        rank-within-class * n_shards + class): per-shard device order
        survives the gather remap, but GLOBAL (code, ts) order does
        not — the host shadows re-sort, and the code space keeps holes
        where the classes are unbalanced."""
        from hstream_tpu.engine import lattice

        sjl = self._dev.get("sjl") if self._dev is not None else None
        parts = [self._stores["l"].code, self._stores["r"].code]
        if self._dev is not None:
            self._refresh_counts()
            if self._dev["n"]["l"] or self._dev["n"]["r"]:
                import jax.numpy as jnp

                codes = np.asarray(jnp.stack(
                    [self._dev["stores"]["l"]["code"],
                     self._dev["stores"]["r"]["code"]]))
                # eviction is lazy: dead-but-resident entries past the
                # live prefix must stay mapped too, so take every
                # non-sentinel slot (works for flat and sharded planes)
                parts.append(np.unique(
                    codes[codes < lattice.JOIN_SENT_CODE]
                ).astype(np.int64))
        live = np.union1d(parts[0], np.concatenate(parts[1:])
                          if len(parts) > 1 else parts[0])
        if sjl is not None:
            cls = live % sjl.n_shards
            new_codes = np.empty(len(live), np.int64)
            for s in range(sjl.n_shards):
                msk = cls == s
                new_codes[msk] = (np.arange(int(msk.sum()),
                                            dtype=np.int64)
                                  * sjl.n_shards + s)
        else:
            new_codes = np.arange(len(live), dtype=np.int64)
        new_of_old = np.full(len(self._jcode_rev), -1, np.int64)
        new_of_old[live] = new_codes
        resort = sjl is not None
        for st in self._stores.values():
            st.remap_codes(new_of_old, resort=resort)
        if self._dev is not None:
            for st in self._dev["shadow"].values():
                # the shadows size every match buffer: leaving them on
                # the old code space would corrupt probe totals
                st.remap_codes(new_of_old, resort=resort)
            self._remap_device_codes(new_of_old)
        new_rev: list = [None] * (int(new_codes.max()) + 1
                                  if len(live) else 0)
        for nc, oc in zip(new_codes.tolist(), live.tolist()):
            new_rev[nc] = self._jcode_rev[oc]
        self._jcode.clear()
        self._jcode.update({k: i for i, k in enumerate(new_rev)
                            if k is not None})
        self._jcode_rev[:] = new_rev      # in place: stores share it
        lut = np.full(max(len(new_rev), 1024), -1, np.int32)
        old_lut = self._kid_lut
        inb = live < len(old_lut)
        lut[new_codes[inb]] = old_lut[live[inb]]
        self._kid_lut = lut

    # ---- match emission ----------------------------------------------------

    def _feed_inner_columnar(self, key_ids, jts, cols, nulls
                             ) -> list[dict[str, Any]]:
        """Step (or coalesce-stage) one columnar match batch into the
        inner executor — shared by the host and device probe paths.
        The joined stream's watermark is the JOIN's watermark (both
        probe paths forward it before stepping matches, so the fused
        device kernel and this host feed apply the same late mask)."""
        inner = self._inner
        if (getattr(inner, "watermark_abs", None) is not None
                and self.watermark > inner.watermark_abs):
            inner.watermark_abs = self.watermark
        if self.coalesce_rows > 0:
            self._staged.append((key_ids, jts, cols, nulls))
            self._staged_n += len(key_ids)
            if self._staged_n < self.coalesce_rows:
                return []
            return self._drain_staged(keep_tail=True)
        return self._inner.process_columnar(key_ids, jts, cols, nulls)

    def _emit_matches(self, side, brows, rep, mcodes, other, oidx,
                      jts) -> list[dict[str, Any]]:
        fast = self._fast_info()
        if fast is not None:
            key_ids = self._match_key_ids(mcodes)
            cols, nulls = self._match_cols(fast, side, brows, rep,
                                           other, oidx)
            return self._feed_inner_columnar(key_ids, jts, cols, nulls)
        # general path: materialize joined-row dicts (also the sample
        # source for the inner executor's construction)
        orows = other.rows[oidx]
        joined: list[dict[str, Any]] = []
        for i in range(len(rep)):
            row, orow = brows[rep[i]], orows[i]
            joined.append(self._joined_row(row, orow) if side == "l"
                          else self._joined_row(orow, row))
        res = self._inner_process(joined, jts.tolist())
        # re-plan while disabled: a field observed on a later batch can
        # make a previously-unresolvable column resolvable
        if not self._fast:
            self._plan_fast()
        return res

    def _match_key_ids(self, mcodes: np.ndarray) -> np.ndarray:
        """Inner-executor key ids per match via a code-indexed LUT (the
        GROUP BY key IS the join key on this path)."""
        lut = self._kid_lut
        if len(lut) < len(self._jcode_rev):
            grown = np.full(max(len(self._jcode_rev), 2 * len(lut)),
                            -1, np.int32)
            grown[:len(lut)] = lut
            self._kid_lut = lut = grown
        need = np.unique(mcodes[lut[mcodes] < 0])
        for c in need.tolist():
            lut[c] = self._inner.key_id_for(self._jcode_rev[c])
        return lut[mcodes]

    def flush_staged(self) -> list[dict[str, Any]]:
        """Step the inner executor with every lagging match: deferred
        device match buffers fetch + decode first (they may stage into
        the coalesce buffer), then every coalesced row steps. A lone
        columnar batch from either half stays a ColumnarEmit."""
        from hstream_tpu.common.columnar import extend_rows

        out = self._drain_matches() if self._pending_matches else None
        out = extend_rows(out, self._drain_staged(keep_tail=False))
        return out if out is not None else []

    def _drain_staged(self, *, keep_tail: bool) -> list[dict[str, Any]]:
        """Step coalesced matches. keep_tail=True steps only whole
        inner-batch-capacity chunks and re-stages the remainder, so the
        steady state reuses ONE compiled step shape (each distinct
        padded cap is a separate XLA compile)."""
        if not self._staged:
            return []
        staged, self._staged = self._staged, []
        self._staged_n = 0
        key_ids = np.concatenate([s[0] for s in staged])
        jts = np.concatenate([s[1] for s in staged])
        names = staged[0][2].keys()
        cols = {c: np.concatenate([s[2][c] for s in staged])
                for c in names}
        nulls = None
        if any(s[3] for s in staged):
            nulls = {}
            for c in names:
                parts = [s[3][c] if (s[3] and c in s[3])
                         else np.zeros(len(s[0]), np.bool_)
                         for s in staged]
                m = np.concatenate(parts)
                if m.any():
                    nulls[c] = m
            nulls = nulls or None
        n = len(key_ids)
        cap = self._inner.batch_capacity
        cut = n - (n % cap) if keep_tail else n
        if keep_tail and cut < n:
            tail_nulls = (None if nulls is None else
                          {c: m[cut:] for c, m in nulls.items()})
            self._staged.append((key_ids[cut:], jts[cut:],
                                 {c: v[cut:] for c, v in cols.items()},
                                 tail_nulls))
            self._staged_n = n - cut
        if cut == 0:
            return []
        head_nulls = (None if nulls is None else
                      {c: m[:cut] for c, m in nulls.items()})
        return self._inner.process_columnar(
            key_ids[:cut], jts[:cut],
            {c: v[:cut] for c, v in cols.items()}, head_nulls)

    def _fast_info(self) -> dict | None:
        if self._fast is None and self._inner is not None:
            self._plan_fast()
        return self._fast if isinstance(self._fast, dict) else None

    def _resolve_col(self, name: str) -> tuple[str, str] | None:
        """Joined-row column name -> (side, source column): qualified
        names split on the alias; bare names take left precedence, the
        same rule _joined_row applies."""
        if "." in name:
            pre, col = name.split(".", 1)
            s = self._aliases.get(pre)
            if s is not None:
                return s, col
        if name in self._fields["l"]:
            return "l", name
        if name in self._fields["r"]:
            return "r", name
        return None

    def close_due_windows(self) -> list[dict[str, Any]]:
        from hstream_tpu.common.columnar import extend_rows

        rows = (self.flush_staged()
                if (self._staged or self._pending_matches) else [])
        # flush_staged can surface a lone ColumnarEmit (no .extend)
        rows = extend_rows(rows, super().close_due_windows())
        return rows if rows is not None else []

    def _plan_fast(self) -> None:
        """Enable the columnar match path when (a) the inner executor
        has one, (b) its GROUP BY columns are exactly the join key (so
        inner key ids broadcast per probe group), and (c) every column
        the inner step needs resolves to one side."""
        inner = self._inner
        self._fast = False
        if inner is None or not hasattr(inner, "process_columnar"):
            return
        # after a snapshot restore the observed-field sets are empty;
        # reseed them from any stored row so bare names still resolve
        for s in ("l", "r"):
            if not self._fields[s] and len(self._stores[s]):
                self._fields[s].update(self._stores[s].rows[0])
        knames_l = ([e.name for e in self.left_keys]
                    if all(isinstance(e, Col) for e in self.left_keys)
                    else None)
        knames_r = ([e.name for e in self.right_keys]
                    if all(isinstance(e, Col) for e in self.right_keys)
                    else None)
        resolved = [self._resolve_col(c) for c in inner.group_cols]
        if any(r is None for r in resolved):
            return
        gs = [s for s, _ in resolved]
        gcols = [c for _, c in resolved]
        if not (len(set(gs)) == 1
                and ((gs[0] == "l" and gcols == knames_l)
                     or (gs[0] == "r" and gcols == knames_r))):
            return
        need = {}
        for name in inner._needed_cols:
            if "." in name:
                pre, col = name.split(".", 1)
                s = self._aliases.get(pre)
                if s is not None:
                    need[name] = (s, col)
                    continue
            if (name in self._fields["l"]
                    or name in self._fields["r"]):
                # bare name: gather per match row with _joined_row's
                # left-precedence (observation can't tell which side a
                # heterogeneous stream carries the field on)
                need[name] = ("both", name)
            else:
                return
        self._fast = {"need": need}

    def _match_cols(self, fast, side, brows, rep, other,
                    oidx) -> tuple[dict, dict | None]:
        """Columns the inner step needs, gathered straight from the
        matched source rows (no joined dicts)."""
        from hstream_tpu.engine.types import ColumnType

        inner = self._inner
        tot = len(rep)
        cols: dict[str, np.ndarray] = {}
        nulls: dict[str, np.ndarray] = {}
        src_cache: dict[tuple, list] = {}
        for name, (cside, col) in fast["need"].items():
            vals = src_cache.get((cside, col))
            if vals is None:
                if cside == "both":
                    # left-precedence bare name, decided per match row
                    lrows, lidx = ((brows, rep) if side == "l"
                                   else (other.rows, oidx))
                    rrows, ridx = ((other.rows, oidx) if side == "l"
                                   else (brows, rep))
                    vals = []
                    for li, ri in zip(lidx.tolist(), ridx.tolist()):
                        v = lrows[li].get(col, _MISS)
                        if v is _MISS:
                            v = rrows[ri].get(col)
                        vals.append(v)
                elif cside == side:
                    vals = [brows[i].get(col) for i in rep.tolist()]
                else:
                    vals = [other.rows[j].get(col)
                            for j in oidx.tolist()]
                src_cache[(cside, col)] = vals
            want = inner.schema.type_of(name)
            msk = np.zeros(tot, np.bool_)
            if want == ColumnType.STRING:
                enc = inner.dicts[name].encode
                arr = np.empty(tot, np.int32)
                for i, v in enumerate(vals):
                    if v is None:
                        arr[i] = -1
                        msk[i] = True
                    else:
                        arr[i] = enc(str(v))
            else:
                dt = (np.bool_ if want == ColumnType.BOOL
                      else np.int32 if want == ColumnType.INT
                      else np.float32)
                arr = np.zeros(tot, dt)
                for i, v in enumerate(vals):
                    if v is None or not isinstance(v, (int, float, bool)):
                        msk[i] = True
                    else:
                        arr[i] = v
            cols[name] = arr
            if msk.any():
                nulls[name] = msk
        return cols, (nulls or None)

    # ---- device-resident join ----------------------------------------------
    #
    # Once the columnar fast path is planned, both sides migrate onto
    # the device (engine.lattice interval-join kernels): per-side
    # sorted stores of (code, ts_rel, flags, packed needed columns),
    # one fused probe+insert dispatch per micro-batch, one D2H fetch of
    # the packed match buffer (deferrable/stackable via
    # match_drain_depth), vmapped two-sided eviction on watermark
    # advance, and epoch rebase instead of the host store's span abort.
    # Host stores stay the equivalence-reference path
    # (use_device_join=False).

    DEVICE_STORE_CAPACITY = 1 << 14   # initial per-side slots (grows)
    REBASE_REL_MS = 1 << 30           # re-anchor epoch past this

    def _device_ready(self) -> bool:
        if self._dev is not None:
            return True
        if not self.use_device_join:
            return False
        fast = self._fast_info()
        if fast is None:
            return False
        try:
            from hstream_tpu.common.faultinject import FAULTS

            if FAULTS.active:  # chaos: provoke an activation failure
                FAULTS.point("device.activate")
            return self._activate_device(fast)
        except Exception as e:  # noqa: BLE001 — an activation failure
            # (kernel build, migration, device OOM, injected fault)
            # degrades to the retained host reference path instead of
            # killing the query; results are identical, only slower
            log.warning(
                "device join activation failed (%s: %s); staying on "
                "the host reference path", type(e).__name__, e)
            self._dev = None
            self.use_device_join = False
            self.device_fallbacks += 1
            return False

    def _activate_device(self, fast: dict) -> bool:
        """Plan per-side column layouts from the fast-path need map and
        migrate the host stores' contents into device stores. Each need
        name stores on every side it can resolve from ('both' = bare
        name with left precedence, stored on both sides with a present
        bit)."""
        from hstream_tpu.engine import lattice

        lay: dict[str, list[tuple[str, str]]] = {"l": [], "r": []}
        for name, (cside, col) in fast["need"].items():
            for s in ("l", "r"):
                if cside in (s, "both"):
                    lay[s].append((name, col))
        if max(len(lay["l"]), len(lay["r"])) > lattice.JOIN_MAX_COLS:
            self.use_device_join = False  # flags word out of bits
            return False
        cap = self.DEVICE_STORE_CAPACITY
        need = max(len(self._stores["l"]), len(self._stores["r"])) * 2
        cap = round_up_pow2(need, lo=cap)
        cands = [int(st.ts.min()) for st in self._stores.values()
                 if len(st)]
        if self.watermark >= 0:
            cands.append(self.watermark)
        t0 = (min(cands) - self.retention_ms) if cands else None
        sjl = None
        if (self.mesh is not None
                and self.key_axis in self.mesh.axis_names
                and self.mesh.shape[self.key_axis] > 1):
            from hstream_tpu.parallel.lattice import ShardedJoinLattice

            # per-shard capacity keeps the single-chip formula: the
            # worst key distribution lands every entry on one shard, so
            # this trades memory (n_shards x) for never growing on skew
            sjl = ShardedJoinLattice(
                self.mesh, self.key_axis, cap, 1024, 4096,
                len(lay["l"]), len(lay["r"]))
        self._dev = {
            "lay": lay,
            "cap": cap,
            "sjl": sjl,
            "t0": t0,
            "n": {"l": 0, "r": 0},
            # match buffers start small and stick at the pow2 the
            # workload's match totals actually need (the host shadow
            # sizes them EXACTLY per batch, so they never overflow):
            # a buffer sized to batch_capacity would make every fetch
            # pay for a worst case that never happens
            "match_cap": 4096,
            "bcaps": set(),
            "evict_cutoff": -(1 << 62),
            "stores": {
                "l": (sjl.init_store("l") if sjl is not None
                      else lattice.init_join_store(cap, len(lay["l"]))),
                "r": (sjl.init_store("r") if sjl is not None
                      else lattice.init_join_store(cap, len(lay["r"]))),
            },
            # host shadow of each side's (code, ts) multiset, pruned at
            # the probe cutoff: gives EXACT match totals before every
            # dispatch (match buffers never overflow, the fused kernel
            # can never silently truncate) for the cost of a rowless
            # numpy insert+searchsorted per batch
            "shadow": {"l": _FlatIntervalStore(self._jcode_rev),
                       "r": _FlatIntervalStore(self._jcode_rev)},
        }
        self._dev["feed"] = self._build_feed_plans()
        # migrate BOTH sides before clearing either host store: a
        # failure partway (caught in _device_ready) must leave the host
        # reference path intact to fall back on
        for s in ("l", "r"):
            self._migrate_store(s)
        for s in ("l", "r"):
            self._stores[s] = _FlatIntervalStore(self._jcode_rev)
        return True

    def _build_feed_plans(self) -> dict | None:
        """Hashable per-side plans mapping the inner step's needed
        columns (and null masks) onto match-buffer sources, for the
        fully fused probe->aggregate kernel. None when the inner
        executor is not a device lattice (stateless joins keep the
        match-fetch path)."""
        from hstream_tpu.engine import lattice
        from hstream_tpu.engine.expr import columns_of

        inner = self._inner
        if (getattr(inner, "spec", None) is None
                or not hasattr(inner, "_null_specs")):
            return None
        if ((self._dev.get("sjl") is not None)
                != (getattr(inner, "_sharded", None) is not None)):
            # a sharded join can only fuse into a sharded inner lattice
            # (and vice versa); a mismatch keeps the match-fetch path
            return None
        lay_idx = {s: {name: j for j, (name, _c)
                       in enumerate(self._dev["lay"][s])}
                   for s in ("l", "r")}
        plans: dict[str, tuple] = {}
        for side in ("l", "r"):
            other = "r" if side == "l" else "l"

            def entry(name):
                cside, _col = self._fast["need"][name]
                jm = lay_idx[side].get(name, -1)
                jo = lay_idx[other].get(name, -1)
                if cside == side:
                    return ("m", jm, jo)
                if cside == other:
                    return ("o", jm, jo)
                # bare name, left precedence: the SQL left side is the
                # probing batch when side == "l", else the probed store
                return ("both" if side == "l" else "both_o", jm, jo)

            feed = tuple(
                (name, lattice.layout_tag(inner.schema.type_of(name)))
                + entry(name)
                for name in self._fast["need"])
            nulls_plan = tuple(
                (key, tuple(entry(c) for c in refs))
                for key, refs in inner._null_specs)
            filter_nulls = (tuple(
                entry(c) for c in sorted(columns_of(inner._filter_expr)))
                if inner._filter_expr is not None else ())
            plans[side] = (feed, nulls_plan, filter_nulls)
        return plans

    def _migrate_store(self, side: str) -> None:
        """Move one host store's live entries into the device store
        (activation / snapshot restore): pack host rows into the device
        entry layout and device_put directly — already (code, ts)
        sorted, so no kernel dispatch is needed."""
        import jax
        import jax.numpy as jnp

        st = self._stores[side]
        n = len(st)
        if n == 0:
            return
        dev = self._dev
        if int(st.ts.max()) - dev["t0"] >= (1 << 31):
            # the host store's span guard allows 2^41 ms but the device
            # store's relative space is int32: a silent wrap here would
            # corrupt every probe bound (found by hstream-analyze,
            # overflow-narrowing)
            raise SQLCodegenError(
                "join store spans more than the int32 relative range "
                "at device activation; reduce within/grace retention")
        dev["shadow"][side].insert_sorted(
            st.code.copy(), st.ts.copy(), np.empty(n, object))
        lay = dev["lay"][side]
        flags, vals = self._encode_join_cols(
            lay, [st.rows[i] for i in range(n)])
        from hstream_tpu.engine import lattice

        cap = dev["cap"]
        sjl = dev.get("sjl")
        if sjl is not None:
            # distribute entries into their owning shard's slice; each
            # residue class of a (code, ts)-sorted sequence is itself
            # (code, ts)-sorted, so per-shard order needs no re-sort
            ns = sjl.n_shards
            scode = np.full((ns, cap), lattice.JOIN_SENT_CODE, np.int32)
            sts = np.zeros((ns, cap), np.int32)
            sfl = np.zeros((ns, cap), np.int32)
            scv = np.zeros((ns, len(lay), cap), np.int32)
            cls = (st.code % ns).astype(np.int64)
            for s in range(ns):
                m = np.nonzero(cls == s)[0]
                k = len(m)
                scode[s, :k] = st.code[m].astype(np.int32)
                sts[s, :k] = (st.ts[m] - dev["t0"]).astype(np.int32)
                sfl[s, :k] = flags[m]
                scv[s, :, :k] = vals[:, m]
            dev["stores"][side] = sjl.put_store(
                {"code": scode, "ts": sts, "flags": sfl, "cols": scv})
            dev["n"][side] = n
            return
        code = np.full(cap, lattice.JOIN_SENT_CODE, np.int32)
        code[:n] = st.code.astype(np.int32)
        ts = np.zeros(cap, np.int32)
        ts[:n] = (st.ts - dev["t0"]).astype(np.int32)
        f32 = np.zeros(cap, np.int32)
        f32[:n] = flags
        cv = np.zeros((len(lay), cap), np.int32)
        cv[:, :n] = vals
        dev["stores"][side] = {
            "code": jax.device_put(jnp.asarray(code)),
            "ts": jax.device_put(jnp.asarray(ts)),
            "flags": jax.device_put(jnp.asarray(f32)),
            "cols": jax.device_put(jnp.asarray(cv)),
        }
        dev["n"][side] = n

    def _encode_join_cols(self, lay, rows) -> tuple[np.ndarray,
                                                    np.ndarray]:
        """Pack one side's needed columns for a list of rows into
        (flags i32[n], values i32[len(lay), n]): 2 bits per column in
        flags (bit 2j = SQL NULL / non-scalar, bit 2j+1 = field
        present), values f32-bitcast / i32 / bool / dictionary id —
        the same per-value rules as the host fast path (_match_cols)."""
        from hstream_tpu.engine.types import ColumnType

        inner = self._inner
        n = len(rows)
        flags = np.zeros(n, np.int32)
        vals = np.zeros((len(lay), n), np.int32)
        for j, (name, col) in enumerate(lay):
            nullb = np.int32(1 << (2 * j))
            presb = np.int32(1 << (2 * j + 1))
            want = inner.schema.type_of(name)
            if want == ColumnType.STRING:
                enc = inner.dicts[name].encode
                arr = np.zeros(n, np.int32)
                for i, r in enumerate(rows):
                    v = r.get(col, _MISS)
                    if v is _MISS:
                        flags[i] |= nullb
                    elif v is None:
                        flags[i] |= nullb | presb
                    else:
                        arr[i] = enc(str(v))
                        flags[i] |= presb
                vals[j] = arr
            else:
                dt = (np.bool_ if want == ColumnType.BOOL
                      else np.int32 if want == ColumnType.INT
                      else np.float32)
                arr = np.zeros(n, dt)
                for i, r in enumerate(rows):
                    v = r.get(col, _MISS)
                    if v is _MISS:
                        flags[i] |= nullb
                    elif v is None or not isinstance(v, (int, float,
                                                         bool)):
                        flags[i] |= nullb | presb
                    else:
                        arr[i] = v
                        flags[i] |= presb
                vals[j] = (arr.view(np.int32) if dt is np.float32
                           else arr.astype(np.int32))
        return flags, vals

    # ---- columnar ingest (vectorized encode, no row dicts) ----------------

    def _columnar_batch(self, side, my_keys, ts, cols, nulls):
        """Vectorized (codes, bts, flags, vals) in (code, ts) sorted
        order for a columnar batch, or None when this batch cannot
        encode columnar (non-Col ON keys, untyped columns) — the
        caller materializes rows once and takes the row path."""
        codes = self._batch_codes_columnar(my_keys, cols, nulls,
                                           len(ts))
        if codes is None:
            return None
        enc = self._encode_join_cols_columnar(
            self._dev["lay"][side], cols, nulls, len(ts))
        if enc is None:
            return None
        flags, vals = enc
        keep = codes >= 0
        if not keep.all():
            kidx = np.nonzero(keep)[0]
            codes = codes[kidx]
            bts = ts[kidx]
            flags = flags[kidx]
            vals = vals[:, kidx]
        else:
            bts = ts
        if not len(codes):
            return codes, bts, flags, vals
        order = np.lexsort((bts, codes))
        return (codes[order], bts[order], flags[order],
                vals[:, order])

    def _batch_codes_columnar(self, my_keys, cols, nulls,
                              n: int) -> np.ndarray | None:
        """Dense join-key codes for a columnar batch: unique + encode
        per DISTINCT value, one gather per row — the vectorized twin of
        _batch_codes. None = fall back to the row path."""
        if not all(isinstance(e, Col) for e in my_keys):
            return None
        # compact BEFORE encoding, like _batch_codes
        if len(self._jcode_rev) + n >= (1 << 22) - 1:
            self._compact_codes()
            if len(self._jcode_rev) + n >= (1 << 22) - 1:
                raise SQLCodegenError(
                    "join key cardinality within the retention window "
                    f"exceeds {1 << 22} distinct keys")
        jcode = self._jcode
        rev = self._jcode_rev

        def code_of(k) -> int:
            c = jcode.get(k)
            if c is None:
                c = len(rev)
                jcode[k] = c
                rev.append(k)
            return c

        col_vals: list[np.ndarray] = []
        col_codes: list[np.ndarray] = []
        null_any = np.zeros(n, np.bool_)
        for e in my_keys:
            arr = cols.get(e.name)
            if arr is None:
                return None if n else np.empty(0, np.int64)
            nm = nulls.get(e.name) if nulls else None
            if nm is not None:
                null_any |= nm
            try:
                uniq, inv = np.unique(np.asarray(arr),
                                      return_inverse=True)
            except TypeError:
                return None  # incomparable mixed values: row path
            col_vals.append(uniq)
            col_codes.append(inv.astype(np.int64))
        if len(my_keys) == 1:
            uniq = col_vals[0]
            lut = np.fromiter(
                (code_of(canon_key((v,))) for v in uniq.tolist()),
                np.int64, len(uniq))
            out = lut[col_codes[0]]
        else:
            combined = col_codes[0]
            for inv, uniq in zip(col_codes[1:], col_vals[1:]):
                combined = combined * len(uniq) + inv
            u, uinv = np.unique(combined, return_inverse=True)
            lut = np.empty(len(u), np.int64)
            for i, cu in enumerate(u.tolist()):
                idxs = []
                for uniq in reversed(col_vals[1:]):
                    idxs.append(cu % len(uniq))
                    cu //= len(uniq)
                idxs.append(cu)
                idxs.reverse()
                key = tuple(col_vals[k][i2].item()
                            if hasattr(col_vals[k][i2], "item")
                            else col_vals[k][i2]
                            for k, i2 in enumerate(idxs))
                lut[i] = code_of(canon_key(key))
            out = lut[uinv]
        if null_any.any():
            out = np.where(null_any, -1, out)
        return out

    def _encode_join_cols_columnar(self, lay, cols, nulls, n: int):
        """Vectorized twin of _encode_join_cols over whole columns:
        (flags i32[n], vals i32[len(lay), n]), or None when a column's
        dtype cannot encode without per-row inspection."""
        from hstream_tpu.engine.types import ColumnType

        inner = self._inner
        flags = np.zeros(n, np.int32)
        vals = np.zeros((len(lay), n), np.int32)
        for j, (name, col) in enumerate(lay):
            nullb = np.int32(1 << (2 * j))
            presb = np.int32(1 << (2 * j + 1))
            arr = cols.get(col)
            if arr is None:
                flags |= nullb  # field absent from every record
                continue
            arr = np.asarray(arr)
            nm = nulls.get(col) if nulls else None
            want = inner.schema.type_of(name)
            if want == ColumnType.STRING:
                enc = inner.dicts[name].encode
                try:
                    uniq, inv = np.unique(arr, return_inverse=True)
                except TypeError:
                    return None
                lut = np.fromiter((enc(str(v)) for v in uniq.tolist()),
                                  np.int32, len(uniq))
                vals[j] = lut[inv]
                row_flags = presb
            else:
                if arr.dtype == object or arr.dtype.kind in ("U", "S"):
                    return None  # untyped numerics: row path decides
                try:
                    if want == ColumnType.FLOAT:
                        vals[j] = arr.astype(
                            np.float32, copy=False).view(np.int32)
                    elif want == ColumnType.BOOL:
                        vals[j] = (np.asarray(arr) != 0).astype(
                            np.int32)
                    else:
                        vals[j] = arr.astype(np.int32)
                except (TypeError, ValueError):
                    return None
                row_flags = presb
            flags |= row_flags
            if nm is not None and nm.any():
                # a null-masked cell is an ABSENT field (drop_null row
                # parity): null bit on, present bit off, value zeroed
                flags[nm] = (flags[nm] | nullb) & ~presb
                vals[j, nm] = 0
        return flags, vals

    @staticmethod
    def _rows_from_cols(cols, nulls, n: int) -> list[dict[str, Any]]:
        """Materialize columnar input into per-row dicts (fallback /
        host reference path) with null-masked cells dropped — the same
        row shape the per-record decode produces, including
        columnar.to_rows' f64 parity (integral doubles decode as ints,
        like Struct number decoding)."""
        host = {}
        masks = {}
        for name, arr in cols.items():
            if isinstance(arr, np.ndarray) and arr.dtype == np.float64:
                vals = [int(v) if v.is_integer() else v
                        for v in arr.tolist()]
            elif isinstance(arr, np.ndarray):
                vals = arr.tolist()
            else:
                vals = list(arr)
            nm = nulls.get(name) if nulls else None
            if nm is not None and nm.any():
                masks[name] = nm.tolist()
            host[name] = vals
        names = list(host)
        if not names:
            return [{} for _ in range(n)]
        rows = [dict(zip(names, vv))
                for vv in zip(*(host[c] for c in names))]
        for name, mask in masks.items():
            for row, isnull in zip(rows, mask):
                if isnull:
                    del row[name]
        return rows

    def _dev_bcap(self, n: int) -> int:
        """Sticky pow2 batch capacity (each distinct shape is its own
        XLA compile; varying batch sizes converge on a few)."""
        caps = self._dev["bcaps"]
        for c in sorted(caps):
            if n <= c <= 8 * max(n, 1):
                return c
        cap = round_up_pow2(n, lo=1024)
        caps.add(cap)
        return cap

    # contract: dispatches<=1 fetches<=0
    def _device_batch(self, side, codes, bts, flags, vals
                      ) -> list[dict[str, Any]]:
        """One micro-batch on the device path: pack, ONE device
        dispatch. When the downstream aggregate can fuse, the dispatch
        scatters the matched pairs straight into the inner lattice —
        matches never leave the device; otherwise the packed match
        buffer is the one (deferrable, stackable) D2H fetch. `flags` /
        `vals` are the side's pre-encoded entry columns in (code, ts)
        sorted order (row or columnar encoder)."""
        from hstream_tpu.engine import lattice

        dev = self._dev
        n = len(codes)
        if dev["t0"] is None:
            dev["t0"] = int(bts.min()) - self.retention_ms
        self._maybe_rebase(int(bts.min()), int(bts.max()))
        if dev["n"][side] + n > dev["cap"]:
            self._refresh_counts()  # upper bound -> exact
        if dev["n"][side] + n > dev["cap"]:
            # capacity pressure: evict with the PRE-batch watermark
            # cutoff — the probe below must still see every entry the
            # host reference would (it prunes only after the batch)
            self._dispatch_evict(self.watermark - self.retention_ms, 0)
            self._refresh_counts()
            if dev["n"][side] + n > dev["cap"]:
                self._grow_device(round_up_pow2(
                    dev["n"][side] + n, lo=dev["cap"] * 2))
            elif max(dev["n"].values()) + n > dev["cap"] // 2:
                # hysteresis: an eviction that leaves the store more
                # than half full would force another sort within a few
                # batches — grow once instead of evicting every batch
                self._grow_device(dev["cap"] * 2)
        # exact match total from the host shadow (code/ts only): sizes
        # the padded match width so the kernel can never truncate
        other_side = "r" if side == "l" else "l"
        cutoff_abs = (self.watermark - self.retention_ms
                      if self.watermark >= 0 else None)
        shadow_o = dev["shadow"][other_side]
        lo_ts = bts - self.within
        if cutoff_abs is not None:
            lo_ts = np.maximum(lo_ts, cutoff_abs)
        pr = shadow_o.probe(codes, lo_ts, bts + self.within)
        sjl = dev.get("sjl")
        if pr is None:
            total = 0
        elif sjl is not None:
            # the match buffer is PER SHARD: size it to the worst
            # shard's total (each shard packs its own segment)
            per = np.bincount((codes % sjl.n_shards).astype(np.int64),
                              weights=(pr[1] - pr[0]).astype(np.float64),
                              minlength=sjl.n_shards)
            total = int(per.max())
        else:
            total = int((pr[1] - pr[0]).sum())
        dev["shadow"][side].insert_sorted(codes, bts,
                                          np.empty(n, object))
        if cutoff_abs is not None and cutoff_abs > 0:
            dev["shadow"][side].prune(cutoff_abs)
            shadow_o.prune(cutoff_abs)
        if total > dev["match_cap"]:
            dev["match_cap"] = round_up_pow2(total,
                                             lo=dev["match_cap"] * 2)
        kid = self._match_key_ids(codes)
        lay = dev["lay"][side]
        bcap = self._dev_bcap(n)
        buf = np.zeros((4 + len(lay), bcap), np.int32)
        buf[0, :n] = codes
        buf[0, n:] = lattice.JOIN_SENT_CODE
        buf[1, :n] = (bts - dev["t0"]).astype(np.int32)
        buf[2, :n] = kid
        buf[3, :n] = flags
        if len(lay):
            buf[4:, :n] = vals
        other = dev["stores"][other_side]
        # the probe-visible retention cutoff mirrors the host
        # reference's prune-before-this-batch state: the device store
        # may still hold older entries (eviction is lazy, capacity
        # only), but matches must not see them
        cutoff = np.int32(np.clip(
            (cutoff_abs - dev["t0"]) if cutoff_abs is not None
            else -(1 << 31), -(1 << 31), (1 << 31) - 1))
        self.join_stats["probe_batches"] += 1
        self.join_stats["probe_dispatches"] += 1
        if dev.get("feed") is not None and self._fuse_ok(bts):
            return self._fused_batch(side, other_side, buf, n, cutoff)
        if sjl is not None:
            with kernel_family("probe", self.dispatch_observer,
                               ready=self._device_values):
                dev["stores"][side], packed = sjl.probe_insert(
                    side, dev["stores"][side], other, buf, np.int32(n),
                    np.int32(self.within), cutoff,
                    match_cap=dev["match_cap"])
            self._sharded_dispatches += 1
        else:
            kern = lattice.join_probe_insert(
                dev["cap"], bcap, dev["match_cap"], len(lay),
                len(dev["lay"][other_side]))
            with kernel_family("probe", self.dispatch_observer,
                               ready=self._device_values):
                dev["stores"][side], packed = kern(
                    dev["stores"][side], other, buf, np.int32(n),
                    np.int32(self.within), cutoff)
        self._note_insert(side, n)
        # the pending entry keeps (batch, other-store ref) alive so a
        # truncated match buffer could re-probe wider (unreachable
        # while the shadow sizes the width, kept as belt-and-braces)
        self._pending_matches.append(
            (packed, side, dev["t0"], buf, n, other, cutoff))
        if len(self._pending_matches) >= max(self.match_drain_depth, 1):
            return self._drain_matches()
        return []

    # ---- fused probe -> inner aggregate (zero per-batch D2H) --------------

    def _fuse_ok(self, bts) -> bool:
        """Whether this batch can take the fully fused kernel: the
        inner executor's host window bookkeeping must be able to track
        the conservative joined-ts range [min bts, max bts + within]
        without a per-row scan — the windows-in-range set must fit the
        fast gate and introduce no slot aliasing (mirrors _gap_guard's
        collision check; a batch that fails falls back to the
        match-fetch path, which runs the full guard)."""
        inner = self._inner
        w = inner.window
        if w is None:
            return True
        if inner.epoch is not None and int(bts.min()) < inner.epoch:
            return False  # pre-epoch joined ts: row path handles
        adv = w.advance_ms
        lo = int(bts.min())
        hi = int(bts.max()) + self.within
        span = (hi - hi % adv - (lo - lo % adv)) // adv + 1
        back = w.windows_per_record - 1
        if span + back > min(inner.spec.n_slots, 64):
            return False
        period = adv * inner.spec.n_slots
        starts = np.arange(lo - lo % adv - back * adv,
                           hi - hi % adv + adv, adv)
        if inner.watermark_abs >= 0:
            starts = starts[starts + w.size_ms + w.grace_ms
                            > inner.watermark_abs]
        cand = set(starts.tolist()) | set(inner._open)
        by_res: dict[int, int] = {}
        for s in cand:
            r = s % period
            if r in by_res and by_res[r] != s:
                return False  # slot aliasing: let _gap_guard handle it
            by_res[r] = s
        return True

    # contract: dispatches<=1 fetches<=0
    def _fused_batch(self, side, other_side, buf, n, cutoff
                     ) -> list[dict[str, Any]]:
        """Dispatch the probe+insert+inner-scatter kernel: the matched
        pairs aggregate on device, so the batch costs ZERO D2H — the
        changelog extract (already deferred/batched) is the only fetch
        left on the join hot path."""
        from hstream_tpu.common.columnar import extend_rows
        from hstream_tpu.engine import lattice

        dev = self._dev
        inner = self._inner
        lo = int(buf[1, :n].min()) + dev["t0"]
        hi = int(buf[1, :n].max()) + dev["t0"] + self.within
        inner._ensure_epoch(lo)
        inner._maybe_rebase(hi)
        # watermark forwarding: the joined stream's watermark is the
        # JOIN's watermark (both paths apply the same sync in
        # _feed_inner_columnar, so late-mask semantics stay identical)
        if self.watermark > inner.watermark_abs:
            inner.watermark_abs = self.watermark
        wm_rel = np.int32(max(inner.watermark_abs - inner.epoch, -1)
                          if inner.watermark_abs >= 0 else -1)
        ts_off = np.int32(dev["t0"] - inner.epoch)
        feed, nulls_plan, filter_nulls = dev["feed"][side]
        sjl = dev.get("sjl")
        if sjl is not None:
            with kernel_family("probe", self.dispatch_observer,
                               ready=self._device_values):
                dev["stores"][side], inner.state, _total = \
                    sjl.probe_insert_step(
                        side, inner._sharded, dev["stores"][side],
                        dev["stores"][other_side], buf, np.int32(n),
                        np.int32(self.within), cutoff, inner.state,
                        wm_rel, ts_off, feed_plan=feed,
                        nulls_plan=nulls_plan,
                        filter_nulls=filter_nulls,
                        match_cap=dev["match_cap"])
            self._sharded_dispatches += 1
        else:
            kern = lattice.join_probe_insert_step(
                dev["cap"], buf.shape[1], dev["match_cap"],
                len(dev["lay"][side]), len(dev["lay"][other_side]),
                inner.spec, inner.schema, inner._filter_expr, feed,
                nulls_plan, filter_nulls)
            with kernel_family("probe", self.dispatch_observer,
                               ready=self._device_values):
                dev["stores"][side], inner.state, _total = kern(
                    dev["stores"][side], dev["stores"][other_side], buf,
                    np.int32(n), np.int32(self.within), cutoff,
                    inner.state, wm_rel, ts_off)
        self._note_insert(side, n)
        self.join_stats["fused_batches"] += 1
        # inner host bookkeeping over the conservative ts range (the
        # overapproximated window set is semantics-free: empty windows
        # close without emitting via the count>0 filter)
        try:
            if inner.window is not None:
                inner._track_windows(np.asarray([lo, hi], np.int64))
            bmax = hi - self.within  # this batch's max record ts
            if bmax > inner.watermark_abs:
                inner.watermark_abs = bmax
            out = None
            if inner.emit_changes:
                out = extend_rows(out, inner._drain_changes())
            out = extend_rows(out, inner.close_due_windows())
            # a lone ColumnarEmit rides through unmaterialized — the
            # fused path must not be the one place rows re-dictify
            return out if out is not None else []
        finally:
            inner._no_close.clear()
            inner._touched_this_call.clear()

    # contract: dispatches<=0 fetches<=1
    def _drain_matches(self) -> list[dict[str, Any]]:
        """Fetch + decode every pending match buffer: buffers of one
        shape stack into ONE device->host transfer (fetch count, not
        bytes, dominates on real links), then decode columnar and feed
        the inner executor."""
        from hstream_tpu.engine.lattice import stack_pow2

        if not self._pending_matches:
            return []
        pending, self._pending_matches = self._pending_matches, []
        # piggyback the deferred post-eviction counts on this sync:
        # everything queued ahead of the match buffers has executed by
        # the time they arrive, so the 2-int copy is free here and the
        # host upper bound stays fresh without hot-loop blocking
        self._refresh_counts()
        host: list[tuple] = []
        if len(pending) == 1:
            packed, *rest = pending[0]
            self.join_stats["probe_fetches"] += 1
            host.append((np.asarray(packed), *rest))
        else:
            by_shape: dict[tuple, list] = {}
            for ent in pending:
                by_shape.setdefault(tuple(ent[0].shape), []).append(ent)
            groups: dict[int, tuple] = {}
            for group in by_shape.values():
                self.join_stats["probe_fetches"] += 1
                stacked = np.asarray(stack_pow2([e[0] for e in group]))
                for ent, hbuf in zip(group, stacked):
                    groups[id(ent)] = (hbuf, *ent[1:])
            # preserve submission order across shape groups
            host = [groups[id(ent)] for ent in pending]
        from hstream_tpu.common.columnar import extend_rows

        out = None
        sjl = self._dev.get("sjl")
        for hbuf, side, t0, buf, n, other, cutoff in host:
            nm = len(self._dev["lay"][side])
            if sjl is not None:
                # per-shard headers sit at column s * match_cap; any
                # shard's truncation forces the whole-buffer redo
                mc = hbuf.shape[1] // sjl.n_shards
                total = max(int(hbuf[0, s * mc])
                            for s in range(sjl.n_shards))
                width = mc
            else:
                total = int(hbuf[0, 0])
                width = hbuf.shape[1]
            if total > width:
                hbuf = self._reprobe_wider(side, buf, n, other, cutoff,
                                           total)
            out = extend_rows(out, self._decode_matches(side, t0, hbuf,
                                                        nm))
        return out if out is not None else []

    # contract: dispatches<=1 fetches<=1
    def _reprobe_wider(self, side, buf, n, other, cutoff,
                       total) -> np.ndarray:
        """Match-overflow redo: probe-only at the next pow2 width (the
        batch is already inserted; `other` is the exact store the fused
        kernel probed, `cutoff` its retention mask)."""
        from hstream_tpu.engine import lattice

        dev = self._dev
        match_cap = round_up_pow2(total, lo=dev["match_cap"] * 2)
        dev["match_cap"] = max(dev["match_cap"], match_cap)
        other_side = "r" if side == "l" else "l"
        self.join_stats["match_redispatches"] += 1
        self.join_stats["probe_fetches"] += 1
        sjl = dev.get("sjl")
        if sjl is not None:
            self._sharded_dispatches += 1
            return np.asarray(sjl.probe_only(
                side, other, buf, np.int32(n), np.int32(self.within),
                cutoff, match_cap))
        kern = lattice.join_probe_only(
            other["code"].shape[0], buf.shape[1], match_cap,
            len(dev["lay"][side]), len(dev["lay"][other_side]))
        return np.asarray(kern(other, buf, np.int32(n),
                               np.int32(self.within), cutoff))

    def _decode_matches(self, side, t0, hbuf, nm
                        ) -> list[dict[str, Any]]:
        """Columnar decode of a fetched match buffer into the inner
        step's input: resolve each needed column from the probe/stored
        side (left precedence for bare names via the present bits) —
        the vectorized twin of _match_cols."""
        from hstream_tpu.engine import lattice
        from hstream_tpu.engine.types import ColumnType

        sjl = self._dev.get("sjl") if self._dev is not None else None
        if sjl is not None:
            total, kid, jts, mflags, oflags, mcols, ocols = \
                sjl.unpack_matches(hbuf, side)
        else:
            total, kid, jts, mflags, oflags, mcols, ocols = \
                lattice.unpack_join_matches(hbuf, nm)
        m = len(kid)
        if m == 0:
            return []
        dev = self._dev
        other_side = "r" if side == "l" else "l"
        lidx = {name: j for j, (name, _c)
                in enumerate(dev["lay"]["l"])}
        ridx = {name: j for j, (name, _c)
                in enumerate(dev["lay"]["r"])}
        phys = {side: (mflags, mcols), other_side: (oflags, ocols)}
        inner = self._inner
        cols: dict[str, np.ndarray] = {}
        nulls: dict[str, np.ndarray] = {}
        for name, (cside, _col) in self._fast["need"].items():
            if cside == "both":
                lf, lv = phys["l"]
                rf, rv = phys["r"]
                lj, rj = lidx[name], ridx[name]
                lpres = ((lf >> (2 * lj + 1)) & 1).astype(np.bool_)
                val = np.where(lpres, lv[lj], rv[rj])
                nb = np.where(lpres, (lf >> (2 * lj)) & 1,
                              (rf >> (2 * rj)) & 1)
            else:
                f, v = phys[cside]
                j = lidx[name] if cside == "l" else ridx[name]
                val = v[j]
                nb = (f >> (2 * j)) & 1
            want = inner.schema.type_of(name)
            if want == ColumnType.FLOAT:
                cols[name] = np.ascontiguousarray(
                    val, np.int32).view(np.float32)
            elif want == ColumnType.BOOL:
                cols[name] = val != 0
            else:
                cols[name] = np.ascontiguousarray(val, np.int32)
            msk = nb.astype(np.bool_)
            if msk.any():
                nulls[name] = msk
        return self._feed_inner_columnar(
            kid.astype(np.int32), jts.astype(np.int64) + t0, cols,
            nulls or None)

    def _maybe_rebase(self, min_ts: int, max_ts: int) -> None:
        """Keep device-relative time inside int32: re-anchor the join
        epoch down when an in-grace batch reaches below it, up when
        stream time approaches the threshold — the rebase rides the
        two-sided eviction kernel (delta arg), so it costs one rare
        dispatch instead of the host store's span abort."""
        dev = self._dev
        # the eviction riding the rebase runs BEFORE this batch's
        # probe, so its cutoff is the PRE-batch watermark's — exactly
        # the prune state the host reference would probe against
        cutoff_abs = ((self.watermark - self.retention_ms)
                      if self.watermark >= 0 else dev["t0"])
        if min_ts - dev["t0"] < 0:
            delta = (min_ts - self.retention_ms) - dev["t0"]
        elif max_ts - dev["t0"] >= self.REBASE_REL_MS:
            delta = max(cutoff_abs - dev["t0"], 0)
        else:
            return
        if max_ts - (dev["t0"] + delta) >= (1 << 31):
            # the span guard must fire even when retention pins the
            # epoch (delta == 0) — silently wrapping int32 relative
            # time would corrupt probe bounds
            raise SQLCodegenError(
                "join record timestamps span more than the int32 "
                "relative range even after epoch rebase; timestamps "
                "must be epoch milliseconds")
        if delta == 0:
            return
        self._dispatch_evict(cutoff_abs, delta)
        self.join_stats["rebase_dispatches"] += 1

    def _maybe_evict(self, cutoff_abs: int) -> None:
        """Watermark-advance eviction policy: dispatch the two-sided
        compaction once retention has advanced a full span past the
        last one AND the stores hold enough dead weight to be worth a
        sort (capacity pressure dispatches it unconditionally in
        _device_batch)."""
        dev = self._dev
        if cutoff_abs - dev["evict_cutoff"] < max(self.retention_ms, 1):
            return
        if dev["n"]["l"] + dev["n"]["r"] < dev["cap"] // 2:
            # mostly-empty stores: skip the sort, just note progress
            dev["evict_cutoff"] = cutoff_abs
            return
        self._dispatch_evict(cutoff_abs, 0)

    # contract: dispatches<=1 fetches<=0
    def _dispatch_evict(self, cutoff_abs: int, delta: int) -> None:
        """One vmapped two-sided eviction (+ rebase) dispatch. The live
        counts stay a DEVICE value (dev["pending_n"]) so the hot loop
        never blocks on them; host-side dev["n"] remains a safe upper
        bound (eviction only shrinks) and _refresh_counts() forces the
        tiny fetch only when a capacity decision needs exact numbers."""
        from hstream_tpu.engine import lattice

        dev = self._dev
        cutoff_rel = max(cutoff_abs - dev["t0"], 0)
        sjl = dev.get("sjl")
        if sjl is not None:
            left, right, narr = sjl.evict(
                dev["stores"]["l"], dev["stores"]["r"],
                np.int32(min(cutoff_rel, (1 << 31) - 1)),
                np.int32(delta))
            self._sharded_dispatches += 1
        else:
            kern = lattice.join_evict(dev["cap"], len(dev["lay"]["l"]),
                                      len(dev["lay"]["r"]))
            left, right, narr = kern(
                dev["stores"]["l"], dev["stores"]["r"],
                np.int32(min(cutoff_rel, (1 << 31) - 1)),
                np.int32(delta))
        dev["stores"]["l"] = left
        dev["stores"]["r"] = right
        # the deferred count snapshot reflects the store AT THIS
        # dispatch; inserts queued after it must be re-added when the
        # snapshot is finally read (_refresh_counts), or the capacity
        # upper bound would silently undercount and let the insert
        # kernel truncate live entries
        dev["pending_n"] = (narr, {"l": 0, "r": 0})
        dev["t0"] += delta
        dev["evict_cutoff"] = max(dev["evict_cutoff"], cutoff_abs)
        self.join_stats["evict_dispatches"] += 1

    def _note_insert(self, side: str, n: int) -> None:
        """Count an insert against the host bound AND any in-flight
        eviction snapshot."""
        dev = self._dev
        dev["n"][side] += n
        pend = dev.get("pending_n")
        if pend is not None:
            pend[1][side] += n

    # contract: dispatches<=0 fetches<=1
    def _refresh_counts(self) -> None:
        """Force the deferred post-eviction live counts (2-int fetch),
        re-adding inserts dispatched after the eviction."""
        dev = self._dev
        pend = dev.pop("pending_n", None)
        if pend is not None:
            narr, since = pend
            n = np.asarray(narr)
            if n.ndim == 2:       # sharded evict: per-shard [ns, 2]
                n = n.sum(axis=0)
            dev["n"] = {"l": int(n[0]) + since["l"],
                        "r": int(n[1]) + since["r"]}

    def _grow_device(self, new_cap: int) -> None:
        """Double a full store pair: pad every plane with empty slots
        (code sentinel) on device — rare, host-driven."""
        import jax.numpy as jnp

        from hstream_tpu.engine import lattice

        dev = self._dev
        extra = new_cap - dev["cap"]
        sjl = dev.get("sjl")
        for s in ("l", "r"):
            st = dev["stores"][s]
            if sjl is not None:
                # per-shard slot axis is axis 1 (leading axis is the
                # shard); re-put to keep the key-axis sharding
                dev["stores"][s] = sjl.put_store({
                    "code": jnp.pad(
                        st["code"], ((0, 0), (0, extra)),
                        constant_values=lattice.JOIN_SENT_CODE),
                    "ts": jnp.pad(st["ts"], ((0, 0), (0, extra))),
                    "flags": jnp.pad(st["flags"], ((0, 0), (0, extra))),
                    "cols": jnp.pad(st["cols"],
                                    ((0, 0), (0, 0), (0, extra))),
                })
            else:
                dev["stores"][s] = {
                    "code": jnp.pad(
                        st["code"], (0, extra),
                        constant_values=lattice.JOIN_SENT_CODE),
                    "ts": jnp.pad(st["ts"], (0, extra)),
                    "flags": jnp.pad(st["flags"], (0, extra)),
                    "cols": jnp.pad(st["cols"], ((0, 0), (0, extra))),
                }
        dev["cap"] = new_cap
        if sjl is not None:
            sjl.cap = new_cap
        self.join_stats["store_grows"] += 1

    def _remap_device_codes(self, new_of_old: np.ndarray) -> None:
        """Apply a code-space compaction to the device stores: live
        codes keep their sorted order under compaction, so a gather
        through the remap LUT suffices (no re-sort). Sentinel slots map
        to themselves."""
        import jax.numpy as jnp

        from hstream_tpu.engine import lattice

        lut = jnp.asarray(new_of_old.astype(np.int32))
        for s in ("l", "r"):
            st = self._dev["stores"][s]
            code = st["code"]
            live = code < np.int32(len(new_of_old))
            st["code"] = jnp.where(
                live, lut[jnp.where(live, code, 0)],
                lattice.JOIN_SENT_CODE)

    def device_store_counts(self) -> dict[str, int] | None:
        """Live entries per device store side (tests/introspection)."""
        if self._dev is None:
            return None
        self._refresh_counts()
        return dict(self._dev["n"])

    def _host_store_view(self) -> dict[str, "_FlatIntervalStore"]:
        """The two side stores as host _FlatIntervalStores (snapshot
        serialization, equivalence tests). Device mode fetches the
        stores and reconstructs per-entry rows from the packed needed
        columns — the only fields future matches can emit on the fast
        path, so the view is faithful for every downstream consumer."""
        if self._dev is None:
            return self._stores
        import jax

        from hstream_tpu.engine.types import ColumnType

        self._refresh_counts()
        out: dict[str, _FlatIntervalStore] = {}
        inner = self._inner
        # the device store evicts lazily (capacity only) and hides
        # expired entries from probes via the cutoff mask; the view
        # applies the same retention filter so it matches the host
        # reference's eagerly-pruned stores exactly
        cutoff = (self.watermark - self.retention_ms
                  if self.watermark >= 0 else None)
        for side in ("l", "r"):
            st = _FlatIntervalStore(self._jcode_rev)
            n = self._dev["n"][side]
            if n:
                # snapshot serialization, off the hot loop; the sides
                # differ in column layout so their fetches cannot stack.
                # analyze: ok dispatch-sync — rare, host-driven
                arrs = {k: np.asarray(v) for k, v in jax.device_get(
                    self._dev["stores"][side]).items()}
                if self._dev.get("sjl") is not None:
                    # flatten the per-shard planes into one globally
                    # (code, ts)-sorted sequence: live entries are each
                    # shard's non-sentinel slots, but shards interleave
                    # in global code order
                    from hstream_tpu.engine import lattice as _lat

                    shard, slot = np.nonzero(
                        arrs["code"] < _lat.JOIN_SENT_CODE)
                    fcols = arrs["cols"].transpose(1, 0, 2)[
                        :, shard, slot]
                    fcode = arrs["code"][shard, slot]
                    fts = arrs["ts"][shard, slot]
                    order = np.lexsort((fts, fcode))
                    arrs = {
                        "code": fcode[order],
                        "ts": fts[order],
                        "flags": arrs["flags"][shard, slot][order],
                        "cols": fcols[:, order],
                    }
                    n = len(order)
                if cutoff is not None:
                    keep = (arrs["ts"][:n].astype(np.int64)
                            + self._dev["t0"]) >= cutoff
                    arrs = {
                        "code": arrs["code"][:n][keep],
                        "ts": arrs["ts"][:n][keep],
                        "flags": arrs["flags"][:n][keep],
                        "cols": arrs["cols"][:, :n][:, keep],
                    }
                    n = int(keep.sum())
                if n == 0:
                    out[side] = st
                    continue
                lay = self._dev["lay"][side]
                decoded: list[tuple[str, list]] = []
                flags = arrs["flags"][:n]
                for j, (name, col) in enumerate(lay):
                    want = inner.schema.type_of(name)
                    raw = arrs["cols"][j, :n]
                    nullm = ((flags >> (2 * j)) & 1).astype(np.bool_)
                    presm = ((flags >> (2 * j + 1)) & 1).astype(
                        np.bool_)
                    if want == ColumnType.FLOAT:
                        vv = np.ascontiguousarray(raw).view(np.float32)
                        py = [float(x) for x in vv]
                    elif want == ColumnType.BOOL:
                        py = [bool(x) for x in raw]
                    elif want == ColumnType.STRING:
                        dec = inner.dicts[name].decode
                        py = [dec(int(x)) if not nl else None
                              for x, nl in zip(raw, nullm)]
                    else:
                        py = [int(x) for x in raw]
                    decoded.append((col, [
                        (_MISS if not p else (None if nl else v))
                        for v, nl, p in zip(py, nullm, presm)]))
                rows = np.empty(n, object)
                for i in range(n):
                    row = {}
                    for col, vals in decoded:
                        if vals[i] is not _MISS:
                            row[col] = vals[i]
                    rows[i] = row
                st.insert_sorted(
                    arrs["code"][:n].astype(np.int64),
                    arrs["ts"][:n].astype(np.int64) + self._dev["t0"],
                    rows)
            out[side] = st
        return out

