"""Stream-stream interval JOIN execution.

Reference semantics (hstream-processing Stream.hs:222-300 /
joinStreamProcessor): each record is inserted into its side's
timestamped KV store, then probed against the other side's store over
[ts - within, ts + within]; matching pairs (equal join key) emit a
joined record whose fields are the union of both sides qualified by
stream name (genJoiner, Internal/Codegen.hs:62-67) and whose timestamp
is max(ts1, ts2). The joined stream feeds the rest of the plan
(filter -> window aggregate -> ...), exactly like the reference's
merged-stream task DAG (Codegen.hs:253-266).

Design: the join itself is host-side two-sided state (per-key sorted
ts lists — the same per-record KV walk the reference does), while the
downstream aggregation still runs as the jitted device lattice. Join
state is pruned by within + downstream grace, bounding memory where the
reference's in-memory store grows forever.
"""

from __future__ import annotations

from dataclasses import replace
from typing import Any, Mapping, Sequence

from hstream_tpu.common.errors import SQLCodegenError
from hstream_tpu.engine.expr import BinOp, Col, Expr, eval_host
from hstream_tpu.engine.plan import AggregateNode
from hstream_tpu.engine.statestore import LastValueStore, TimestampedKVStore
from hstream_tpu.engine.types import canon_key
from hstream_tpu.engine.window import DEFAULT_GRACE_MS


def split_on_condition(on: Expr, left_streams: set[str],
                       right_streams: set[str]) -> tuple[list[Expr],
                                                         list[Expr]]:
    """Decompose `ON a.k1 = b.k2 [AND ...]` into per-side key-selector
    expression lists (evaluated over each side's RAW rows, so
    qualification is stripped). The reference's key selectors are
    functions of one side's record (Stream.hs:224-230)."""
    eqs: list[tuple[Expr, Expr]] = []

    def walk(e: Expr) -> None:
        if isinstance(e, BinOp) and e.op == "AND":
            walk(e.left)
            walk(e.right)
        elif isinstance(e, BinOp) and e.op == "=":
            eqs.append((e.left, e.right))
        else:
            raise SQLCodegenError(
                "JOIN ON must be a conjunction of equality comparisons")

    walk(on)

    def side_of(e: Expr) -> str:
        streams = set()

        def scan(x: Expr) -> None:
            if isinstance(x, Col):
                streams.add(x.stream)
            elif isinstance(x, BinOp):
                scan(x.left)
                scan(x.right)
            elif hasattr(x, "operand"):
                scan(x.operand)

        scan(e)
        named = {s for s in streams if s is not None}
        if named <= left_streams and named:
            return "l"
        if named <= right_streams and named:
            return "r"
        if not named:
            raise SQLCodegenError(
                "JOIN ON columns must be stream-qualified (s.col)")
        raise SQLCodegenError(
            f"JOIN ON side mixes streams {sorted(named)}")

    def strip(e: Expr) -> Expr:
        if isinstance(e, Col):
            return Col(e.name)
        if isinstance(e, BinOp):
            return BinOp(e.op, strip(e.left), strip(e.right))
        if hasattr(e, "operand"):
            return type(e)(e.op, strip(e.operand))
        return e

    lks: list[Expr] = []
    rks: list[Expr] = []
    for a, b in eqs:
        sa, sb = side_of(a), side_of(b)
        if sa == sb:
            raise SQLCodegenError("JOIN ON equality must relate both sides")
        if sa == "l":
            lks.append(strip(a))
            rks.append(strip(b))
        else:
            lks.append(strip(b))
            rks.append(strip(a))
    return lks, rks


# the interval join's side stores ARE the reference's TimestampedKVStore
# shape; one shared implementation lives in engine.statestore
_SideStore = TimestampedKVStore


class _JoinBase:
    """Shared plumbing of both join executors: alias/side routing, ON
    key split, joined-row construction, and the inner (downstream)
    executor lifecycle."""

    def __init__(self, plan, *, initial_keys: int = 1024,
                 batch_capacity: int = 4096):
        join = plan.join
        self.plan = plan
        self.left_name = plan.source
        self.right_name = join.right.name
        if self.right_name == self.left_name:
            raise SQLCodegenError("self-join needs distinct streams")
        self.join_type = join.join_type
        if self.join_type not in ("INNER", "JOIN"):
            raise SQLCodegenError(
                f"{self.join_type} JOIN not supported (INNER only, like "
                "the reference's RJoinInner path)")
        self._aliases = {self.left_name: "l", self.right_name: "r"}
        left_al = {self.left_name}
        right_al = {self.right_name}
        la = getattr(plan, "source_alias", None)
        if la:
            self._aliases[la] = "l"
            left_al.add(la)
        if join.right.alias:
            self._aliases[join.right.alias] = "r"
            right_al.add(join.right.alias)
        self.left_keys, self.right_keys = split_on_condition(
            join.on, left_al, right_al)
        self._inner = None
        self._inner_plan = replace(plan, join=None)
        self._initial_keys = initial_keys
        self._batch_capacity = batch_capacity

    def _side_of(self, stream: str | None) -> str:
        if stream is None:
            raise SQLCodegenError(
                f"{type(self).__name__}.process requires stream=<name or "
                "alias>: a join consumes two streams and must know each "
                "batch's origin")
        side = self._aliases.get(stream)
        if side is None:
            raise SQLCodegenError(
                f"stream {stream!r} is not part of this join")
        return side

    def _joined_row(self, lrow: Mapping[str, Any],
                    rrow: Mapping[str, Any]) -> dict[str, Any]:
        """Union of both sides, stream-qualified (genJoiner); bare names
        kept as a convenience with left precedence."""
        out = {}
        for f, v in lrow.items():
            out[f"{self.left_name}.{f}"] = v
        for f, v in rrow.items():
            out[f"{self.right_name}.{f}"] = v
        for f, v in rrow.items():
            out.setdefault(f, v)
        for f, v in lrow.items():
            out[f] = v
        return out

    def _key(self, exprs: list[Expr], row: Mapping[str, Any]):
        try:
            vals = tuple(eval_host(e, row) for e in exprs)
        except (TypeError, KeyError):
            return None
        if any(v is None for v in vals):
            return None
        return canon_key(vals)

    def _inner_process(self, joined, jts):
        if self._inner is None:
            from hstream_tpu.sql.codegen import make_executor

            self._inner = make_executor(
                self._inner_plan, sample_rows=joined,
                initial_keys=self._initial_keys,
                batch_capacity=self._batch_capacity)
        return self._inner.process(joined, jts)

    # ---- drains (API parity with QueryExecutor) ----------------------------

    def peek(self) -> list[dict[str, Any]]:
        return [] if self._inner is None else self._inner.peek()

    def close_due_windows(self) -> list[dict[str, Any]]:
        if self._inner is None or not hasattr(self._inner,
                                              "close_due_windows"):
            return []
        return self._inner.close_due_windows()

    def block_until_ready(self) -> None:
        if self._inner is not None and hasattr(self._inner,
                                               "block_until_ready"):
            self._inner.block_until_ready()


class TableJoinExecutor(_JoinBase):
    """Executes `SELECT ... FROM l INNER JOIN TABLE(r) ON ...`.

    Reference semantics (Stream.hs:302-344, joinStreamTable): the right
    side is a TABLE — the latest row per join key of a changelog stream.
    Stream records probe the table and emit one joined row when the key
    is present; table records only update state (no retroactive
    emission). State is bounded by the table's key cardinality.
    """

    def __init__(self, plan, *, initial_keys: int = 1024,
                 batch_capacity: int = 4096):
        super().__init__(plan, initial_keys=initial_keys,
                         batch_capacity=batch_capacity)
        # the keyed last-value table (engine.statestore.LastValueStore)
        self._table = LastValueStore()

    @property
    def table(self) -> dict:
        """key -> (ts, row) view of the last-value table (snapshots,
        introspection)."""
        return self._table.data

    def process(self, rows: Sequence[Mapping[str, Any]],
                ts_ms: Sequence[int], stream: str | None = None
                ) -> list[dict[str, Any]]:
        side = self._side_of(stream)
        if side == "r":
            for row, ts in zip(rows, ts_ms):
                key = self._key(self.right_keys, row)
                if key is None:
                    continue
                self._table.update(key, int(ts), row)
            return []
        joined: list[dict[str, Any]] = []
        jts: list[int] = []
        for row, ts in zip(rows, ts_ms):
            key = self._key(self.left_keys, row)
            if key is None:
                continue
            match = self._table.lookup(key)
            if match is None:
                continue  # INNER: stream rows without a table row drop
            joined.append(self._joined_row(row, match))
            jts.append(int(ts))
        if not joined:
            return []
        return self._inner_process(joined, jts)


class JoinExecutor(_JoinBase):
    """Executes `SELECT ... FROM l [INNER|LEFT] JOIN r WITHIN(...) ON ...`.

    API: process(rows, ts_ms, stream=<source name or alias>) — the task
    runtime feeds records from BOTH streams through the one executor,
    tagging each batch with its origin (the reference merges both
    sources into one task, Codegen.hs:250-266). Joined rows feed the
    inner (aggregate/stateless) executor built over the joined schema.
    """

    def __init__(self, plan, *, initial_keys: int = 1024,
                 batch_capacity: int = 4096):
        super().__init__(plan, initial_keys=initial_keys,
                         batch_capacity=batch_capacity)
        join = plan.join
        self.within = join.within.ms

        # retention: a future in-grace record can probe back `within`;
        # grace defaults to the downstream window's (or the SQL default)
        node = plan.node
        grace = DEFAULT_GRACE_MS
        if isinstance(node, AggregateNode) and node.window is not None:
            grace = node.window.grace_ms
        self.retention_ms = self.within + grace

        self._stores = {"l": _SideStore(), "r": _SideStore()}
        self.watermark: int = -1

    # ---- ingest ------------------------------------------------------------

    def process(self, rows: Sequence[Mapping[str, Any]],
                ts_ms: Sequence[int], stream: str | None = None
                ) -> list[dict[str, Any]]:
        side = self._side_of(stream)
        mine = self._stores[side]
        other = self._stores["r" if side == "l" else "l"]
        my_keys = self.left_keys if side == "l" else self.right_keys
        joined: list[dict[str, Any]] = []
        jts: list[int] = []
        for row, ts in zip(rows, ts_ms):
            ts = int(ts)
            key = self._key(my_keys, row)
            if key is None:
                continue
            mine.put(key, ts, dict(row))
            for ots, orow in other.range(key, ts - self.within,
                                         ts + self.within):
                if side == "l":
                    jrow = self._joined_row(row, orow)
                else:
                    jrow = self._joined_row(orow, row)
                joined.append(jrow)
                jts.append(max(ts, ots))
        new_wm = max((int(t) for t in ts_ms), default=self.watermark)
        if new_wm > self.watermark:
            self.watermark = new_wm
            cutoff = self.watermark - self.retention_ms
            if cutoff > 0:
                mine.prune(cutoff)
                other.prune(cutoff)
        if not joined:
            return []
        return self._inner_process(joined, jts)

