"""Stream-stream interval JOIN execution.

Reference semantics (hstream-processing Stream.hs:222-300 /
joinStreamProcessor): each record is inserted into its side's
timestamped KV store, then probed against the other side's store over
[ts - within, ts + within]; matching pairs (equal join key) emit a
joined record whose fields are the union of both sides qualified by
stream name (genJoiner, Internal/Codegen.hs:62-67) and whose timestamp
is max(ts1, ts2). The joined stream feeds the rest of the plan
(filter -> window aggregate -> ...), exactly like the reference's
merged-stream task DAG (Codegen.hs:253-266).

Design: the join itself is host-side two-sided state (per-key sorted
ts lists — the same per-record KV walk the reference does), while the
downstream aggregation still runs as the jitted device lattice. Join
state is pruned by within + downstream grace, bounding memory where the
reference's in-memory store grows forever.
"""

from __future__ import annotations

from dataclasses import replace
from typing import Any, Mapping, Sequence

import numpy as np

from hstream_tpu.common.errors import SQLCodegenError
from hstream_tpu.engine.expr import BinOp, Col, Expr, eval_host
from hstream_tpu.engine.plan import AggregateNode
from hstream_tpu.engine.statestore import LastValueStore
from hstream_tpu.engine.types import canon_key
from hstream_tpu.engine.window import DEFAULT_GRACE_MS


def split_on_condition(on: Expr, left_streams: set[str],
                       right_streams: set[str]) -> tuple[list[Expr],
                                                         list[Expr]]:
    """Decompose `ON a.k1 = b.k2 [AND ...]` into per-side key-selector
    expression lists (evaluated over each side's RAW rows, so
    qualification is stripped). The reference's key selectors are
    functions of one side's record (Stream.hs:224-230)."""
    eqs: list[tuple[Expr, Expr]] = []

    def walk(e: Expr) -> None:
        if isinstance(e, BinOp) and e.op == "AND":
            walk(e.left)
            walk(e.right)
        elif isinstance(e, BinOp) and e.op == "=":
            eqs.append((e.left, e.right))
        else:
            raise SQLCodegenError(
                "JOIN ON must be a conjunction of equality comparisons")

    walk(on)

    def side_of(e: Expr) -> str:
        streams = set()

        def scan(x: Expr) -> None:
            if isinstance(x, Col):
                streams.add(x.stream)
            elif isinstance(x, BinOp):
                scan(x.left)
                scan(x.right)
            elif hasattr(x, "operand"):
                scan(x.operand)

        scan(e)
        named = {s for s in streams if s is not None}
        if named <= left_streams and named:
            return "l"
        if named <= right_streams and named:
            return "r"
        if not named:
            raise SQLCodegenError(
                "JOIN ON columns must be stream-qualified (s.col)")
        raise SQLCodegenError(
            f"JOIN ON side mixes streams {sorted(named)}")

    def strip(e: Expr) -> Expr:
        if isinstance(e, Col):
            return Col(e.name)
        if isinstance(e, BinOp):
            return BinOp(e.op, strip(e.left), strip(e.right))
        if hasattr(e, "operand"):
            return type(e)(e.op, strip(e.operand))
        return e

    lks: list[Expr] = []
    rks: list[Expr] = []
    for a, b in eqs:
        sa, sb = side_of(a), side_of(b)
        if sa == sb:
            raise SQLCodegenError("JOIN ON equality must relate both sides")
        if sa == "l":
            lks.append(strip(a))
            rks.append(strip(b))
        else:
            lks.append(strip(b))
            rks.append(strip(a))
    return lks, rks


class _JoinBase:
    """Shared plumbing of both join executors: alias/side routing, ON
    key split, joined-row construction, and the inner (downstream)
    executor lifecycle."""

    def __init__(self, plan, *, initial_keys: int = 1024,
                 batch_capacity: int = 4096):
        join = plan.join
        self.plan = plan
        self.left_name = plan.source
        self.right_name = join.right.name
        if self.right_name == self.left_name:
            raise SQLCodegenError("self-join needs distinct streams")
        self.join_type = join.join_type
        if self.join_type not in ("INNER", "JOIN"):
            raise SQLCodegenError(
                f"{self.join_type} JOIN not supported (INNER only, like "
                "the reference's RJoinInner path)")
        self._aliases = {self.left_name: "l", self.right_name: "r"}
        left_al = {self.left_name}
        right_al = {self.right_name}
        la = getattr(plan, "source_alias", None)
        if la:
            self._aliases[la] = "l"
            left_al.add(la)
        if join.right.alias:
            self._aliases[join.right.alias] = "r"
            right_al.add(join.right.alias)
        self.left_keys, self.right_keys = split_on_condition(
            join.on, left_al, right_al)
        self._inner = None
        self._inner_plan = replace(plan, join=None)
        self._initial_keys = initial_keys
        self._batch_capacity = batch_capacity
        # deferred-change tuning proxied onto the (lazily created) inner
        # executor, so the server's _tune_executor and bench harnesses
        # treat a join exactly like a plain aggregate: the downstream
        # changelog extraction pipelines/batches instead of serializing
        # the join's compute loop with one D2H fetch per micro-batch
        self.emit_changes = bool(getattr(plan, "emit_changes", False))
        self.supports_deferred_changes = True
        self._inner_tuning: dict[str, object] = {}

    def _side_of(self, stream: str | None) -> str:
        if stream is None:
            raise SQLCodegenError(
                f"{type(self).__name__}.process requires stream=<name or "
                "alias>: a join consumes two streams and must know each "
                "batch's origin")
        side = self._aliases.get(stream)
        if side is None:
            raise SQLCodegenError(
                f"stream {stream!r} is not part of this join")
        return side

    def _joined_row(self, lrow: Mapping[str, Any],
                    rrow: Mapping[str, Any]) -> dict[str, Any]:
        """Union of both sides, stream-qualified (genJoiner); bare names
        kept as a convenience with left precedence."""
        out = {}
        for f, v in lrow.items():
            out[f"{self.left_name}.{f}"] = v
        for f, v in rrow.items():
            out[f"{self.right_name}.{f}"] = v
        for f, v in rrow.items():
            out.setdefault(f, v)
        for f, v in lrow.items():
            out[f] = v
        return out

    def _key(self, exprs: list[Expr], row: Mapping[str, Any]):
        try:
            vals = tuple(eval_host(e, row) for e in exprs)
        except (TypeError, KeyError):
            return None
        if any(v is None for v in vals):
            return None
        return canon_key(vals)

    def _inner_process(self, joined, jts):
        if self._inner is None:
            from hstream_tpu.sql.codegen import make_executor

            self._inner = make_executor(
                self._inner_plan, sample_rows=joined,
                initial_keys=self._initial_keys,
                batch_capacity=self._batch_capacity)
            self._apply_inner_tuning()
        return self._inner.process(joined, jts)

    def _apply_inner_tuning(self) -> None:
        inner = self._inner
        if inner is None or not getattr(inner, "supports_deferred_changes",
                                        False):
            return
        for k, v in self._inner_tuning.items():
            setattr(inner, k, v)

    def _proxy_tuning(self, name: str, value) -> None:
        self._inner_tuning[name] = value
        self._apply_inner_tuning()

    # change-drain knobs ride through to the inner executor (set before
    # OR after its lazy creation); reads fall back to the pending value
    @property
    def defer_change_decode(self) -> bool:
        return bool(self._inner_tuning.get("defer_change_decode", False))

    @defer_change_decode.setter
    def defer_change_decode(self, v: bool) -> None:
        self._proxy_tuning("defer_change_decode", bool(v))

    @property
    def change_drain_depth(self) -> int:
        return int(self._inner_tuning.get("change_drain_depth", 1))

    @change_drain_depth.setter
    def change_drain_depth(self, v: int) -> None:
        self._proxy_tuning("change_drain_depth", int(v))

    @property
    def async_change_drain(self) -> bool:
        return bool(self._inner_tuning.get("async_change_drain", False))

    @async_change_drain.setter
    def async_change_drain(self, v: bool) -> None:
        self._proxy_tuning("async_change_drain", bool(v))

    # ---- drains (API parity with QueryExecutor) ----------------------------

    def flush_changes(self) -> list[dict[str, Any]]:
        """Deliver every lagging emission: coalesced match rows staged
        for the inner step first, then the inner executor's deferred
        changelog extracts — the same barrier QueryExecutor exposes."""
        rows = (self.flush_staged()
                if hasattr(self, "flush_staged") else [])
        inner = self._inner
        if inner is not None and hasattr(inner, "flush_changes"):
            rows.extend(inner.flush_changes())
        return rows

    def has_pending_changes(self) -> bool:
        if getattr(self, "_staged_n", 0):
            return True
        inner = self._inner
        if inner is None:
            return False
        hp = getattr(inner, "has_pending_changes", None)
        if hp is not None:
            return bool(hp())
        return bool(getattr(inner, "_pending_changes", None))

    def peek(self) -> list[dict[str, Any]]:
        return [] if self._inner is None else self._inner.peek()

    def close_due_windows(self) -> list[dict[str, Any]]:
        if self._inner is None or not hasattr(self._inner,
                                              "close_due_windows"):
            return []
        return self._inner.close_due_windows()

    def block_until_ready(self) -> None:
        if self._inner is not None and hasattr(self._inner,
                                               "block_until_ready"):
            self._inner.block_until_ready()


class TableJoinExecutor(_JoinBase):
    """Executes `SELECT ... FROM l INNER JOIN TABLE(r) ON ...`.

    Reference semantics (Stream.hs:302-344, joinStreamTable): the right
    side is a TABLE — the latest row per join key of a changelog stream.
    Stream records probe the table and emit one joined row when the key
    is present; table records only update state (no retroactive
    emission). State is bounded by the table's key cardinality.
    """

    def __init__(self, plan, *, initial_keys: int = 1024,
                 batch_capacity: int = 4096):
        super().__init__(plan, initial_keys=initial_keys,
                         batch_capacity=batch_capacity)
        # the keyed last-value table (engine.statestore.LastValueStore)
        self._table = LastValueStore()

    @property
    def table(self) -> dict:
        """key -> (ts, row) view of the last-value table (snapshots,
        introspection)."""
        return self._table.data

    def process(self, rows: Sequence[Mapping[str, Any]],
                ts_ms: Sequence[int], stream: str | None = None
                ) -> list[dict[str, Any]]:
        side = self._side_of(stream)
        if side == "r":
            for row, ts in zip(rows, ts_ms):
                key = self._key(self.right_keys, row)
                if key is None:
                    continue
                self._table.update(key, int(ts), row)
            return []
        joined: list[dict[str, Any]] = []
        jts: list[int] = []
        for row, ts in zip(rows, ts_ms):
            key = self._key(self.left_keys, row)
            if key is None:
                continue
            match = self._table.lookup(key)
            if match is None:
                continue  # INNER: stream rows without a table row drop
            joined.append(self._joined_row(row, match))
            jts.append(int(ts))
        if not joined:
            return []
        return self._inner_process(joined, jts)


class _FlatIntervalStore:
    """One side of the interval join as flat sorted arrays.

    Rows live in arrays sorted by a composite (key code, ts) int64 —
    code * 2^41 + (ts - t0) — so a WHOLE batch probes with one
    searchsorted pair and inserts with one np.insert: no per-key Python.
    The reference walks a per-record ordered map instead
    (Processing/Store.hs tksPut/tksRange); this is that store's batch
    restatement. Key codes are dense ints owned by the executor
    (shared across both sides so probes and inserts agree).
    """

    TS_BITS = 41                     # ~69 years of ms offsets
    SPAN = 1 << TS_BITS

    def __init__(self, key_rev: list):
        self.code = np.empty(0, np.int64)
        self.ts = np.empty(0, np.int64)
        self.comp = np.empty(0, np.int64)
        self.rows = np.empty(0, object)
        self.t0: int | None = None
        self.key_rev = key_rev       # shared code -> canon key (executor)

    def __len__(self) -> int:
        return len(self.code)

    def _rebase(self, t0: int) -> None:
        self.t0 = t0
        self.comp = self.code * self.SPAN + (self.ts - t0)

    def insert_sorted(self, code: np.ndarray, ts: np.ndarray,
                      rows: np.ndarray) -> None:
        """Insert a batch already sorted by (code, ts)."""
        if len(code) == 0:
            return
        mn = int(ts.min())
        new_t0 = mn if self.t0 is None else min(mn, self.t0)
        hi = int(ts.max())
        if len(self.ts):
            hi = max(hi, int(self.ts.max()))
        if hi - new_t0 >= self.SPAN:
            # an offset past 2^41 ms (~69 years) would overflow into a
            # neighboring code's composite range and silently corrupt
            # probes — loud failure beats wrong join results. Checked
            # over existing AND incoming rows: a rebase to an older t0
            # shifts every resident row's offset too.
            raise SQLCodegenError(
                "join record timestamps span more than 2^41 ms; "
                "timestamps must be epoch milliseconds")
        if self.t0 is None or new_t0 < self.t0:
            self._rebase(new_t0)
        bcomp = code * self.SPAN + (ts - self.t0)
        if len(self.comp) == 0:
            self.code, self.ts, self.comp = code, ts, bcomp
            self.rows = rows
            return
        idx = np.searchsorted(self.comp, bcomp)
        self.code = np.insert(self.code, idx, code)
        self.ts = np.insert(self.ts, idx, ts)
        self.comp = np.insert(self.comp, idx, bcomp)
        self.rows = np.insert(self.rows, idx, rows)

    def probe(self, code: np.ndarray, lo_ts: np.ndarray,
              hi_ts: np.ndarray) -> tuple[np.ndarray, np.ndarray] | None:
        """Per query i: [start, end) indices of rows with this code and
        lo_ts[i] <= ts <= hi_ts[i]."""
        if len(self.comp) == 0:
            return None
        lo = np.clip(lo_ts - self.t0, 0, self.SPAN - 1)
        hi = np.clip(hi_ts - self.t0, -1, self.SPAN - 1)
        lo_i = np.searchsorted(self.comp, code * self.SPAN + lo, "left")
        hi_i = np.searchsorted(self.comp, code * self.SPAN + hi, "right")
        return lo_i, np.maximum(hi_i, lo_i)

    def prune(self, min_ts: int) -> None:
        keep = self.ts >= min_ts
        if not keep.all():
            self.code = self.code[keep]
            self.ts = self.ts[keep]
            self.comp = self.comp[keep]
            self.rows = self.rows[keep]

    def remap_codes(self, new_of_old: np.ndarray) -> None:
        """Apply a code compaction (sorted-order-preserving)."""
        self.code = new_of_old[self.code]
        if self.t0 is not None:
            self.comp = self.code * self.SPAN + (self.ts - self.t0)

    @property
    def by_key(self) -> dict:
        """key tuple -> (ts list, rows list) view (snapshots; same shape
        TimestampedKVStore exposes, so the blob format is unchanged)."""
        out: dict[tuple, tuple[list, list]] = {}
        for i in range(len(self.code)):
            key = self.key_rev[int(self.code[i])]
            tss, rows = out.setdefault(key, ([], []))
            tss.append(int(self.ts[i]))
            rows.append(self.rows[i])
        return out


class JoinExecutor(_JoinBase):
    """Executes `SELECT ... FROM l [INNER|LEFT] JOIN r WITHIN(...) ON ...`.

    API: process(rows, ts_ms, stream=<source name or alias>) — the task
    runtime feeds records from BOTH streams through the one executor,
    tagging each batch with its origin (the reference merges both
    sources into one task, Codegen.hs:250-266). Joined rows feed the
    inner (aggregate/stateless) executor built over the joined schema.
    """

    def __init__(self, plan, *, initial_keys: int = 1024,
                 batch_capacity: int = 4096):
        super().__init__(plan, initial_keys=initial_keys,
                         batch_capacity=batch_capacity)
        join = plan.join
        self.within = join.within.ms

        # retention: a future in-grace record can probe back `within`;
        # grace defaults to the downstream window's (or the SQL default)
        node = plan.node
        grace = DEFAULT_GRACE_MS
        if isinstance(node, AggregateNode) and node.window is not None:
            grace = node.window.grace_ms
        self.retention_ms = self.within + grace

        # shared join-key code space across both sides
        self._jcode: dict[tuple, int] = {}
        self._jcode_rev: list[tuple] = []
        self._kid_lut = np.full(1024, -1, np.int32)  # code -> inner key id
        self._stores = {"l": _FlatIntervalStore(self._jcode_rev),
                        "r": _FlatIntervalStore(self._jcode_rev)}
        self.watermark: int = -1
        # fast-path plumbing (computed lazily once the inner executor
        # and both sides' observed fields exist)
        self._fields = {"l": set(), "r": set()}
        self._fast: dict | None = None   # None = unknown yet
        # opt-in: accumulate this many matched rows before stepping the
        # inner executor — on a real link every step dispatch pays a
        # round trip, so small probe batches must coalesce (the same
        # lever as the ingest pipeline's staged caps). Emission then
        # lags by the coalesce horizon; callers flush via flush_staged.
        self.coalesce_rows = 0
        self._staged: list[tuple] = []   # (key_ids, jts, cols, nulls)
        self._staged_n = 0

    # ---- ingest ------------------------------------------------------------
    #
    # Batched: the per-record reference loop (insert my side, probe the
    # other side over [ts-within, ts+within], Stream.hs:238-300) is
    # restated as: group the batch by join key, batch-append each group
    # to my side's store, then probe the other side with ONE
    # searchsorted pair per group (the other side never changes during
    # the batch, so insert/probe need no interleaving). Matched pairs
    # feed the inner aggregate COLUMNAR (key ids broadcast per group
    # when the GROUP BY key is the join key) — no joined-row dicts on
    # the steady path.

    def process(self, rows: Sequence[Mapping[str, Any]],
                ts_ms: Sequence[int], stream: str | None = None
                ) -> list[dict[str, Any]]:
        side = self._side_of(stream)
        mine = self._stores[side]
        other = self._stores["r" if side == "l" else "l"]
        my_keys = self.left_keys if side == "l" else self.right_keys
        n = len(rows)
        out: list[dict[str, Any]] = []
        if n:
            if rows[0]:
                self._fields[side].update(rows[0])
            ts = np.asarray(ts_ms, np.int64)
            codes = self._batch_codes(my_keys, rows)       # -1 = no key
            keep = codes >= 0
            if not keep.all():
                kidx = np.nonzero(keep)[0]
                codes = codes[kidx]
                bts = ts[kidx]
                brows = np.asarray([dict(rows[i]) for i in kidx.tolist()],
                                   object)
            else:
                bts = ts
                brows = np.empty(n, object)
                for i, r in enumerate(rows):
                    brows[i] = dict(r)
            if len(codes):
                order = np.lexsort((bts, codes))
                codes = codes[order]
                bts = bts[order]
                brows = brows[order]
                # probe the other side BEFORE inserting: the reference
                # loop probes only the opposite store, which this batch
                # never mutates, so insert/probe need no interleaving
                pr = other.probe(codes, bts - self.within,
                                 bts + self.within)
                mine.insert_sorted(codes, bts, brows)
                if pr is not None:
                    lo_i, hi_i = pr
                    cnt = hi_i - lo_i
                    tot = int(cnt.sum())
                    if tot:
                        start = np.cumsum(cnt) - cnt
                        oidx = (np.arange(tot, dtype=np.int64)
                                - np.repeat(start, cnt)
                                + np.repeat(lo_i, cnt))
                        rep = np.repeat(np.arange(len(codes)), cnt)
                        jts = np.maximum(bts[rep], other.ts[oidx])
                        out = self._emit_matches(
                            side, brows, rep, codes[rep], other, oidx,
                            jts)
        new_wm = max((int(t) for t in ts_ms), default=self.watermark)
        if new_wm > self.watermark:
            self.watermark = new_wm
            cutoff = self.watermark - self.retention_ms
            if cutoff > 0:
                mine.prune(cutoff)
                other.prune(cutoff)
        return out

    def _batch_codes(self, my_keys, rows) -> np.ndarray:
        """Dense join-key code per row (-1 = null key, skipped). One
        shared code space for both sides; compacted when it outgrows
        the composite-key budget."""
        # compact BEFORE encoding so this batch's fresh keys get live
        # codes (compacting afterwards would remap them to -1 and drop
        # the rows)
        if len(self._jcode_rev) + len(rows) >= (1 << 22) - 1:
            self._compact_codes()
            if len(self._jcode_rev) + len(rows) >= (1 << 22) - 1:
                raise SQLCodegenError(
                    "join key cardinality within the retention window "
                    f"exceeds {1 << 22} distinct keys")
        jcode = self._jcode
        rev = self._jcode_rev
        out = np.empty(len(rows), np.int64)

        def code_of(k) -> int:
            c = jcode.get(k)
            if c is None:
                c = len(rev)
                jcode[k] = c
                rev.append(k)
            return c

        if all(isinstance(e, Col) for e in my_keys):
            names = [e.name for e in my_keys]
            if len(names) == 1:
                nm = names[0]
                for i, r in enumerate(rows):
                    v = r.get(nm)
                    out[i] = -1 if v is None else code_of(canon_key((v,)))
            else:
                for i, r in enumerate(rows):
                    vals = tuple(r.get(c) for c in names)
                    out[i] = (-1 if any(v is None for v in vals)
                              else code_of(canon_key(vals)))
        else:
            for i, r in enumerate(rows):
                k = self._key(my_keys, r)
                out[i] = -1 if k is None else code_of(k)
        return out

    def _compact_codes(self) -> None:
        """Code-space compaction: keep only codes still live in either
        store (retention bounds them), reassign dense codes in sorted
        order (store order is preserved), remap stores + lut + dict."""
        live = np.union1d(self._stores["l"].code, self._stores["r"].code)
        new_of_old = np.full(len(self._jcode_rev), -1, np.int64)
        new_of_old[live] = np.arange(len(live))
        for st in self._stores.values():
            st.remap_codes(new_of_old)
        new_rev = [self._jcode_rev[int(c)] for c in live.tolist()]
        self._jcode.clear()
        self._jcode.update({k: i for i, k in enumerate(new_rev)})
        self._jcode_rev[:] = new_rev      # in place: stores share it
        lut = np.full(max(len(new_rev), 1024), -1, np.int32)
        old_lut = self._kid_lut
        for new_c, old_c in enumerate(live.tolist()):
            if old_c < len(old_lut):
                lut[new_c] = old_lut[old_c]
        self._kid_lut = lut

    # ---- match emission ----------------------------------------------------

    def _emit_matches(self, side, brows, rep, mcodes, other, oidx,
                      jts) -> list[dict[str, Any]]:
        fast = self._fast_info()
        if fast is not None:
            key_ids = self._match_key_ids(mcodes)
            cols, nulls = self._match_cols(fast, side, brows, rep,
                                           other, oidx)
            if self.coalesce_rows > 0:
                self._staged.append((key_ids, jts, cols, nulls))
                self._staged_n += len(key_ids)
                if self._staged_n < self.coalesce_rows:
                    return []
                return self._drain_staged(keep_tail=True)
            return self._inner.process_columnar(key_ids, jts, cols,
                                                nulls)
        # general path: materialize joined-row dicts (also the sample
        # source for the inner executor's construction)
        orows = other.rows[oidx]
        joined: list[dict[str, Any]] = []
        for i in range(len(rep)):
            row, orow = brows[rep[i]], orows[i]
            joined.append(self._joined_row(row, orow) if side == "l"
                          else self._joined_row(orow, row))
        res = self._inner_process(joined, jts.tolist())
        # re-plan while disabled: a field observed on a later batch can
        # make a previously-unresolvable column resolvable
        if not self._fast:
            self._plan_fast()
        return res

    def _match_key_ids(self, mcodes: np.ndarray) -> np.ndarray:
        """Inner-executor key ids per match via a code-indexed LUT (the
        GROUP BY key IS the join key on this path)."""
        lut = self._kid_lut
        if len(lut) < len(self._jcode_rev):
            grown = np.full(max(len(self._jcode_rev), 2 * len(lut)),
                            -1, np.int32)
            grown[:len(lut)] = lut
            self._kid_lut = lut = grown
        need = np.unique(mcodes[lut[mcodes] < 0])
        for c in need.tolist():
            lut[c] = self._inner.key_id_for(self._jcode_rev[c])
        return lut[mcodes]

    def flush_staged(self) -> list[dict[str, Any]]:
        """Step the inner executor with every coalesced match row."""
        return self._drain_staged(keep_tail=False)

    def _drain_staged(self, *, keep_tail: bool) -> list[dict[str, Any]]:
        """Step coalesced matches. keep_tail=True steps only whole
        inner-batch-capacity chunks and re-stages the remainder, so the
        steady state reuses ONE compiled step shape (each distinct
        padded cap is a separate XLA compile)."""
        if not self._staged:
            return []
        staged, self._staged = self._staged, []
        self._staged_n = 0
        key_ids = np.concatenate([s[0] for s in staged])
        jts = np.concatenate([s[1] for s in staged])
        names = staged[0][2].keys()
        cols = {c: np.concatenate([s[2][c] for s in staged])
                for c in names}
        nulls = None
        if any(s[3] for s in staged):
            nulls = {}
            for c in names:
                parts = [s[3][c] if (s[3] and c in s[3])
                         else np.zeros(len(s[0]), np.bool_)
                         for s in staged]
                m = np.concatenate(parts)
                if m.any():
                    nulls[c] = m
            nulls = nulls or None
        n = len(key_ids)
        cap = self._inner.batch_capacity
        cut = n - (n % cap) if keep_tail else n
        if keep_tail and cut < n:
            tail_nulls = (None if nulls is None else
                          {c: m[cut:] for c, m in nulls.items()})
            self._staged.append((key_ids[cut:], jts[cut:],
                                 {c: v[cut:] for c, v in cols.items()},
                                 tail_nulls))
            self._staged_n = n - cut
        if cut == 0:
            return []
        head_nulls = (None if nulls is None else
                      {c: m[:cut] for c, m in nulls.items()})
        return self._inner.process_columnar(
            key_ids[:cut], jts[:cut],
            {c: v[:cut] for c, v in cols.items()}, head_nulls)

    def _fast_info(self) -> dict | None:
        if self._fast is None and self._inner is not None:
            self._plan_fast()
        return self._fast if isinstance(self._fast, dict) else None

    def _resolve_col(self, name: str) -> tuple[str, str] | None:
        """Joined-row column name -> (side, source column): qualified
        names split on the alias; bare names take left precedence, the
        same rule _joined_row applies."""
        if "." in name:
            pre, col = name.split(".", 1)
            s = self._aliases.get(pre)
            if s is not None:
                return s, col
        if name in self._fields["l"]:
            return "l", name
        if name in self._fields["r"]:
            return "r", name
        return None

    def close_due_windows(self) -> list[dict[str, Any]]:
        rows = self.flush_staged() if self._staged else []
        rows.extend(super().close_due_windows())
        return rows

    def _plan_fast(self) -> None:
        """Enable the columnar match path when (a) the inner executor
        has one, (b) its GROUP BY columns are exactly the join key (so
        inner key ids broadcast per probe group), and (c) every column
        the inner step needs resolves to one side."""
        inner = self._inner
        self._fast = False
        if inner is None or not hasattr(inner, "process_columnar"):
            return
        # after a snapshot restore the observed-field sets are empty;
        # reseed them from any stored row so bare names still resolve
        for s in ("l", "r"):
            if not self._fields[s] and len(self._stores[s]):
                self._fields[s].update(self._stores[s].rows[0])
        knames_l = ([e.name for e in self.left_keys]
                    if all(isinstance(e, Col) for e in self.left_keys)
                    else None)
        knames_r = ([e.name for e in self.right_keys]
                    if all(isinstance(e, Col) for e in self.right_keys)
                    else None)
        resolved = [self._resolve_col(c) for c in inner.group_cols]
        if any(r is None for r in resolved):
            return
        gs = [s for s, _ in resolved]
        gcols = [c for _, c in resolved]
        if not (len(set(gs)) == 1
                and ((gs[0] == "l" and gcols == knames_l)
                     or (gs[0] == "r" and gcols == knames_r))):
            return
        need = {}
        for name in inner._needed_cols:
            if "." in name:
                pre, col = name.split(".", 1)
                s = self._aliases.get(pre)
                if s is not None:
                    need[name] = (s, col)
                    continue
            if (name in self._fields["l"]
                    or name in self._fields["r"]):
                # bare name: gather per match row with _joined_row's
                # left-precedence (observation can't tell which side a
                # heterogeneous stream carries the field on)
                need[name] = ("both", name)
            else:
                return
        self._fast = {"need": need}

    def _match_cols(self, fast, side, brows, rep, other,
                    oidx) -> tuple[dict, dict | None]:
        """Columns the inner step needs, gathered straight from the
        matched source rows (no joined dicts)."""
        from hstream_tpu.engine.types import ColumnType

        inner = self._inner
        tot = len(rep)
        cols: dict[str, np.ndarray] = {}
        nulls: dict[str, np.ndarray] = {}
        src_cache: dict[tuple, list] = {}
        _MISS = object()
        for name, (cside, col) in fast["need"].items():
            vals = src_cache.get((cside, col))
            if vals is None:
                if cside == "both":
                    # left-precedence bare name, decided per match row
                    lrows, lidx = ((brows, rep) if side == "l"
                                   else (other.rows, oidx))
                    rrows, ridx = ((other.rows, oidx) if side == "l"
                                   else (brows, rep))
                    vals = []
                    for li, ri in zip(lidx.tolist(), ridx.tolist()):
                        v = lrows[li].get(col, _MISS)
                        if v is _MISS:
                            v = rrows[ri].get(col)
                        vals.append(v)
                elif cside == side:
                    vals = [brows[i].get(col) for i in rep.tolist()]
                else:
                    vals = [other.rows[j].get(col)
                            for j in oidx.tolist()]
                src_cache[(cside, col)] = vals
            want = inner.schema.type_of(name)
            msk = np.zeros(tot, np.bool_)
            if want == ColumnType.STRING:
                enc = inner.dicts[name].encode
                arr = np.empty(tot, np.int32)
                for i, v in enumerate(vals):
                    if v is None:
                        arr[i] = -1
                        msk[i] = True
                    else:
                        arr[i] = enc(str(v))
            else:
                dt = (np.bool_ if want == ColumnType.BOOL
                      else np.int32 if want == ColumnType.INT
                      else np.float32)
                arr = np.zeros(tot, dt)
                for i, v in enumerate(vals):
                    if v is None or not isinstance(v, (int, float, bool)):
                        msk[i] = True
                    else:
                        arr[i] = v
            cols[name] = arr
            if msk.any():
                nulls[name] = msk
        return cols, (nulls or None)

