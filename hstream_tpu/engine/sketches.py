"""Streaming sketches as pure JAX ops over the state lattice.

Both sketches are commutative monoids, which is what makes the whole
engine data-parallel: per-chip partial sketches merge with an elementwise
max / add collective at window close.

* HyperLogLog (APPROX_COUNT_DISTINCT): registers int8 [..., m], m = 2^p.
  Update = scatter-max of the leading-zero rank of a 32-bit hash; estimate
  uses the standard bias-corrected harmonic mean with the linear-counting
  small-range correction.
* Log-binned histogram (APPROX_QUANTILE, DDSketch-flavored): int32 counts
  over geometric value buckets; quantiles read off the bucket CDF with a
  known relative error set by the bucket growth factor gamma.

The reference declares these capabilities at the SQL surface (AST.hs
aggregates; BASELINE configs 3-4) — there they would run per record on the
CPU; here they are batched scatter ops that XLA fuses into the same kernel
pass as the other accumulators.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

# ---- 32-bit hashing (device) ----------------------------------------------

_U32 = jnp.uint32


def _mix32(h):
    """murmur3 finalizer: a fast avalanche over uint32."""
    h = h.astype(_U32)
    h = h ^ (h >> 16)
    h = h * _U32(0x85EBCA6B)
    h = h ^ (h >> 13)
    h = h * _U32(0xC2B2AE35)
    h = h ^ (h >> 16)
    return h


def hash_u32(values: jnp.ndarray) -> jnp.ndarray:
    """Hash a float32/int32/bool column to uint32."""
    if values.dtype == jnp.float32:
        # canonicalize -0.0 == 0.0 before bitcasting
        values = jnp.where(values == 0.0, 0.0, values)
        bits = jax.lax.bitcast_convert_type(values, jnp.uint32)
    else:
        bits = values.astype(jnp.int32).astype(_U32)
    return _mix32(bits)


def clz32(x: jnp.ndarray) -> jnp.ndarray:
    """Count leading zeros of uint32, branch-free."""
    x = x.astype(_U32)
    n = jnp.zeros(x.shape, dtype=jnp.int32)
    for shift in (16, 8, 4, 2, 1):
        hi_empty = (x >> (32 - shift)) == 0  # top `shift` bits all zero
        n = n + jnp.where(hi_empty, shift, 0)
        x = jnp.where(hi_empty, x << shift, x)
    return jnp.where(x == 0, 32, n)


# ---- HyperLogLog -----------------------------------------------------------

@dataclass(frozen=True)
class HLLConfig:
    precision: int = 10  # m = 1024 registers, ~3.2% standard error

    @property
    def m(self) -> int:
        return 1 << self.precision


def hll_update_indices(values: jnp.ndarray, cfg: HLLConfig):
    """Per-record (register index, rank) for scatter-max into registers."""
    h = hash_u32(values)
    p = cfg.precision
    reg = (h >> (32 - p)).astype(jnp.int32)
    w = (h << p).astype(_U32)  # remaining 32-p bits, left-aligned
    rank = jnp.minimum(clz32(w) + 1, 32 - p + 1).astype(jnp.int8)
    return reg, rank


def _alpha(m: int) -> float:
    if m == 16:
        return 0.673
    if m == 32:
        return 0.697
    if m == 64:
        return 0.709
    return 0.7213 / (1 + 1.079 / m)


def hll_estimate(registers: jnp.ndarray, cfg: HLLConfig) -> jnp.ndarray:
    """Estimate cardinality from int8 registers [..., m] -> float32 [...]."""
    m = cfg.m
    regs = registers.astype(jnp.float32)
    raw = _alpha(m) * m * m / jnp.sum(jnp.exp2(-regs), axis=-1)
    zeros = jnp.sum(registers == 0, axis=-1).astype(jnp.float32)
    linear = m * jnp.log(m / jnp.maximum(zeros, 1.0))
    use_linear = (raw <= 2.5 * m) & (zeros > 0)
    return jnp.where(use_linear, linear, raw)


def hll_merge(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return jnp.maximum(a, b)


# ---- log-binned quantile histogram ----------------------------------------

@dataclass(frozen=True)
class QuantileConfig:
    """Geometric buckets over [min_value, max_value]; values below
    min_value (incl. zero/negatives) land in bucket 0."""

    n_bins: int = 512
    min_value: float = 1e-6
    max_value: float = 1e9

    @property
    def gamma_log(self) -> float:
        return math.log(self.max_value / self.min_value) / (self.n_bins - 1)


def quantile_bin(values: jnp.ndarray, cfg: QuantileConfig) -> jnp.ndarray:
    """Bucket index int32 [...] for float values."""
    v = jnp.maximum(values.astype(jnp.float32), 0.0)
    safe = jnp.maximum(v, cfg.min_value)
    b = jnp.floor(jnp.log(safe / cfg.min_value) / cfg.gamma_log).astype(jnp.int32) + 1
    b = jnp.clip(b, 1, cfg.n_bins - 1)
    return jnp.where(v < cfg.min_value, 0, b)


def quantile_estimate(hist: jnp.ndarray, q: float,
                      cfg: QuantileConfig) -> jnp.ndarray:
    """q-quantile from histogram counts [..., n_bins] -> float32 [...].

    Returns each bucket's geometric midpoint; relative error is bounded by
    the bucket width."""
    counts = hist.astype(jnp.float32)
    total = jnp.sum(counts, axis=-1, keepdims=True)
    cdf = jnp.cumsum(counts, axis=-1)
    target = q * jnp.maximum(total, 1.0)
    # first bucket whose cdf >= target
    idx = jnp.sum((cdf < target).astype(jnp.int32), axis=-1)
    idx = jnp.clip(idx, 0, cfg.n_bins - 1)
    # geometric midpoint of bucket idx (bucket 0 -> ~0)
    log_lo = (idx.astype(jnp.float32) - 1.0) * cfg.gamma_log
    mid = cfg.min_value * jnp.exp(log_lo + 0.5 * cfg.gamma_log)
    return jnp.where(idx == 0, 0.0, mid)


def np_quantile_reference(values: "np.ndarray", q: float) -> float:
    """Exact quantile for tests."""
    return float(np.quantile(np.asarray(values, dtype=np.float64), q))
