"""Scalar expressions: one AST, two evaluators.

The reference interprets scalar expressions over Aeson JSON values per
record (hstream-sql Internal/Codegen.hs:76-250, op enums AST.hs:87-105).
Here the same AST is evaluated two ways:

  * `compile_device(expr, ...)` -> a traced jnp function over columnar
    batches, used for WHERE masks and aggregate inputs **inside the jitted
    step** (numeric/boolean ops + dictionary-encoded string equality);
  * `eval_host(expr, row)` -> Python-value interpreter with the full scalar
    op set (strings, arrays, ifnull...), used for HAVING and SELECT
    projections over emitted aggregate rows, which are tiny compared to
    the ingest stream.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Callable, Mapping

import jax.numpy as jnp

from hstream_tpu.common.errors import SQLCodegenError
from hstream_tpu.engine.types import ColumnType, Schema, StringDictionary


# ---- AST -------------------------------------------------------------------

class Expr:
    pass


@dataclass(frozen=True)
class Col(Expr):
    name: str
    stream: str | None = None  # qualified `stream.field` references


@dataclass(frozen=True)
class Lit(Expr):
    value: Any  # int | float | str | bool | None


@dataclass(frozen=True)
class BinOp(Expr):
    op: str  # + - * / % = <> < <= > >= AND OR
    left: Expr
    right: Expr


@dataclass(frozen=True)
class UnOp(Expr):
    op: str  # NOT NEG SIN COS ... STRLEN TO_UPPER ...
    operand: Expr


def columns_of(e: Expr) -> set[str]:
    if isinstance(e, Col):
        return {e.name}
    if isinstance(e, BinOp):
        return columns_of(e.left) | columns_of(e.right)
    if isinstance(e, UnOp):
        return columns_of(e.operand)
    return set()


# ---- device compilation ----------------------------------------------------

_NUM_UNARY = {
    "NEG": lambda x: -x,
    "ABS": jnp.abs,
    "CEIL": lambda x: jnp.ceil(x),
    "FLOOR": lambda x: jnp.floor(x),
    "ROUND": lambda x: jnp.round(x),
    "SQRT": jnp.sqrt,
    "SIGN": jnp.sign,
    "SIN": jnp.sin, "COS": jnp.cos, "TAN": jnp.tan,
    "ASIN": jnp.arcsin, "ACOS": jnp.arccos, "ATAN": jnp.arctan,
    "SINH": jnp.sinh, "COSH": jnp.cosh, "TANH": jnp.tanh,
    "ASINH": jnp.arcsinh, "ACOSH": jnp.arccosh, "ATANH": jnp.arctanh,
    "LOG": jnp.log, "LOG2": jnp.log2, "LOG10": jnp.log10, "EXP": jnp.exp,
}

_CMP_OPS = {"=", "<>", "<", "<=", ">", ">="}


def _is_string_expr(e: Expr, schema: Schema) -> bool:
    if isinstance(e, Col):
        return schema.has(e.name) and schema.type_of(e.name) == ColumnType.STRING
    if isinstance(e, Lit):
        return isinstance(e.value, str)
    return False


def encode_strings(expr: Expr, schema: Schema,
                   dicts: Mapping[str, StringDictionary]) -> Expr:
    """Rewrite string-vs-column comparisons into dictionary-id comparisons.

    Encoding the literal inserts it into the column's dictionary, so later
    record values of the same string map to the same id. The resulting
    expression is fully hashable and dictionary-free, which lets compiled
    step functions be shared across executors (lru_cache in lattice.py)."""
    if isinstance(expr, BinOp):
        if expr.op in ("=", "<>") and (_is_string_expr(expr.left, schema)
                                       or _is_string_expr(expr.right, schema)):
            col_e, lit_e = ((expr.left, expr.right)
                            if isinstance(expr.right, Lit)
                            else (expr.right, expr.left))
            if not isinstance(col_e, Col) or not isinstance(lit_e, Lit):
                raise SQLCodegenError(
                    "device string comparison must be column vs literal")
            lit_id = dicts[col_e.name].encode(str(lit_e.value))
            return BinOp(expr.op, col_e, Lit(lit_id))
        return BinOp(expr.op, encode_strings(expr.left, schema, dicts),
                     encode_strings(expr.right, schema, dicts))
    if isinstance(expr, UnOp):
        return UnOp(expr.op, encode_strings(expr.operand, schema, dicts))
    return expr


def compile_device(
    expr: Expr,
    schema: Schema,
) -> Callable[[Mapping[str, jnp.ndarray]], jnp.ndarray]:
    """Build cols->array function. String literals must be pre-encoded via
    encode_strings; raises SQLCodegenError for host-only ops."""

    def build(e: Expr):
        if isinstance(e, Col):
            name = e.name
            if not schema.has(name):
                raise SQLCodegenError(f"unknown column {name}")
            return lambda cols: cols[name]
        if isinstance(e, Lit):
            v = e.value
            if isinstance(v, str):
                raise SQLCodegenError(
                    "string literal not pre-encoded (see encode_strings)")
            if v is None:
                raise SQLCodegenError("NULL literal unsupported on device")
            if isinstance(v, bool):
                return lambda cols: jnp.asarray(v)
            return lambda cols: jnp.asarray(v, dtype=jnp.float32
                                            if isinstance(v, float) else jnp.int32)
        if isinstance(e, BinOp):
            op = e.op
            lf, rf = build(e.left), build(e.right)
            if op == "+":
                return lambda cols: lf(cols) + rf(cols)
            if op == "-":
                return lambda cols: lf(cols) - rf(cols)
            if op == "*":
                return lambda cols: lf(cols) * rf(cols)
            if op == "/":
                return lambda cols: lf(cols) / rf(cols)
            if op == "%":
                return lambda cols: jnp.mod(lf(cols), rf(cols))
            if op == "=":
                return lambda cols: lf(cols) == rf(cols)
            if op == "<>":
                return lambda cols: lf(cols) != rf(cols)
            if op == "<":
                return lambda cols: lf(cols) < rf(cols)
            if op == "<=":
                return lambda cols: lf(cols) <= rf(cols)
            if op == ">":
                return lambda cols: lf(cols) > rf(cols)
            if op == ">=":
                return lambda cols: lf(cols) >= rf(cols)
            if op == "AND":
                return lambda cols: lf(cols) & rf(cols)
            if op == "OR":
                return lambda cols: lf(cols) | rf(cols)
            raise SQLCodegenError(f"unsupported device op {op}")
        if isinstance(e, UnOp):
            if e.op == "NOT":
                f = build(e.operand)
                return lambda cols: ~f(cols)
            fn = _NUM_UNARY.get(e.op)
            if fn is None:
                raise SQLCodegenError(f"op {e.op} is host-only")
            f = build(e.operand)
            return lambda cols: fn(f(cols))
        raise SQLCodegenError(f"unknown expr {e!r}")

    return build(expr)


# ---- host interpreter ------------------------------------------------------

_HOST_UNARY: dict[str, Callable[[Any], Any]] = {
    "NEG": lambda x: -x,
    "NOT": lambda x: not x,
    "ABS": abs,
    "CEIL": lambda x: math.ceil(x),
    "FLOOR": lambda x: math.floor(x),
    "ROUND": lambda x: round(x),
    "SQRT": math.sqrt,
    "SIGN": lambda x: (x > 0) - (x < 0),
    "SIN": math.sin, "COS": math.cos, "TAN": math.tan,
    "ASIN": math.asin, "ACOS": math.acos, "ATAN": math.atan,
    "SINH": math.sinh, "COSH": math.cosh, "TANH": math.tanh,
    "ASINH": math.asinh, "ACOSH": math.acosh, "ATANH": math.atanh,
    "LOG": math.log, "LOG2": math.log2, "LOG10": math.log10, "EXP": math.exp,
    "IS_INT": lambda x: isinstance(x, int) and not isinstance(x, bool),
    "IS_FLOAT": lambda x: isinstance(x, float),
    "IS_NUM": lambda x: isinstance(x, (int, float)) and not isinstance(x, bool),
    "IS_BOOL": lambda x: isinstance(x, bool),
    "IS_STR": lambda x: isinstance(x, str),
    "IS_ARRAY": lambda x: isinstance(x, list),
    "TO_STR": str,
    "TO_UPPER": lambda x: str(x).upper(),
    "TO_LOWER": lambda x: str(x).lower(),
    "TRIM": lambda x: str(x).strip(),
    "LTRIM": lambda x: str(x).lstrip(),
    "RTRIM": lambda x: str(x).rstrip(),
    "REVERSE": lambda x: x[::-1],
    "STRLEN": len,
    "ARR_DISTINCT": lambda x: list(dict.fromkeys(x)),
    "ARR_LENGTH": len,
    "ARR_MAX": max,
    "ARR_MIN": min,
    "ARR_SORT": sorted,
    "ARR_SUM": sum,
    "IFNULL_CHECK": lambda x: x,  # placeholder; IFNULL handled as BinOp
}


def eval_host_vec(expr: Expr, cols: Mapping[str, Any]) -> Any:
    """Columnwise twin of eval_host over numpy arrays: evaluates HAVING
    and SELECT projections for a whole emitted batch in one pass instead
    of one interpreter walk per row (the window-close and changelog
    emission paths).

    The numeric/boolean/comparison core and the numeric unaries map to
    native numpy ufuncs; every remaining scalar op from the host
    interpreter — string builtins, type predicates, array ops, IFNULL —
    evaluates through a frompyfunc broadcast of the SAME host function,
    so joined projections over string/array columns stay columnar with
    semantics identical to the per-row interpreter. Only NULL literals
    (and genuinely unknown ops) still raise SQLCodegenError for the
    per-row fallback."""
    import numpy as np

    if isinstance(expr, Col):
        key = f"{expr.stream}.{expr.name}" if expr.stream else expr.name
        if key in cols:
            return cols[key]
        v = cols.get(expr.name)
        if v is None:
            raise SQLCodegenError(f"column {expr.name} not columnar")
        return v
    if isinstance(expr, Lit):
        if expr.value is None:
            raise SQLCodegenError("NULL literal: per-row fallback")
        return expr.value
    if isinstance(expr, BinOp):
        op = expr.op
        if op == "IFNULL":
            l = eval_host_vec(expr.left, cols)
            r = eval_host_vec(expr.right, cols)
            if np.ndim(l) == 0:
                return r if l is None else l
            la = np.asarray(l)
            if la.dtype != object:
                return la  # typed arrays cannot hold SQL NULLs
            mask = np.frompyfunc(lambda x: x is None, 1, 1)(
                la).astype(bool)
            if not mask.any():
                return la
            return np.where(mask, r, la)
        l = eval_host_vec(expr.left, cols)
        r = eval_host_vec(expr.right, cols)
        if op == "AND":
            return np.logical_and(l, r)
        if op == "OR":
            return np.logical_or(l, r)
        if op == "+":
            return l + r
        if op == "-":
            return l - r
        if op == "*":
            return l * r
        if op == "/":
            return l / r
        if op == "%":
            return l % r
        if op == "=":
            return l == r
        if op == "<>":
            return l != r
        if op == "<":
            return l < r
        if op == "<=":
            return l <= r
        if op == ">":
            return l > r
        if op == ">=":
            return l >= r
        if op == "ARR_CONTAINS":
            return np.frompyfunc(lambda a, b: b in a, 2, 1)(
                l, r).astype(bool)
        if op == "ARR_JOIN":
            return np.frompyfunc(
                lambda a, b: str(b).join(str(x) for x in a), 2, 1)(l, r)
        raise SQLCodegenError(f"op {op}: per-row fallback")
    if isinstance(expr, UnOp):
        op = expr.op
        v = eval_host_vec(expr.operand, cols)
        if op == "NOT":
            return np.logical_not(v)
        if op == "NEG":
            return -np.asarray(v)
        vec = {"ABS": np.abs, "CEIL": np.ceil, "FLOOR": np.floor,
               "ROUND": np.round, "SQRT": np.sqrt, "SIGN": np.sign,
               "SIN": np.sin, "COS": np.cos, "TAN": np.tan,
               "ASIN": np.arcsin, "ACOS": np.arccos, "ATAN": np.arctan,
               "SINH": np.sinh, "COSH": np.cosh, "TANH": np.tanh,
               "ASINH": np.arcsinh, "ACOSH": np.arccosh,
               "ATANH": np.arctanh, "LOG": np.log, "LOG2": np.log2,
               "LOG10": np.log10, "EXP": np.exp}.get(op)
        if vec is not None:
            arr = np.asarray(v)
            if arr.dtype != object:
                return vec(arr)
            # object column (e.g. ints mixed with NULL-bearing rows):
            # broadcast the exact host scalar through frompyfunc
        host_fn = _HOST_UNARY.get(op)
        if host_fn is None:
            raise SQLCodegenError(f"op {op}: per-row fallback")
        if np.ndim(v) == 0:
            return host_fn(v)
        out = np.frompyfunc(host_fn, 1, 1)(np.asarray(v, object))
        if op.startswith("IS_"):
            return out.astype(bool)
        return out
    raise SQLCodegenError(f"unknown expr {expr!r}")


def eval_host(expr: Expr, row: Mapping[str, Any]) -> Any:
    if isinstance(expr, Col):
        key = f"{expr.stream}.{expr.name}" if expr.stream else expr.name
        if key in row:
            return row[key]
        return row.get(expr.name)
    if isinstance(expr, Lit):
        return expr.value
    if isinstance(expr, BinOp):
        op = expr.op
        if op == "AND":
            return bool(eval_host(expr.left, row)) and bool(eval_host(expr.right, row))
        if op == "OR":
            return bool(eval_host(expr.left, row)) or bool(eval_host(expr.right, row))
        if op == "IFNULL":
            v = eval_host(expr.left, row)
            return eval_host(expr.right, row) if v is None else v
        l, r = eval_host(expr.left, row), eval_host(expr.right, row)
        if op == "+":
            return l + r
        if op == "-":
            return l - r
        if op == "*":
            return l * r
        if op == "/":
            return l / r
        if op == "%":
            return l % r
        if op == "=":
            return l == r
        if op == "<>":
            return l != r
        if op == "<":
            return l < r
        if op == "<=":
            return l <= r
        if op == ">":
            return l > r
        if op == ">=":
            return l >= r
        if op == "ARR_CONTAINS":
            return r in l
        if op == "ARR_JOIN":
            return str(r).join(str(x) for x in l)
        raise SQLCodegenError(f"unsupported host op {op}")
    if isinstance(expr, UnOp):
        fn = _HOST_UNARY.get(expr.op)
        if fn is None:
            raise SQLCodegenError(f"unsupported host op {expr.op}")
        return fn(eval_host(expr.operand, row))
    raise SQLCodegenError(f"unknown expr {expr!r}")
