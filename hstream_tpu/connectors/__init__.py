"""Connectors: move records between streams and external systems.

Reference surface (hstream-connector):
  * hstoreSourceConnector / hstoreSinkConnector — records in/out of
    streams via checkpointed readers and appends (HStore.hs:119-163)
  * mysqlSinkConnector / clickHouseSinkConnector — flatten the JSON
    payload and issue `INSERT INTO table (cols) VALUES (...)`
    (MySQL.hs:38-48, ClickHouse.hs:36-48)

The source side of hstore is the query-task reader loop
(server/tasks.py); this module provides the SINK side plus the managed
connector task. The relational sink contract (flatten -> INSERT) is
implemented over DB-API so sqlite (stdlib, used in tests) and MySQL /
ClickHouse (optional drivers) share one code path.
"""

from __future__ import annotations

import threading
import traceback
from typing import Any, Mapping

from hstream_tpu.common import columnar
from hstream_tpu.common import records as rec
from hstream_tpu.common.errors import ServerError
from hstream_tpu.common.logger import get_logger
from hstream_tpu.common.records import flatten_json
from hstream_tpu.server.persistence import TaskStatus
from hstream_tpu.store.api import LSN_MIN, DataBatch
from hstream_tpu.store.checkpoint import CheckpointedReader
from hstream_tpu.store.streams import StreamType

log = get_logger("connectors")


class SinkConnector:
    """writeRecord analogue (Connector.hs:24-38)."""

    def write_records(self, rows: list[Mapping[str, Any]]) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class HStoreSinkConnector(SinkConnector):
    """Sink into another stream (HStore.hs:152-163)."""

    def __init__(self, ctx, target_stream: str):
        self.ctx = ctx
        self.logid = ctx.streams.get_logid(target_stream,
                                           StreamType.STREAM)

    def write_records(self, rows: list[Mapping[str, Any]]) -> None:
        payloads = [rec.build_record(dict(r)).SerializeToString()
                    for r in rows]
        self.ctx.store.append_batch(self.logid, payloads)


class DbApiSinkConnector(SinkConnector):
    """Relational sink over a DB-API connection: flatten nested JSON to
    columns and INSERT (the MySQL.hs:38-48 contract)."""

    def __init__(self, conn, table: str, *, paramstyle: str = "qmark"):
        self.conn = conn
        self.table = table
        self.mark = "?" if paramstyle == "qmark" else "%s"
        self._lock = threading.Lock()

    def write_records(self, rows: list[Mapping[str, Any]]) -> None:
        with self._lock:
            cur = self.conn.cursor()
            for row in rows:
                flat = flatten_json(row)
                cols = ", ".join(f'"{c}"' for c in flat)
                marks = ", ".join([self.mark] * len(flat))
                cur.execute(
                    f'INSERT INTO {self.table} ({cols}) VALUES ({marks})',
                    tuple(flat.values()))
            self.conn.commit()

    def close(self) -> None:
        self.conn.close()


def sqlite_sink(path: str, table: str) -> DbApiSinkConnector:
    import sqlite3

    conn = sqlite3.connect(path, check_same_thread=False)
    return DbApiSinkConnector(conn, table, paramstyle="qmark")


def mysql_sink(options: Mapping[str, Any]) -> DbApiSinkConnector:
    try:
        import pymysql  # type: ignore[import-not-found]
    except ImportError as e:
        raise ServerError(
            "MySQL sink requires the pymysql driver, which is not "
            "installed in this environment") from e
    conn = pymysql.connect(
        host=options.get("HOST", "127.0.0.1"),
        port=int(options.get("PORT", 3306)),
        user=options.get("USER", "root"),
        password=str(options.get("PASSWORD", "")),
        database=options["DATABASE"])
    return DbApiSinkConnector(conn, options["TABLE"], paramstyle="format")


def clickhouse_sink(options: Mapping[str, Any]) -> DbApiSinkConnector:
    try:
        from clickhouse_driver import dbapi  # type: ignore[import-not-found]
    except ImportError as e:
        raise ServerError(
            "ClickHouse sink requires clickhouse-driver, which is not "
            "installed in this environment") from e
    conn = dbapi.connect(
        host=options.get("HOST", "127.0.0.1"),
        port=int(options.get("PORT", 9000)),
        user=options.get("USER", "default"),
        password=str(options.get("PASSWORD", "")),
        database=options.get("DATABASE", "default"))
    return DbApiSinkConnector(conn, options["TABLE"], paramstyle="format")


def make_sink(ctx, options: Mapping[str, Any]) -> SinkConnector:
    """Build a sink from CREATE SINK CONNECTOR ... WITH (...) options."""
    kind = str(options.get("TYPE", "")).lower()
    if kind == "hstore":
        return HStoreSinkConnector(ctx, options["TARGET"])
    if kind == "sqlite":
        return sqlite_sink(options["PATH"], options["TABLE"])
    if kind == "mysql":
        return mysql_sink(options)
    if kind == "clickhouse":
        return clickhouse_sink(options)
    raise ServerError(f"unknown connector type {kind!r} (supported: "
                      "hstore, sqlite, mysql, clickhouse)")


class ConnectorTask(threading.Thread):
    """Managed connector: checkpointed reader on the source stream ->
    sink.write_records (the reference forks these exactly like query
    threads, Handler/Common.hs:195-207)."""

    def __init__(self, ctx, connector_id: str, source_stream: str,
                 sink: SinkConnector):
        super().__init__(name=f"connector-{connector_id}", daemon=True)
        self.ctx = ctx
        self.connector_id = connector_id
        self.source_stream = source_stream
        self.sink = sink
        self.error: BaseException | None = None
        self._stop_ev = threading.Event()
        self.logid = ctx.streams.get_logid(source_stream)

    def stop(self, timeout: float = 10.0) -> None:
        self._stop_ev.set()
        if self.is_alive():
            self.join(timeout)

    def run(self) -> None:
        ctx = self.ctx
        try:
            reader = CheckpointedReader(
                f"connector-{self.connector_id}",
                ctx.store.new_reader(), ctx.ckp_store)
            reader.set_timeout(50)
            reader.start_reading_from_checkpoint(self.logid, LSN_MIN)
            ctx.persistence.set_connector_status(self.connector_id,
                                                 TaskStatus.RUNNING)
            flow = getattr(ctx, "flow", None)
            while not self._stop_ev.is_set():
                if flow is not None and flow.active:
                    # connectors are background work: shed this cycle
                    # under overload (DEFER and above) and give the
                    # host back to user traffic
                    wait = flow.admit_background("connector")
                    if wait > 0.0:
                        if self._stop_ev.wait(min(wait, 1.0)):
                            break
                        continue
                results = reader.read(256)
                if not results:
                    continue
                last = 0
                rows = []
                for r in results:
                    if isinstance(r, DataBatch):
                        for payload in r.payloads:
                            pr = rec.parse_record(payload)
                            if pr.header.flag == rec.pb.RECORD_FLAG_RAW:
                                # columnar producer batches flow to
                                # sinks too (same decode as query tasks)
                                crows = columnar.payload_rows(pr.payload)
                                if crows:
                                    rows.extend(crows)
                                elif columnar.is_columnar(pr.payload):
                                    log.warning(
                                        "connector %s: skipping "
                                        "malformed columnar record",
                                        self.connector_id)
                                continue
                            d = rec.record_to_dict(pr)
                            if d is not None:
                                rows.append(d)
                        last = max(last, r.lsn)
                    else:
                        last = max(last, r.hi_lsn)
                if rows:
                    self.sink.write_records(rows)
                reader.write_checkpoints({self.logid: last})
            ctx.persistence.set_connector_status(self.connector_id,
                                                 TaskStatus.TERMINATED)
        except BaseException as e:  # noqa: BLE001
            self.error = e
            log.error("connector %s died: %s\n%s", self.connector_id, e,
                      traceback.format_exc())
            try:
                ctx.persistence.set_connector_status(
                    self.connector_id, TaskStatus.CONNECTION_ABORT)
            except Exception:
                pass
        finally:
            self.sink.close()
            self.ctx.running_connectors.pop(self.connector_id, None)
