"""ColumnarProducer: the client half of the framed append fast path.

A high-throughput producer should ship the server the exact staging
layout its encode workers consume — one framed columnar block per
micro-batch (``common/colframe.py``) — instead of N protobuf records
the server would parse and re-serialize. Two RPC shapes:

* ``append(ts, cols)`` — one unary ``AppendColumnar`` carrying one (or
  a few) framed blocks; simplest integration, one RPC per call.
* ``append_stream(batches)`` — ONE client-streaming
  ``AppendColumnarStream`` call carrying many micro-batches; the
  server validates/appends each message as it arrives (overlapping
  the next message's receive with the previous append's fsync through
  its append front) and answers once with every block's record id.
  Co-located producers use this to stop paying per-call gRPC overhead.

Usage::

    p = ColumnarProducer("127.0.0.1:6570", "sensors")
    p.append(ts_ms, {"device": devs, "temp": temps})
    p.append_stream((ts, cols) for ...)       # or (ts, cols, nulls)
    p.close()
"""

from __future__ import annotations

from typing import Any, Iterable, Iterator, Mapping

import grpc
import numpy as np

from hstream_tpu.common import colframe, columnar
from hstream_tpu.proto import api_pb2 as pb
from hstream_tpu.proto.rpc import HStreamApiStub

# blocks per streaming request message: enough to amortize message
# overhead, small enough to stay far under the gRPC message cap even
# at megabyte blocks
STREAM_BLOCKS_PER_MSG = 4


def encode_batch(ts_ms, cols: Mapping[str, Any],
                 nulls: Mapping[str, np.ndarray] | None = None,
                 *, float_kind: str = "f32") -> bytes:
    """One framed wire block from numpy columns (+ optional per-column
    null masks) — the exact bytes ``AppendColumnar`` carries."""
    return colframe.encode_frame(
        columnar.encode_columnar(ts_ms, cols, nulls=nulls,
                                 float_kind=float_kind))


class ColumnarProducer:
    """One stream's framed-append producer over one channel."""

    def __init__(self, addr_or_channel, stream: str):
        if isinstance(addr_or_channel, str):
            self.channel = grpc.insecure_channel(addr_or_channel)
            self._owns_channel = True
        else:
            self.channel = addr_or_channel
            self._owns_channel = False
        self.stub = HStreamApiStub(self.channel)
        self.stream = stream

    def close(self) -> None:
        if self._owns_channel:
            self.channel.close()

    # ---- unary -----------------------------------------------------------

    def append(self, ts_ms, cols: Mapping[str, Any],
               nulls: Mapping[str, np.ndarray] | None = None):
        """Encode one micro-batch and append it in one unary RPC.
        Returns the AppendColumnarResponse (record_ids, rows)."""
        return self.append_frames([encode_batch(ts_ms, cols, nulls)])

    def append_frames(self, frames: Iterable[bytes]):
        """Append pre-encoded framed blocks (one store batch each)."""
        return self.stub.AppendColumnar(pb.AppendColumnarRequest(
            stream_name=self.stream, blocks=list(frames)))

    # ---- streaming -------------------------------------------------------

    def append_stream(self, batches: Iterable[tuple]):
        """One AppendColumnarStream call over many micro-batches.
        `batches` yields (ts, cols) or (ts, cols, nulls) tuples; returns
        the aggregate AppendColumnarResponse (one record id per block,
        in submission order)."""
        return self.stub.AppendColumnarStream(
            self._requests(encode_batch(*b) for b in batches))

    def append_stream_frames(self, frames: Iterable[bytes]):
        """Streaming append of pre-encoded framed blocks."""
        return self.stub.AppendColumnarStream(self._requests(frames))

    def _requests(self, frames: Iterable[bytes]
                  ) -> Iterator[pb.AppendColumnarRequest]:
        pending: list[bytes] = []
        for f in frames:
            pending.append(f)
            if len(pending) >= STREAM_BLOCKS_PER_MSG:
                yield pb.AppendColumnarRequest(stream_name=self.stream,
                                               blocks=pending)
                pending = []
        if pending:
            yield pb.AppendColumnarRequest(stream_name=self.stream,
                                           blocks=pending)
