from hstream_tpu.client import main

main()
