"""Interactive SQL REPL client.

Reference: the haskeline REPL in hstream/app/client.hs (216 LoC) —
reads SQL until ';', parses LOCALLY to classify the statement, then
routes: push queries (SELECT ... EMIT CHANGES) stream results over the
server-streaming RPC until Ctrl-C cancels (client.hs:117-132); DDL and
everything else go through dedicated RPCs / ExecuteQuery
(client.hs:91-116). Results render as aligned tables (the reference's
Format.hs table rendering).
"""

from __future__ import annotations

import math
import sys
import uuid
from typing import Any, Iterable

import grpc

from hstream_tpu.client.retry import RetryPolicy
from hstream_tpu.client.producer import ColumnarProducer  # noqa: F401
# re-exported: the framed-append producer (ISSUE 12) lives beside the
# SQL shell so `from hstream_tpu.client import ColumnarProducer` works
from hstream_tpu.common import records as rec
from hstream_tpu.common.logger import REQUEST_ID_KEY
from hstream_tpu.common.errors import SQLError
from hstream_tpu.proto import api_pb2 as pb
from hstream_tpu.proto.rpc import HStreamApiStub
from hstream_tpu.sql import plans
from hstream_tpu.sql.codegen import stream_codegen

BANNER = """hstream-tpu SQL shell — end with ';', Ctrl-C cancels a \
streaming query, \\q quits."""
PROMPT = "hstream> "
CONT = "       > "


def format_table(rows: list[dict[str, Any]]) -> str:
    """Aligned-column rendering (reference Format.hs)."""
    if not rows:
        return "(0 rows)"
    cols: list[str] = []
    for row in rows:
        for k in row:
            if k not in cols:
                cols.append(k)
    cells = [[_show(row.get(c)) for c in cols] for row in rows]
    widths = [max(len(c), *(len(r[i]) for r in cells))
              for i, c in enumerate(cols)]
    sep = "+" + "+".join("-" * (w + 2) for w in widths) + "+"
    out = [sep,
           "|" + "|".join(f" {c:<{w}} " for c, w in zip(cols, widths))
           + "|", sep]
    for r in cells:
        out.append("|" + "|".join(
            f" {v:<{w}} " for v, w in zip(r, widths)) + "|")
    out.append(sep)
    out.append(f"({len(rows)} row{'s' if len(rows) != 1 else ''})")
    return "\n".join(out)


def _show(v: Any) -> str:
    if v is None:
        return "NULL"
    if isinstance(v, float) and math.isfinite(v) and v == int(v):
        return str(int(v))
    return str(v)


class Client:
    """One connected SQL shell session."""

    def __init__(self, addr: str, out=None,
                 retry: RetryPolicy | None = None):
        self.addr = addr
        self.channel = grpc.insecure_channel(addr)
        self.stub = HStreamApiStub(self.channel)
        self.out = out or sys.stdout
        # RESOURCE_EXHAUSTED (quota/overload shed) retries with jittered
        # backoff honoring the server's retry-after hint; a NOT_LEADER
        # refusal (UNAVAILABLE + leader hint after a store failover)
        # rebinds the channel to the hinted leader and retries; every
        # other status surfaces immediately
        self.retry = retry or RetryPolicy()
        # correlation: every statement gets a fresh request id, stamped
        # into the gRPC metadata; kept here so "what id did my last
        # statement run under" is answerable (and testable)
        self.last_request_id: str | None = None

    def close(self) -> None:
        self.channel.close()

    @property
    def retries(self) -> int:
        """Total flow-control retries this session performed."""
        return self.retry.retries

    def _new_request_id(self) -> str:
        self.last_request_id = f"cli-{uuid.uuid4().hex[:12]}"
        return self.last_request_id

    def _metadata(self) -> tuple:
        # trace context (ISSUE 13): the request id doubles as the
        # trace id, and the client hop names itself as the parent span
        # — the server decides sampling from the id, so an unarmed
        # server pays one metadata compare
        from hstream_tpu.common.tracing import (
            PARENT_SPAN_KEY,
            TRACE_ID_KEY,
        )

        rid = self._new_request_id()
        return ((REQUEST_ID_KEY, rid), (TRACE_ID_KEY, rid),
                (PARENT_SPAN_KEY, f"cli-{rid}"))

    def _follow_leader_hint(self, hint: str) -> None:
        """The server lost store leadership: reconnect to the hinted
        new leader so the retry (and every later statement) lands
        there (ISSUE 9 failover-aware clients)."""
        print(f"-- leader moved; following hint to {hint} --",
              file=self.out)
        old = self.channel
        self.addr = hint
        self.channel = grpc.insecure_channel(hint)
        self.stub = HStreamApiStub(self.channel)
        try:
            old.close()
        except Exception:  # noqa: BLE001 — the old channel is dead
            pass           # weight either way

    def _call(self, method: str, request):
        # resolve the RPC by NAME each attempt: a leader-hint follow
        # swaps self.stub, and a bound method would pin the old channel
        def attempt(req, **kw):
            return getattr(self.stub, method)(req, **kw)

        return self.retry.call(attempt, request,
                               metadata=self._metadata(),
                               on_leader_hint=self._follow_leader_hint)

    # ---- statement routing (client.hs:91-132) ---------------------------

    def execute(self, sql: str) -> None:
        try:
            plan = stream_codegen(sql)  # local parse first
        except SQLError as e:
            print(f"parse error: {e}", file=self.out)
            return
        try:
            if isinstance(plan, plans.SelectPlan) and plan.emit_changes:
                self._push_query(sql)
            elif isinstance(plan, plans.CreateViewPlan):
                v = self._call("CreateView",
                               pb.CreateViewRequest(sql=sql))
                print(f"view {v.view_id} created", file=self.out)
            elif isinstance(plan, plans.CreateSinkConnectorPlan):
                c = self._call("CreateSinkConnector",
                               pb.CreateSinkConnectorRequest(config=sql))
                print(f"connector {c.id} created", file=self.out)
            elif isinstance(plan, plans.CreatePlan):
                self._call("CreateStream", pb.Stream(
                    stream_name=plan.stream, replication_factor=1))
                print(f"stream {plan.stream} created", file=self.out)
            elif isinstance(plan, plans.TerminatePlan):
                req = (pb.TerminateQueriesRequest(all=True)
                       if plan.query_id is None else
                       pb.TerminateQueriesRequest(
                           query_ids=[plan.query_id]))
                done = self._call("TerminateQueries", req)
                print(f"terminated: {list(done.query_ids)}",
                      file=self.out)
            else:
                resp = self._call("ExecuteQuery",
                                  pb.CommandQuery(stmt_text=sql))
                rows = [rec.struct_to_dict(s) for s in resp.result_set]
                print(format_table(rows), file=self.out)
        except grpc.RpcError as e:
            print(f"server error: {e.details()}", file=self.out)

    def _push_query(self, sql: str) -> None:
        """Stream a push query until Ctrl-C (client.hs:117-132)."""
        call = self.stub.ExecutePushQuery(
            pb.CommandPushQuery(query_text=sql),
            metadata=self._metadata())
        print("-- streaming; Ctrl-C to stop --", file=self.out)
        try:
            for s in call:
                print(rec.struct_to_dict(s), file=self.out, flush=True)
        except KeyboardInterrupt:
            call.cancel()
            print("\n-- query cancelled --", file=self.out)
        except grpc.RpcError as e:
            if e.code() != grpc.StatusCode.CANCELLED:
                print(f"server error: {e.details()}", file=self.out)

    # ---- REPL -----------------------------------------------------------

    def repl(self, input_lines: Iterable[str] | None = None) -> None:
        """Run the shell. `input_lines` makes it scriptable for tests;
        interactive mode uses readline-backed input()."""
        interactive = input_lines is None
        if interactive:
            try:
                import readline  # noqa: F401 — line editing/history
            except ImportError:
                pass
            print(BANNER, file=self.out)
        it = iter(input_lines) if input_lines is not None else None
        buf: list[str] = []
        while True:
            prompt = CONT if buf else PROMPT
            try:
                if it is None:
                    line = input(prompt)
                else:
                    line = next(it, None)
                    if line is None:
                        break
            except EOFError:
                break
            except KeyboardInterrupt:
                buf.clear()
                print("", file=self.out)
                continue
            line = line.rstrip("\n")
            if not buf and line.strip() in ("\\q", "quit", "exit"):
                break
            if not line.strip():
                continue
            buf.append(line)
            if line.rstrip().endswith(";"):
                sql = "\n".join(buf)
                buf.clear()
                self.execute(sql)


def main(argv=None) -> None:
    import argparse

    ap = argparse.ArgumentParser("hstream-tpu-client")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=6570)
    ap.add_argument("-e", "--execute", default=None,
                    help="run one statement and exit")
    args = ap.parse_args(argv)
    client = Client(f"{args.host}:{args.port}")
    try:
        if args.execute:
            client.execute(args.execute)
        else:
            client.repl()
    finally:
        client.close()


if __name__ == "__main__":
    main()
