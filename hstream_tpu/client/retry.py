"""Client-side resilience: jittered backoff honoring retry-after.

When the server refuses work with RESOURCE_EXHAUSTED it attaches a
retry-after hint twice: a `retry-after-ms` trailing-metadata entry and
a ``retry_after_ms=N`` token in the status message (so even clients
that drop metadata can parse it). `RetryPolicy.call` retries only the
statuses `RETRYABLE_CODES` classifies as duplication-safe (flow-control
refusals, issued before any work — every other status, including
mid-call transport drops, is explicitly NON_RETRYABLE), sleeping

  * ``hint * (1 + U[0, 0.5))`` when the server sent a hint — the hint
    is a floor, the jitter spreads the herd, or
  * full-jitter exponential backoff ``U[0, min(max, base * 2^attempt))``
    when it did not,

for at most `attempts` tries. Sleep/rng are injectable so tier-1 tests
drive convergence with a fake clock and zero wall-clock sleeps.

Leader failover (ISSUE 9): a fenced store leader refuses mutations
with UNAVAILABLE carrying the NEW leader's address twice — an
``x-leader-hint`` trailing-metadata entry and a ``not_leader
leader_hint=ADDR`` token in the message. UNAVAILABLE stays
non-retryable in general (a mid-call transport drop may have landed a
mutation), but WITH a hint the refusal was issued before any work, so
`RetryPolicy.call` follows it: the caller passes ``on_leader_hint``
(rebind your channel/stub to the hinted address) and the policy
retries with the same jittered backoff instead of failing the
statement (`HINTED_RETRYABLE_CODES`).
"""

from __future__ import annotations

import random
import re
import time

import grpc

RETRY_AFTER_KEY = "retry-after-ms"
_RETRY_AFTER_RE = re.compile(r"retry_after_ms=(\d+)")
LEADER_HINT_KEY = "x-leader-hint"
_LEADER_HINT_RE = re.compile(r"not_leader leader_hint=([^\s)]+)")

# Retryability classification of every status the server emits (the
# analyzer's errcontract pass keeps this table honest in both
# directions: emitted ⊆ classified, retried ⊆ emitted ∪ transport).
#
# Retryable: the refusal is issued BEFORE any work happens, so
# re-sending the identical request is duplication-safe.
#   RESOURCE_EXHAUSTED  flow-control refusal (quota / overload shed);
#                       the server attaches a retry-after hint
# Non-retryable: re-sending cannot help, or could double-apply.
#   NOT_FOUND / ALREADY_EXISTS / INVALID_ARGUMENT — caller errors
#   FAILED_PRECONDITION — state conflict (e.g. a replica already bound
#                       to another leader); needs operator action
#   INTERNAL            server-side failure; retrying re-runs the
#                       failure and can duplicate side effects
#   ABORTED             the operation was terminated on purpose
#   UNAVAILABLE         transport drop — possibly MID-CALL, after a
#                       mutation landed but before its response; the
#                       server has no request-id dedup, so a blind
#                       resend can append the same records twice.
#                       Blanket retry is unsafe at this layer; an
#                       application that knows its call is idempotent
#                       retries it itself.
RETRYABLE_CODES = frozenset({
    grpc.StatusCode.RESOURCE_EXHAUSTED,
})
NON_RETRYABLE_CODES = frozenset({
    grpc.StatusCode.NOT_FOUND,
    grpc.StatusCode.ALREADY_EXISTS,
    grpc.StatusCode.INVALID_ARGUMENT,
    grpc.StatusCode.FAILED_PRECONDITION,
    grpc.StatusCode.INTERNAL,
    grpc.StatusCode.ABORTED,
    grpc.StatusCode.UNAVAILABLE,
})
# Statuses retryable ONLY when the error carries a leader hint (the
# NOT_LEADER contract): the refusal is issued before any work, and the
# hint names where to send the retry. The BARE form of each code stays
# in NON_RETRYABLE_CODES — without the hint an UNAVAILABLE may be a
# mid-call transport drop whose mutation landed. The errcontract pass
# enforces both halves (hinted ⊆ non-retryable-bare, hinted ⊆ emitted).
HINTED_RETRYABLE_CODES = frozenset({
    grpc.StatusCode.UNAVAILABLE,
})


def is_retryable(code) -> bool:
    """Classify a grpc.StatusCode; unknown codes are non-retryable."""
    return code in RETRYABLE_CODES


def retry_after_ms_from_error(e: grpc.RpcError) -> int | None:
    """The server's retry-after hint in ms, or None: trailing metadata
    first, message text as the fallback."""
    try:
        md = e.trailing_metadata() or ()
    except Exception:  # noqa: BLE001 — not all RpcErrors carry it
        md = ()
    for k, v in md:
        if k == RETRY_AFTER_KEY:
            try:
                return int(v)
            except ValueError:
                break
    try:
        details = e.details() or ""
    except Exception:  # noqa: BLE001
        details = str(e)
    m = _RETRY_AFTER_RE.search(details)
    return int(m.group(1)) if m else None


def leader_hint_from_error(e: grpc.RpcError) -> str | None:
    """The new leader's address from a NOT_LEADER refusal, or None:
    trailing metadata first, message token as the fallback."""
    try:
        md = e.trailing_metadata() or ()
    except Exception:  # noqa: BLE001 — not all RpcErrors carry it
        md = ()
    for k, v in md:
        if k == LEADER_HINT_KEY and v:
            return str(v)
    try:
        details = e.details() or ""
    except Exception:  # noqa: BLE001
        details = str(e)
    m = _LEADER_HINT_RE.search(details)
    return m.group(1) if m else None


class RetryPolicy:
    """Bounded retry of retryable statuses with jittered backoff."""

    def __init__(self, attempts: int = 6, base_ms: float = 50.0,
                 max_ms: float = 5000.0, *, sleep=None, rng=None):
        self.attempts = max(int(attempts), 1)
        self.base_ms = float(base_ms)
        self.max_ms = float(max_ms)
        self._sleep = time.sleep if sleep is None else sleep
        self._rng = random.Random() if rng is None else rng
        self.retries = 0  # total retries performed over this policy
        self.leader_follows = 0  # retries that followed a leader hint

    def next_delay_ms(self, attempt: int,
                      hint_ms: int | None = None) -> float:
        if hint_ms is not None:
            return hint_ms * (1.0 + 0.5 * self._rng.random())
        cap = min(self.max_ms, self.base_ms * (1 << attempt))
        return max(1.0, cap * self._rng.random())

    def call(self, fn, *args, on_leader_hint=None, **kwargs):
        """Call `fn`, retrying retryable statuses. `on_leader_hint`
        (optional) makes a NOT_LEADER refusal — a HINTED_RETRYABLE
        status carrying a leader hint — followable: the callback
        receives the hinted address (rebind your channel/stub there)
        and the call retries with the same jittered backoff. Without
        the callback, hinted errors surface like any non-retryable."""
        for attempt in range(self.attempts):
            try:
                return fn(*args, **kwargs)
            except grpc.RpcError as e:
                code = None
                try:
                    code = e.code()
                except Exception:  # noqa: BLE001
                    pass
                hint = None
                if (on_leader_hint is not None
                        and code in HINTED_RETRYABLE_CODES):
                    hint = leader_hint_from_error(e)
                if ((not is_retryable(code) and hint is None)
                        or attempt == self.attempts - 1):
                    raise
                self.retries += 1
                if hint is not None:
                    self.leader_follows += 1
                    on_leader_hint(hint)
                delay = self.next_delay_ms(
                    attempt, retry_after_ms_from_error(e))
                self._sleep(delay / 1000.0)
        raise AssertionError("unreachable")  # loop always returns/raises
