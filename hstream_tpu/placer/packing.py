"""Co-compile query packing: N compatible queries, ONE dispatch.

Every windowed aggregate compiles to the same lattice program once its
shapes match — that is the pow2-padding trick that already makes cycle
widths share compiled XLA executables. Packing pushes it one level up:
queries with the same *signature* (source stream, window shape, agg
kinds + params, key count, emission mode) run on ONE shared
``QueryExecutor`` whose group key is extended with a synthetic ``__q``
slot column. A member query's rows are tagged with its slot and its
key/agg columns are renamed to canonical positions (``__k0..``,
``__a0..``), so the shared lattice sees one homogeneous row shape —
the 2nd..Nth attached query changes only key VALUES, never a shape,
and compiles nothing (RetraceGuard-pinned in tests/test_packing.py).
Emitted rows demux on ``__q`` back to per-member names and sinks.

Incompatible plans refuse with a typed :class:`PackRefusal` that
EXPLAIN surfaces as a ``PACK:`` line, mirroring the mesh-exclusion
discipline (sql/codegen.mesh_exclusion_reason).

Scope: packing applies to freshly launched queries when the server
runs with ``--pack-queries``; a packed query that is resumed after a
restart comes back as a normal standalone task (its state snapshot
discipline is per-task), so packing never risks the recovery path.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass

from hstream_tpu.common.logger import get_logger
from hstream_tpu.engine.expr import Col
from hstream_tpu.engine.plan import (
    AggKind,
    AggregateNode,
    AggSpec,
    SourceNode,
)
from hstream_tpu.engine.types import ColumnType, Schema
from hstream_tpu.engine.window import (
    HoppingWindow,
    SessionWindow,
    TumblingWindow,
)

log = get_logger("placer.packing")


@dataclass(frozen=True)
class PackRefusal:
    """Why a plan cannot join a pack (machine-readable: EXPLAIN prints
    ``code``, admin output carries both)."""

    code: str
    detail: str

    def __str__(self) -> str:
        return f"{self.code}: {self.detail}"


def _select_of(plan):
    """The SelectPlan under a lowered statement, or None."""
    from hstream_tpu.sql import plans

    if isinstance(plan, plans.SelectPlan):
        return plan
    if isinstance(plan, plans.CreateBySelectPlan):
        return plan.select
    return None


def pack_signature(plan):
    """The pack-compatibility signature of a lowered plan, or a
    :class:`PackRefusal`. Two plans with equal signatures share one
    compiled lattice; agg INPUT column names and key column names are
    deliberately absent — they canonicalize to positional columns."""
    sel = _select_of(plan)
    if sel is None:
        return PackRefusal("not-a-select",
                           "only stream SELECT queries pack")
    if sel.join is not None:
        return PackRefusal("join",
                           "join state is per-query (two-sided stores)")
    node = sel.node
    if not isinstance(node, AggregateNode):
        return PackRefusal("stateless",
                           "no windowed aggregate state to share")
    if not isinstance(node.child, SourceNode):
        return PackRefusal("filter",
                           "WHERE/projection stages are per-query")
    w = node.window
    if w is None:
        return PackRefusal("unwindowed",
                           "global group-by has no shared close cycle")
    if isinstance(w, TumblingWindow):
        wsig = ("tumbling", int(w.size_ms), int(w.grace_ms))
    elif isinstance(w, HoppingWindow):
        wsig = ("hopping", int(w.size_ms), int(w.advance_ms),
                int(w.grace_ms))
    elif isinstance(w, SessionWindow):
        return PackRefusal("session-window",
                           "session arenas merge per-key gap chains; "
                           "slots would couple unrelated sessions")
    else:
        return PackRefusal("window",
                           f"unpackable window {type(w).__name__}")
    if node.having is not None:
        return PackRefusal("having", "HAVING predicates are per-query")
    for g in node.group_keys:
        if not isinstance(g, Col):
            return PackRefusal("computed-key",
                               "computed group keys are per-query")
    if node.post_projections:
        # pure renames (SELECT k, COUNT(*) AS c) are member-local —
        # untag applies them; anything computed changes row VALUES
        # and would have to run inside the shared lattice
        keys = {g.name for g in node.group_keys}
        outs = {a.out_name for a in node.aggs}
        for _name, e in node.post_projections:
            if not isinstance(e, Col) or (e.name not in keys
                                          and e.name not in outs):
                return PackRefusal(
                    "projection",
                    "computed select items are per-query")
    aggsig = []
    for a in node.aggs:
        if a.input is not None and not isinstance(a.input, Col):
            return PackRefusal("computed-agg-input",
                               f"{a.kind.value} over an expression is "
                               "per-query")
        aggsig.append((a.kind.value, a.quantile, a.k))
    return (node.child.stream, wsig, bool(sel.emit_changes),
            tuple(aggsig), len(node.group_keys))


def signature_text(sig) -> str:
    """Human-readable one-liner for a signature (EXPLAIN/admin)."""
    stream, wsig, changes, aggs, n_keys = sig
    aggtxt = "+".join(a[0] for a in aggs)
    return (f"{stream} {wsig[0]}({'/'.join(str(x) for x in wsig[1:])}ms)"
            f" {aggtxt} keys={n_keys}"
            f" {'changes' if changes else 'final'}")


def _canonical_plan(sig):
    """Synthesize the shared SelectPlan for one signature: group keys
    ``[__q, __k0..]``, aggs over ``__a0..`` outputs ``__o0..``."""
    from hstream_tpu.sql import plans

    stream, wsig, emit_changes, aggsig, n_keys = sig
    if wsig[0] == "tumbling":
        window = TumblingWindow(size_ms=wsig[1], grace_ms=wsig[2])
    else:
        window = HoppingWindow(size_ms=wsig[1], advance_ms=wsig[2],
                               grace_ms=wsig[3])
    keys = [Col("__q")] + [Col(f"__k{i}") for i in range(n_keys)]
    aggs = []
    inferred: dict[str, ColumnType] = {}
    for j, (kind, quantile, k) in enumerate(aggsig):
        akind = AggKind(kind)
        inp = None
        if akind is not AggKind.COUNT_ALL:
            inp = Col(f"__a{j}")
            inferred[f"__a{j}"] = ColumnType.FLOAT
        aggs.append(AggSpec(kind=akind, out_name=f"__o{j}", input=inp,
                            quantile=quantile, k=k))
    node = AggregateNode(child=SourceNode(stream=stream, schema=Schema(())),
                         group_keys=keys, window=window, aggs=aggs)
    return plans.SelectPlan(
        sql=f"<packed {signature_text(sig)}>", source=stream, node=node,
        schema_req=plans.SchemaRequirement(inferred=inferred),
        emit_changes=emit_changes)


class PackMember:
    """One query's seat in a pack group: its slot, the mapping between
    its column names and the canonical positions, its sink, and the
    LSN it attached at (earlier source rows belong to earlier state and
    are not fed for this member)."""

    def __init__(self, qid: str, slot: int, key_cols: list[str],
                 agg_inputs: list[str | None],
                 emits: list[tuple[str, str, int]],
                 sink, attach_lsn: int):
        self.qid = qid
        self.slot = slot
        self._slot_val = str(slot)
        self.key_cols = key_cols
        self.agg_inputs = agg_inputs
        # emitted-row layout: (field name, "key"|"agg", canonical idx)
        # — carries the member's SELECT-list renames
        self.emits = emits
        self.sink = sink
        self.attach_lsn = attach_lsn

    def tag(self, row: dict) -> dict:
        out = {"__q": self._slot_val}
        for i, kc in enumerate(self.key_cols):
            if kc in row:
                out[f"__k{i}"] = row[kc]
        for j, ac in enumerate(self.agg_inputs):
            if ac is not None and ac in row:
                out[f"__a{j}"] = row[ac]
        return out

    def untag(self, row: dict) -> dict:
        out = {}
        for name, kind, idx in self.emits:
            src = f"__k{idx}" if kind == "key" else f"__o{idx}"
            if kind == "key":
                v = row.get(src)
                if v is not None:
                    out[name] = v
            elif src in row:
                out[name] = row[src]
        for k, v in row.items():
            if k == "__q" or k.startswith(("__k", "__o", "__a")):
                continue
            out.setdefault(k, v)  # winStart/winEnd, change markers
        return out


class PackGroup:
    """One signature's shared executor + its attached members. Feeding
    is serialized under the group lock; one ``feed`` call is one
    ``executor.process`` — one dispatch chain for every member."""

    def __init__(self, ctx, sig, *, batch_capacity: int = 4096):
        self.ctx = ctx
        self.sig = sig
        self.plan = _canonical_plan(sig)
        self.batch_capacity = batch_capacity
        self.executor = None
        self.members: dict[str, PackMember] = {}
        self._next_slot = 0
        self._lock = threading.Lock()
        self._runner: _PackRunner | None = None
        self.batches = 0
        self.rows_in = 0

    @property
    def source_stream(self) -> str:
        return self.sig[0]

    def attach(self, qid: str, sel_plan, sink,
               attach_lsn: int) -> PackMember:
        node = sel_plan.node
        key_cols = [g.name for g in node.group_keys]
        out_names = [a.out_name for a in node.aggs]
        if node.post_projections:
            # pure renames (pack_signature already vetted them): emit
            # each projected item from its canonical position
            keyidx = {n: i for i, n in enumerate(key_cols)}
            aggidx = {n: j for j, n in enumerate(out_names)}
            emits = [(name, "key", keyidx[e.name])
                     if e.name in keyidx
                     else (name, "agg", aggidx[e.name])
                     for name, e in node.post_projections]
        else:
            emits = ([(n, "key", i) for i, n in enumerate(key_cols)]
                     + [(n, "agg", j) for j, n in enumerate(out_names)])
        with self._lock:
            member = PackMember(
                qid, self._next_slot, key_cols=key_cols,
                agg_inputs=[a.input.name if a.input is not None else None
                            for a in node.aggs],
                emits=emits, sink=sink, attach_lsn=attach_lsn)
            self._next_slot += 1
            self.members[qid] = member
        return member

    def detach(self, qid: str) -> bool:
        """Remove a member; True when the group is now empty (the pool
        tears it down)."""
        with self._lock:
            self.members.pop(qid, None)
            return not self.members

    def feed(self, rows: list[dict], ts_ms,
             lsn: int | None = None) -> None:
        """One source micro-batch for every member attached at or
        before `lsn`; builds the shared executor on first contact so
        schema inference sees real (tagged) rows. `ts_ms` is one
        timestamp per row (an int applies to the whole batch)."""
        ts_list = ([int(ts_ms)] * len(rows) if isinstance(ts_ms, int)
                   else list(ts_ms))
        with self._lock:
            members = [m for m in self.members.values()
                       if lsn is None or lsn > m.attach_lsn]
            if not members or not rows:
                return
            tagged = [m.tag(r) for m in members for r in rows]
            ts_tagged = [t for _ in members for t in ts_list]
            if self.executor is None:
                from hstream_tpu.sql.codegen import make_executor

                self.executor = make_executor(
                    self.plan, sample_rows=tagged,
                    batch_capacity=self.batch_capacity)
            out = self.executor.process(tagged, ts_tagged)
            self.batches += 1
            self.rows_in += len(tagged)
            self._demux(out)

    def _demux(self, out_rows) -> None:
        if not out_rows:
            return
        per_slot: dict[str, list[dict]] = {}
        for r in out_rows:
            per_slot.setdefault(str(r.get("__q")), []).append(r)
        by_slot = {m._slot_val: m for m in self.members.values()}
        for slot, rows in per_slot.items():
            m = by_slot.get(slot)
            if m is None:
                continue  # member detached with windows still open
            try:
                m.sink([m.untag(r) for r in rows])
            except Exception:  # noqa: BLE001 — one member's sink
                log.exception("pack sink for %s failed", m.qid)

    def status(self) -> dict:
        with self._lock:
            return {
                "signature": signature_text(self.sig),
                "members": sorted(self.members),
                "slots": {qid: m.slot
                          for qid, m in self.members.items()},
                "batches": self.batches,
                "rows_in": self.rows_in,
                "compiled": self.executor is not None,
            }


class _PackRunner:
    """The group's single source reader: tail the source stream and
    feed every batch to the shared executor. One reader + one dispatch
    per micro-batch regardless of member count."""

    def __init__(self, ctx, group: PackGroup):
        self.ctx = ctx
        self.group = group
        self._stop_evt = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"pack-{group.source_stream}")

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stop_evt.set()
        self._thread.join(timeout=5)

    def _run(self) -> None:
        from hstream_tpu.common import columnar
        from hstream_tpu.common import records as rec
        from hstream_tpu.store.api import DataBatch

        ctx = self.ctx
        try:
            logid = ctx.streams.get_logid(self.group.source_stream)
            reader = ctx.store.new_reader()
            reader.set_timeout(100)
            reader.start_reading(logid, ctx.store.tail_lsn(logid) + 1)
        except Exception:  # noqa: BLE001 — a torn-down store at boot
            log.exception("pack runner for %s could not start",
                          self.group.source_stream)
            return
        while not self._stop_evt.is_set():
            try:
                items = reader.read(256)
            except Exception:  # noqa: BLE001 — store closing
                return
            if not items:
                continue
            for it in items:
                if not isinstance(it, DataBatch):
                    continue
                rows: list[dict] = []
                ts: list[int] = []
                for p in it.payloads:
                    try:
                        pr = rec.parse_record(p)
                    except Exception:  # noqa: BLE001 — foreign bytes
                        continue
                    t = (int(pr.header.publish_time_ms)
                         or int(it.append_time_ms))
                    crows = columnar.payload_rows(pr.payload)
                    if crows is not None:
                        rows.extend(crows)
                        ts.extend([t] * len(crows))
                        continue
                    row = rec.record_to_dict(pr)
                    if row is not None:
                        rows.append(row)
                        ts.append(t)
                if not rows:
                    continue
                try:
                    self.group.feed(rows, ts, lsn=it.lsn)
                except Exception:  # noqa: BLE001 — one poisoned batch
                    log.exception("pack feed on %s failed",
                                  self.group.source_stream)


class PackMemberTask:
    """The running_queries facade for a packed query: the handler
    surface (terminate, status introspection) without a thread of its
    own. `stop` detaches the member from its group."""

    packed = True
    error: BaseException | None = None
    started = True

    def __init__(self, pool: "PackPool", group: PackGroup,
                 member: PackMember, info):
        self.pool = pool
        self.group = group
        self.member = member
        self.info = info
        self.query_id = member.qid
        self.sink_stream = getattr(info, "sink_stream", None)

    def stop(self, detach: bool = False) -> None:  # noqa: ARG002 — the
        # group's lattice holds shared state; a member leaving never
        # snapshots it (signature matches QueryTask.stop)
        self.pool.detach(self.query_id)

    def status(self) -> dict:
        return {"packed": True,
                "signature": signature_text(self.group.sig),
                "slot": self.member.slot}


class PackPool:
    """All pack groups on one server, keyed by signature. ``manual``
    pools never start reader threads — tests drive ``group.feed``
    directly for determinism."""

    def __init__(self, ctx, *, manual: bool = False,
                 batch_capacity: int = 4096):
        self.ctx = ctx
        self.manual = manual
        self.batch_capacity = batch_capacity
        self.groups: dict[tuple, PackGroup] = {}
        self._runners: dict[tuple, _PackRunner] = {}
        self._by_qid: dict[str, PackGroup] = {}
        self._lock = threading.Lock()

    def try_attach(self, qid: str, plan, sink):
        """Attach a freshly launched query. Returns a
        :class:`PackMemberTask` (caller puts it in running_queries) or
        a :class:`PackRefusal` (caller launches a normal task)."""
        sig = pack_signature(plan)
        if isinstance(sig, PackRefusal):
            return sig
        sel = _select_of(plan)
        try:
            logid = self.ctx.streams.get_logid(sel.source)
            attach_lsn = self.ctx.store.tail_lsn(logid)
        except Exception:  # noqa: BLE001 — source gone mid-launch
            return PackRefusal("source", "source stream unavailable")
        with self._lock:
            # lookup + attach under ONE pool-lock hold (lock order
            # pool -> group, same as detach): a concurrent detach of
            # the group's last member cannot pop the group and stop
            # its runner between our lookup and the attach, which
            # would strand this member on a torn-down group that
            # feeds nobody
            group = self.groups.get(sig)
            created = group is None
            if created:
                group = PackGroup(self.ctx, sig,
                                  batch_capacity=self.batch_capacity)
                self.groups[sig] = group
            member = group.attach(qid, sel, sink, attach_lsn)
            self._by_qid[qid] = group
            if created and not self.manual:
                runner = _PackRunner(self.ctx, group)
                self._runners[sig] = runner
                runner.start()
        log.info("packed query %s into %s (slot %d)", qid,
                 signature_text(sig), member.slot)
        return PackMemberTask(self, group, member, None)

    def detach(self, qid: str) -> None:
        runner = None
        with self._lock:
            # check and act in ONE critical section: a concurrent
            # try_attach to the same signature either sees the group
            # before we empty it (and keeps it alive) or creates a
            # fresh one after we popped it — never a member attached
            # to a torn-down group. Lock order is pool -> group; no
            # path nests them the other way.
            group = self._by_qid.pop(qid, None)
            if group is None:
                return
            if group.detach(qid):
                self.groups.pop(group.sig, None)
                runner = self._runners.pop(group.sig, None)
        if runner is not None:
            runner.stop()

    def member_of(self, qid: str) -> PackGroup | None:
        with self._lock:
            return self._by_qid.get(qid)

    def status(self) -> list[dict]:
        with self._lock:
            groups = list(self.groups.values())
        return [g.status() for g in groups]

    def stop(self) -> None:
        with self._lock:
            runners = list(self._runners.values())
            self._runners.clear()
        for r in runners:
            r.stop()
