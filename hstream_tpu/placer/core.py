"""The placer loop: publish, heartbeat, adopt, rebalance.

One daemon thread per armed server (``--placer-interval-ms``). Each
tick:

  1. **publish** this node's load record to ``cluster/nodes/<node>``
     (stats/cluster.publish_node_record) — the cluster-level heartbeat
     every peer's ranking reads;
  2. **heartbeat** the ``scheduler/query/*`` records of queries this
     node owns (running tasks AND tasks the supervisor is about to
     restart — a backoff wait must not read as death to peers); a
     heartbeat that finds the record gone or naming another owner
     means ownership was LOST (a delayed tick let the lease lapse and
     a peer live-adopted) — the loser self-fences: it stops the local
     task crash-style (no snapshot, no status write — the adopter's
     state is the live one) and cancels its supervisor slot, so a
     slow-but-alive owner can never stay a second live owner;
  3. **adopt** queries whose owner's heartbeat lapsed past the lease,
     or that were ``offered`` to this node by a rebalance or a remote
     placement — CAS first (``scheduler.try_adopt_live``: racing
     survivors converge to one owner), then resume from the last
     snapshot; a failed resume goes through the supervisor intake
     (ISSUE 8) so it backs off and breakers like any other death;
  4. **rebalance** when this node's query count skews past the least
     loaded eligible peer: stop one local task WITH a final snapshot
     (``stop(detach=True)`` — status stays RUNNING), then CAS the
     record to ``offered`` naming the target. Never two live owners:
     the local task is dead before the offer is visible, and the offer
     carries a fresh heartbeat so only the target may claim it for one
     full lease.

Every decision journals ``placement_decision`` with a machine-readable
reason and bumps ``placement_decisions``; live adoptions also bump
``queries_adopted``. Disarmed (interval unset), the loop never starts
and none of the records exist — single-server deployments keep the
pure boot-epoch semantics.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque

from hstream_tpu.common.logger import get_logger
from hstream_tpu.placer.score import node_score, rank_nodes, skip_reason
from hstream_tpu.server import scheduler
from hstream_tpu.stats.cluster import (
    cluster_node_records,
    publish_node_record,
)
from hstream_tpu.store.versioned import VersionMismatch

log = get_logger("placer")

DEFAULT_LEASE_MS = 10_000

# a node must exceed the cluster-min query count by this many queries
# before it offers one away — rebalance hysteresis, so two near-equal
# nodes never ping-pong a query
REBALANCE_MIN_DELTA = 2


class Placer:
    """Placement decisions for one server. Constructed always (admin
    introspection and scrape-time scoring work regardless); the loop
    runs only when armed."""

    def __init__(self, ctx, *, interval_ms: int | None = None,
                 lease_ms: int = DEFAULT_LEASE_MS):
        self.ctx = ctx
        self.interval_ms = interval_ms
        self.lease_ms = int(lease_ms)
        self.armed = bool(interval_ms) and int(interval_ms) > 0
        if self.armed:
            # an owner heartbeats once per tick: a lease shorter than
            # a few ticks makes every healthy owner look dead between
            # heartbeats — continuous spurious live-adoptions. Clamp
            # rather than reject so a misconfigured node still boots.
            min_lease = 3 * int(interval_ms)
            if self.lease_ms < min_lease:
                log.warning(
                    "heartbeat lease %dms < 3x placer interval %dms; "
                    "clamping lease to %dms so a delayed tick cannot "
                    "read as owner death", self.lease_ms,
                    int(interval_ms), min_lease)
                self.lease_ms = min_lease
        # bound by the servicer once handlers exist (same resume path
        # the supervisor and RestartQuery use)
        self.resume_fn = None
        self.last_decision: dict | None = None
        self._decisions: deque[dict] = deque(maxlen=64)
        self._stop_evt = threading.Event()
        self._thread: threading.Thread | None = None
        self.ticks = 0

    # ---- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        """Called AFTER the port is bound (like LoadReporter.start):
        records must carry the node's real identity."""
        if not self.armed or self._thread is not None:
            return
        self._thread = threading.Thread(target=self._run, name="placer",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop_evt.set()
        t = self._thread
        if t is not None and t.ident is not None:
            t.join(timeout=5)

    def _run(self) -> None:
        interval_s = max(int(self.interval_ms) / 1000.0, 0.05)
        self.tick()  # boot-time record: visible to peers immediately
        while not self._stop_evt.wait(interval_s):
            self.tick()

    # ---- one tick ----------------------------------------------------------

    def tick(self) -> None:
        """One full decision pass; every stage fails open so a torn-
        down subsystem mid-shutdown cannot kill the loop."""
        self.ticks += 1
        for stage in (self._publish, self._heartbeat_owned,
                      self._adopt_sweep, self._rebalance):
            if self._stop_evt.is_set():
                return
            try:
                stage()
            except Exception:  # noqa: BLE001 — the loop must survive
                log.exception("placer stage %s failed",
                              stage.__name__)

    def _publish(self) -> None:
        publish_node_record(self.ctx)

    def _heartbeat_owned(self) -> None:
        ctx = self.ctx
        owned = set(getattr(ctx, "running_queries", {}))
        sup = getattr(ctx, "supervisor", None)
        if sup is not None:
            # a query in supervised backoff is still OURS: without the
            # heartbeat a short lease would let a peer adopt it while
            # the local restart is pending — two live owners
            st = sup.status()
            owned.update(st.get("pending", {}))
        for qid in sorted(owned):
            if not scheduler.heartbeat_assignment(ctx, qid):
                # definitive ownership loss (record gone, re-owned by
                # a peer, or offered away): keeping the local task
                # running would make two live owners
                self._self_fence(qid)

    def _self_fence(self, qid: str) -> None:
        """Stop the local task for a query this node no longer owns.
        Crash-mode stop: no final snapshot and no status write — the
        new owner already resumed from the last snapshot and writes
        its own; a stale snapshot or a TERMINATED status from the
        fenced loser would corrupt the adopter's run. The supervisor
        slot is cancelled first so a pending restart cannot resurrect
        the query after the fence."""
        ctx = self.ctx
        rec = scheduler.assignment(ctx, qid)
        sup = getattr(ctx, "supervisor", None)
        if sup is not None:
            sup.cancel(qid)
        task = ctx.running_queries.pop(qid, None)
        if task is not None:
            try:
                if getattr(task, "packed", False):
                    task.stop()  # detach from the shared lattice
                else:
                    task.stop(crash=True)
            except Exception:  # noqa: BLE001 — the fence must stand
                log.exception("self-fence stop of %s failed", qid)
        log.warning("self-fenced query %s: record now names %s (%s)",
                    qid, (rec or {}).get("node"),
                    "missing" if rec is None
                    else rec.get("state", "owned"))
        self._decide("self_fence", qid, target=(rec or {}).get("node"),
                     reason="ownership_lost")

    def _adopt_sweep(self) -> None:
        from hstream_tpu.server.persistence import TaskStatus

        ctx = self.ctx
        if getattr(ctx.store, "fenced_by", None) is not None:
            return  # a fenced store cannot own queries
        me = scheduler.node_name(ctx)
        for info in ctx.persistence.get_queries():
            qid = info.query_id
            if qid in ctx.running_queries:
                continue
            rec = scheduler.assignment(ctx, qid)
            state = (rec or {}).get("state", "owned")
            offered_to_me = (rec is not None and state == "offered"
                             and rec.get("node") == me)
            if info.status == TaskStatus.CREATED and not offered_to_me:
                # mid-launch on its creator — UNLESS the record's
                # heartbeat already lapsed: the creator died before
                # the task registered, or a remote placement's target
                # died before claiming its offer. Any survivor may
                # rescue those; otherwise an orphaned CREATED query
                # would wait for a server reboot while the cluster is
                # live. No record at all (the creator is writing it
                # right now) stays off-limits.
                age = scheduler.owner_heartbeat_age_ms(rec)
                if age is None or age <= self.lease_ms:
                    continue
            if info.status not in (TaskStatus.CREATED,
                                   TaskStatus.RUNNING):
                continue
            if rec is not None and rec.get("node") == me \
                    and state == "owned":
                continue  # already mine: the supervisor's domain
            if rec is not None and rec.get("node") != me \
                    and "hb_ms" not in rec:
                # legacy record (written by a server with the placer
                # disarmed): its owner never heartbeats, so it may be
                # alive RIGHT NOW — the live sweep must not apply the
                # boot-epoch rule to it. Boot-time adoption (where a
                # lower epoch really does mean a dead predecessor)
                # remains the rescue path for these.
                continue
            if not scheduler.adoption_allowed(ctx, qid):
                continue
            if not scheduler.try_adopt_live(ctx, qid, self.lease_ms):
                continue
            reason = "offered" if offered_to_me else (
                "unowned" if rec is None else "lease_lapsed")
            self._count("queries_adopted", qid)
            self._decide("adopt", qid, target=me, reason=reason,
                         prev_owner=(rec or {}).get("node"))
            self._resume_adopted(info)

    def _resume_adopted(self, info) -> None:
        from hstream_tpu.server.persistence import TaskStatus

        ctx = self.ctx
        resume = self.resume_fn
        if resume is None:
            log.warning("adopted %s but no resume_fn bound yet",
                        info.query_id)
            return
        try:
            resume(info)
            ctx.persistence.set_query_status(info.query_id,
                                             TaskStatus.RUNNING)
        except Exception as e:  # noqa: BLE001 — supervisor intake: a
            # failed adoption resume backs off and breakers exactly
            # like a crashed task (ISSUE 8)
            log.exception("resume of adopted query %s failed",
                          info.query_id)
            sup = getattr(ctx, "supervisor", None)
            if sup is not None:
                sup.note_death(info, e)

    def _rebalance(self) -> None:
        from hstream_tpu.server.persistence import TaskStatus

        ctx = self.ctx
        me = scheduler.node_name(ctx)
        local = getattr(ctx, "running_queries", {})
        if len(local) < REBALANCE_MIN_DELTA:
            return
        ranked, _skipped = rank_nodes(cluster_node_records(ctx),
                                      lease_ms=self.lease_ms)
        counts = {node: rec.get("running_queries", 0)
                  for node, rec in cluster_node_records(ctx).items()}
        peers = [(s, n) for s, n in ranked if n != me]
        if not peers:
            return
        target_score, target = peers[0]
        if len(local) - int(counts.get(target, 0)) < REBALANCE_MIN_DELTA:
            return
        # deterministic pick: the newest movable query (highest id) —
        # its state is smallest, so the move costs the least
        for qid in sorted(local, reverse=True):
            task = local.get(qid)
            if task is None or getattr(task, "packed", False):
                continue  # pack members share a lattice; never moved
            rec = scheduler.assignment(ctx, qid)
            if rec is None or rec.get("node") != me \
                    or rec.get("state", "owned") != "owned":
                continue
            try:
                if ctx.persistence.get_query(qid).status \
                        != TaskStatus.RUNNING:
                    continue
            except Exception:  # noqa: BLE001 — deleted mid-sweep
                continue
            self._move(qid, task, target, target_score)
            return  # at most ONE move per tick: re-rank before more

    def _move(self, qid: str, task, target: str,
              target_score: float) -> None:
        ctx = self.ctx
        sup = getattr(ctx, "supervisor", None)
        if sup is not None:
            sup.cancel(qid)  # no resurrect racing the handoff
        ctx.running_queries.pop(qid, None)
        try:
            task.stop(detach=True)  # final snapshot; status RUNNING
        except Exception:  # noqa: BLE001 — a dying task still moves:
            pass           # the target resumes from the last snapshot
        if scheduler.offer_assignment(ctx, qid, target):
            self._decide("rebalance", qid, target=target,
                         reason="load_skew", target_score=target_score)
            return
        # lost the record race: take the query back locally
        log.warning("rebalance offer of %s to %s lost CAS; relaunching "
                    "locally", qid, target)
        scheduler.record_assignment(ctx, qid)
        resume = self.resume_fn
        if resume is not None:
            try:
                resume(ctx.persistence.get_query(qid))
            except Exception:  # noqa: BLE001
                log.exception("local relaunch of %s failed", qid)

    # ---- placement of new queries ------------------------------------------

    def place_for_launch(self, qid: str) -> str | None:
        """Pick the owner for a freshly launched query. None = launch
        locally (disarmed, no eligible peer, or this node won). A
        remote winner gets an ``offered`` record — its placer claims
        and resumes it within one tick."""
        ctx = self.ctx
        me = scheduler.node_name(ctx)
        if not self.armed:
            return None
        publish_node_record(ctx)  # rank with my freshest numbers
        ranked, skipped = rank_nodes(cluster_node_records(ctx),
                                     lease_ms=self.lease_ms)
        if not ranked:
            return None
        score, winner = ranked[0]
        self._decide("place", qid, target=winner, reason="least_loaded",
                     score=score,
                     scores={n: s for s, n in ranked}, skipped=skipped)
        if winner == me:
            return None
        value = json.dumps(
            {"node": winner, "epoch": 0, "hb_ms": scheduler.now_ms(),
             "state": "offered", "src": me}).encode()
        key = "scheduler/query/" + qid
        for _ in range(16):
            cur = ctx.config.get(key)
            try:
                ctx.config.put(key, value, base_version=None
                               if cur is None else cur[0])
                return winner
            except VersionMismatch:
                continue
        return None  # record kept losing CAS: launch locally

    # ---- introspection -----------------------------------------------------

    def scores(self) -> dict[str, float]:
        """node -> score for nodes with a fresh record (stale nodes
        drop off, taking their gauge series with them)."""
        ranked, _ = rank_nodes(cluster_node_records(self.ctx),
                               lease_ms=max(self.lease_ms, 1))
        return {node: score for score, node in ranked}

    def status(self) -> dict:
        ctx = self.ctx
        now = int(time.time() * 1000)
        nodes = {}
        for node, rec in sorted(cluster_node_records(ctx).items()):
            nodes[node] = {
                "score": node_score(rec),
                "skip": skip_reason(rec, lease_ms=self.lease_ms,
                                    now_ms=now),
                "running_queries": rec.get("running_queries", 0),
                "rss_mb": round(rec.get("rss_bytes", 0) / 1e6, 1),
                "dispatch_p99_ms": rec.get("dispatch_p99_ms"),
                "shed_level": rec.get("shed_level", 0),
                "fenced": rec.get("fenced", False),
                "hb_age_ms": max(0, now - int(rec.get("hb_ms", 0))),
            }
        placements = {}
        for qid, rec in sorted(scheduler.assignments(ctx).items()):
            placements[qid] = {
                "node": rec.get("node"),
                "state": rec.get("state", "owned"),
                "epoch": rec.get("epoch"),
                "hb_age_ms": scheduler.owner_heartbeat_age_ms(rec),
            }
        pool = getattr(ctx, "pack_pool", None)
        return {
            "node": scheduler.node_name(ctx),
            "armed": self.armed,
            "interval_ms": self.interval_ms,
            "lease_ms": self.lease_ms,
            "ticks": self.ticks,
            "nodes": nodes,
            "placements": placements,
            "last_decision": self.last_decision,
            "decisions": list(self._decisions),
            "packs": pool.status() if pool is not None else [],
        }

    # ---- bookkeeping -------------------------------------------------------

    def _decide(self, action: str, qid: str, **fields) -> None:
        decision = {"action": action, "query": qid,
                    "node": scheduler.node_name(self.ctx),
                    "ts_ms": int(time.time() * 1000), **fields}
        self.last_decision = decision
        self._decisions.append(decision)
        self._count("placement_decisions", qid)
        events = getattr(self.ctx, "events", None)
        if events is None:
            return
        try:
            events.append(
                "placement_decision",
                f"{action} {qid} -> {fields.get('target')} "
                f"({fields.get('reason')})",
                **decision)
        except Exception:  # noqa: BLE001 — journaling is best-effort
            pass

    def _count(self, metric: str, qid: str) -> None:
        stats = getattr(self.ctx, "stats", None)
        if stats is None:
            return
        try:
            stats.stream_stat_add(metric, qid)
        except Exception:  # noqa: BLE001 — metrics must not gate
            pass           # placement
