"""Node scoring: fold one published node record into a load score.

The record is the bounded ``node_record_fields`` shape every armed
placer publishes to ``cluster/nodes/<node>`` each tick (the same axes
``NodeStatsReport`` and the ``node_load_report`` journal event carry:
rss, device HBM bytes, append-front depth, running queries, dispatch
p99, health counts).
Lower score = preferred. The fold is deliberately simple and DOCUMENTED
(README "Placement & failover adoption"); determinism matters more than
cleverness — two placers ranking the same records must pick the same
winner, so ties break on the node name.
"""

from __future__ import annotations

import time

# score weights: one running query costs as much as 5 staged-but-
# unstepped batches; a DEGRADED query as much as a running one; a
# STALLED query dominates everything but ineligibility
W_RUNNING_QUERIES = 10.0
W_APPEND_INFLIGHT = 2.0
W_APPEND_FRONT = 2.0
W_ARENA_PENDING = 2.0
W_DISPATCH_P99_MS = 1.0
W_RSS_GB = 1.0
# device HBM is the scarce axis on an accelerator host: a GB of live
# arena bytes costs 5x a GB of host rss (ISSUE 18 — the record carries
# device_hbm_bytes from the HBM accounting plane; nodes without device
# executors report 0 and the term vanishes)
W_HBM_GB = 5.0
W_DEGRADED = 10.0
W_STALLED = 100.0

# machine-readable ineligibility reasons (admin `placer` surfaces them)
SKIP_STALE = "stale-record"      # node record heartbeat lapsed
SKIP_FENCED = "fenced"           # store fenced by a higher epoch
SKIP_SHEDDING = "shedding"       # overload ladder at DEFER or worse
SKIP_STALLED = "stalled-queries"  # node reports STALLED queries


def node_score(record: dict) -> float:
    """Load score of one node record; lower = preferred."""
    health = record.get("health") or {}
    return round(
        W_RUNNING_QUERIES * float(record.get("running_queries", 0))
        + W_APPEND_INFLIGHT * float(record.get("append_inflight", 0))
        + W_APPEND_FRONT * float(
            (record.get("append_front") or {}).get("in_flight", 0))
        + W_ARENA_PENDING * float(
            record.get("arena_pending_batches", 0))
        + W_DISPATCH_P99_MS * float(record.get("dispatch_p99_ms") or 0.0)
        + W_RSS_GB * float(record.get("rss_bytes", 0)) / 1e9
        + W_HBM_GB * float(record.get("device_hbm_bytes", 0)) / 1e9
        + W_DEGRADED * float(health.get("degraded", 0))
        + W_STALLED * float(health.get("stalled", 0)), 3)


def skip_reason(record: dict, *, lease_ms: int,
                now_ms: int | None = None) -> str | None:
    """Why this node must not receive placements (None = eligible).
    ISSUE 17: skip STALLED / breaker-open / fenced nodes — a node
    reporting stalled queries is either overloaded or sick, and a
    fenced store cannot own anything."""
    if now_ms is None:
        now_ms = int(time.time() * 1000)
    hb = record.get("hb_ms") or record.get("ts_ms") or 0
    if now_ms - int(hb) > int(lease_ms):
        return SKIP_STALE
    if record.get("fenced"):
        return SKIP_FENCED
    if int(record.get("shed_level", 0)) >= 1:
        return SKIP_SHEDDING
    if int((record.get("health") or {}).get("stalled", 0)) > 0:
        return SKIP_STALLED
    return None


def rank_nodes(records: dict[str, dict], *, lease_ms: int,
               now_ms: int | None = None
               ) -> tuple[list[tuple[float, str]], dict[str, str]]:
    """(ranked eligible [(score, node)] best-first, skipped
    {node: reason}). Deterministic: score then node name."""
    ranked: list[tuple[float, str]] = []
    skipped: dict[str, str] = {}
    for node, rec in records.items():
        reason = skip_reason(rec, lease_ms=lease_ms, now_ms=now_ms)
        if reason is not None:
            skipped[node] = reason
            continue
        ranked.append((node_score(rec), node))
    ranked.sort()
    return ranked, skipped
