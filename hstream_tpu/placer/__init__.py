"""The placer (ISSUE 17): turn the load/health signal plane into
placement decisions.

Three decision surfaces over the signals PRs 12-14 built:

  * **placement** — rank candidate nodes by the load fold each node
    publishes to ``cluster/nodes/<node>`` (stats/cluster) and write the
    winner onto ``scheduler/query/<qid>`` in the CAS-versioned config
    store (the ``try_adopt`` discipline: racing placers converge).
  * **runtime adoption** — owners heartbeat their scheduler records;
    survivors adopt a crashed node's queries live through
    ``try_adopt_live`` once the heartbeat lease lapses, resuming from
    the last snapshot through the supervisor intake (no restart of the
    dead node needed).
  * **co-compile packing** — bucket compatible queries (same source /
    window shape / agg set) into ONE shared executor whose lattice is
    keyed by a synthetic ``__q`` slot column, so N queries ride one
    pow2-padded dispatch and the 2nd..Nth query compiles nothing.

The loop is **disarmed by default** (``--placer-interval-ms`` unset):
a single-server deployment keeps the pure boot-epoch adoption
semantics with zero new background writes.
"""

from hstream_tpu.placer.core import (
    DEFAULT_LEASE_MS,
    Placer,
)
from hstream_tpu.placer.packing import (
    PackPool,
    PackRefusal,
    pack_signature,
    signature_text,
)
from hstream_tpu.placer.score import node_score, rank_nodes

__all__ = [
    "DEFAULT_LEASE_MS",
    "PackPool",
    "PackRefusal",
    "Placer",
    "node_score",
    "pack_signature",
    "rank_nodes",
    "signature_text",
]
