"""REST gateway: HTTP façade over the gRPC API.

Reference: hstream-http-server (~1,057 LoC Servant) — one resource
module per entity (streams/queries/nodes/connectors/views/overview)
proxying a gRPC client, plus Swagger
(HStream/HTTP/Server/API.hs:33-54). Here a stdlib ThreadingHTTPServer
routes the same resources onto the HStreamApi stub; /overview surfaces
the server's stats holder via the GetStats RPC.

Every request carries a correlation id: the caller's ``X-Request-Id``
header when present, a generated one otherwise. The id is stamped into
the proxied gRPC call's metadata (handlers bind it into their log
records) and echoed back as a response header, so one id follows a
request client -> gateway -> handler.

Routes (JSON in/out unless noted):
  GET    /overview                    cluster summary + per-stream stats
                                      + flow/shed state + pipeline stages
  GET    /metrics                     Prometheus text exposition
  GET    /stats?entity=&interval=     per-entity rate-family tables
                                      (streams|subscriptions|queries x
                                      1min|10min|1h)
  GET    /cluster-stats?peers=        federated node load reports
  GET    /events?kind=&since=&limit=  event journal slice
  GET    /streams                     list
  POST   /streams {"name": ...}       create
  DELETE /streams/<name>              delete
  POST   /streams/<name>/append {"records": [{...}]}   append JSON rows
  POST   /streams/<name>/appendColumnar <raw frame>     framed columnar
                                        block (octet-stream, ISSUE 12)
  GET    /queries | POST /queries {"sql": ...} | GET|DELETE /queries/<id>
  POST   /queries/<id>/restart
  GET    /queries/<id>/health         OK/DEGRADED/STALLED rollup
  GET    /queries/<id>/trace          span ring, Chrome trace JSON
  GET    /queries/<id>/flightrec      flight-recorder postmortem bundles
  GET    /programs                    compiled-program inventory +
                                      XLA cost analysis
  GET    /views | GET /views/<name> (pull query) | DELETE /views/<name>
  GET    /connectors | POST /connectors {"config": sql} | DELETE .../<id>
  GET    /nodes
  GET    /swagger.json
"""

from __future__ import annotations

import json
import re
import threading
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any

import grpc

from hstream_tpu.common import locktrace
from hstream_tpu.common import records as rec
from hstream_tpu.common.logger import (
    REQUEST_ID_KEY,
    current_request_id,
    request_context,
)
from hstream_tpu.proto import api_pb2 as pb
from hstream_tpu.proto.rpc import HStreamApiStub

# Every status the server emits maps EXPLICITLY (the analyzer's
# errcontract pass enforces it): 500-by-default would let a new status
# silently degrade to an opaque 500 instead of failing the contract.
_STATUS = {
    grpc.StatusCode.NOT_FOUND: 404,
    grpc.StatusCode.ALREADY_EXISTS: 409,
    grpc.StatusCode.INVALID_ARGUMENT: 400,
    grpc.StatusCode.FAILED_PRECONDITION: 400,
    grpc.StatusCode.RESOURCE_EXHAUSTED: 429,
    grpc.StatusCode.ABORTED: 409,
    grpc.StatusCode.INTERNAL: 500,
    grpc.StatusCode.UNAVAILABLE: 503,
}


class _CorrelatedStub:
    """Stub proxy stamping the active request's correlation id — and
    its trace context (ISSUE 13: trace id = request id, parent span =
    this gateway hop) — into every proxied gRPC call's metadata."""

    def __init__(self, stub: HStreamApiStub):
        self._stub = stub

    def __getattr__(self, name: str):
        fn = getattr(self._stub, name)

        def call(request, **kwargs):
            rid = current_request_id()
            if rid and "metadata" not in kwargs:
                from hstream_tpu.common import tracing

                kwargs["metadata"] = (
                    (REQUEST_ID_KEY, rid),
                    (tracing.TRACE_ID_KEY, rid),
                    (tracing.PARENT_SPAN_KEY, f"gw-{rid}"))
            return fn(request, **kwargs)

        return call


class Gateway:
    """Routes HTTP requests onto a single shared gRPC stub.

    Failover-aware (ISSUE 9): when the server answers UNAVAILABLE with
    a leader hint (its replicated store was fenced by a promoted
    follower), the gateway rebinds its channel to the hinted leader
    and retries the request once instead of bouncing a 503 to every
    HTTP caller; later requests ride the rebound channel."""

    def __init__(self, server_addr: str):
        self.server_addr = server_addr
        self.leader_follows = 0  # rebinds performed after a hint
        # named traced lock (ISSUE 14): the rebind-once channel swap is
        # the gateway's one cross-thread rendezvous — witness-covered
        self._bind_lock = locktrace.lock("gateway.bind")
        self.channel = grpc.insecure_channel(server_addr)
        self.stub = _CorrelatedStub(HStreamApiStub(self.channel))
        # channels replaced by a leader-hint rebind, closed only at
        # gateway shutdown: another handler thread may still have an
        # RPC in flight on the old channel (e.g. a read the fenced
        # leader can still serve) — closing it mid-call would turn
        # that request into a spurious CANCELLED/500. Bounded by the
        # number of failovers over the gateway's lifetime.
        self._retired: list = []

    def close(self) -> None:
        with self._bind_lock:
            retired, self._retired = self._retired, []
        for ch in retired:
            try:
                ch.close()
            except Exception:  # noqa: BLE001
                pass
        self.channel.close()

    def _follow_leader_hint(self, hint: str) -> None:
        """Rebind the shared channel/stub to the hinted new leader.
        Concurrent requests that all hit the fenced leader rebind
        once — the second caller finds the address already current."""
        with self._bind_lock:
            if hint == self.server_addr:
                return
            self._retired.append(self.channel)
            self.server_addr = hint
            self.channel = grpc.insecure_channel(hint)
            self.stub = _CorrelatedStub(HStreamApiStub(self.channel))
            self.leader_follows += 1

    # ---- resource handlers -----------------------------------------------

    def handle(self, method: str, path: str, body: dict | None,
               query: str = "") -> tuple[int, Any]:
        out = self._handle_once(method, path, body, query)
        hint = out[2].pop("x-follow-leader", None) if len(out) > 2 else None
        if hint is not None:
            # NOT_LEADER: follow the hint and retry this request once
            self._follow_leader_hint(hint)
            out = self._handle_once(method, path, body, query)
            if len(out) > 2:
                out[2].pop("x-follow-leader", None)
        return out

    def _handle_once(self, method: str, path: str, body: dict | None,
                     query: str = "") -> tuple[int, Any]:
        stub = self.stub
        try:
            if path == "/overview" and method == "GET":
                return 200, self._overview()
            if path == "/metrics" and method == "GET":
                # Prometheus scrape: raw text passthrough, not JSON
                from hstream_tpu.stats.prometheus import CONTENT_TYPE

                text = self._admin("metrics")["text"]
                return 200, text, {"Content-Type": CONTENT_TYPE}
            if path == "/events" and method == "GET":
                from urllib.parse import parse_qs

                q = parse_qs(query or "")
                args: dict[str, Any] = {}
                if q.get("kind"):
                    args["kind"] = q["kind"][0]
                if q.get("since"):
                    args["since"] = int(q["since"][0])
                if q.get("limit"):
                    args["limit"] = int(q["limit"][0])
                return 200, self._admin("events", **args)["events"]
            if path == "/stats" and method == "GET":
                # Overview stats endpoint (ISSUE 15): per-entity rate
                # tables off the multi-level ladders — the JSON face
                # of `admin stats`
                from urllib.parse import parse_qs

                q = parse_qs(query or "")
                args = {}
                if q.get("entity"):
                    args["entity"] = q["entity"][0]
                if q.get("interval"):
                    args["interval"] = q["interval"][0]
                return 200, self._admin("stats", **args)
            if path == "/cluster-stats" and method == "GET":
                # federated per-node load reports (one JSON object per
                # node, keyed by node name)
                from urllib.parse import parse_qs

                q = parse_qs(query or "")
                args = {}
                if q.get("peers"):
                    args["peers"] = q["peers"][0]
                if q.get("timeout_s"):
                    args["timeout_s"] = float(q["timeout_s"][0])
                return 200, self._admin("cluster-stats", **args)
            if path == "/swagger.json" and method == "GET":
                return 200, SWAGGER
            if path == "/streams" and method == "GET":
                out = stub.ListStreams(pb.ListStreamsRequest())
                return 200, [{"name": s.stream_name,
                              "replication_factor": s.replication_factor}
                             for s in out.streams]
            if path == "/streams" and method == "POST":
                name = (body or {}).get("name")
                if not name:
                    return 400, {"error": "body needs {\"name\": ...}"}
                stub.CreateStream(pb.Stream(
                    stream_name=name,
                    replication_factor=(body or {}).get(
                        "replication_factor", 1)))
                return 201, {"name": name}
            m = re.fullmatch(r"/streams/([^/]+)", path)
            if m and method == "DELETE":
                stub.DeleteStream(pb.DeleteStreamRequest(
                    stream_name=m.group(1)))
                return 200, {"deleted": m.group(1)}
            m = re.fullmatch(r"/streams/([^/]+)/append", path)
            if m and method == "POST":
                rows = (body or {}).get("records")
                if not isinstance(rows, list) or not rows:
                    return 400, {"error":
                                 "body needs {\"records\": [{...}]}"}
                req = pb.AppendRequest(stream_name=m.group(1))
                for row in rows:
                    ts = row.pop("__time_ms", None) if isinstance(
                        row, dict) else None
                    req.records.append(
                        rec.build_record(row, publish_time_ms=ts))
                resp = stub.Append(req)
                return 200, {"record_ids": [
                    {"batch_id": r.batch_id, "batch_index": r.batch_index}
                    for r in resp.record_ids]}
            m = re.fullmatch(r"/streams/([^/]+)/appendColumnar", path)
            if m and method == "POST":
                # raw framed columnar block (application/octet-stream):
                # the HTTP face of the wire-speed append path — the
                # gateway proxies the bytes untouched, the server's
                # frame door does all validation (400 on a bad frame)
                if not isinstance(body, (bytes, bytearray)) or not body:
                    return 400, {"error": "body must be one framed "
                                          "columnar block (raw bytes)"}
                resp = stub.AppendColumnar(pb.AppendColumnarRequest(
                    stream_name=m.group(1), blocks=[bytes(body)]))
                return 200, {"rows": resp.rows, "record_ids": [
                    {"batch_id": r.batch_id, "batch_index": r.batch_index}
                    for r in resp.record_ids]}

            if path == "/queries" and method == "GET":
                out = stub.ListQueries(pb.ListQueriesRequest())
                return 200, [self._query_json(q) for q in out.queries]
            if path == "/queries" and method == "POST":
                sql = (body or {}).get("sql")
                if not sql:
                    return 400, {"error": "body needs {\"sql\": ...}"}
                q = stub.CreateQuery(pb.CreateQueryRequest(
                    query_text=sql, id=(body or {}).get("id", "")))
                return 201, self._query_json(q)
            m = re.fullmatch(r"/queries/([^/]+)", path)
            if m and method == "GET":
                q = stub.GetQuery(pb.GetQueryRequest(id=m.group(1)))
                return 200, self._query_json(q)
            if m and method == "DELETE":
                stub.DeleteQuery(pb.DeleteQueryRequest(id=m.group(1)))
                return 200, {"deleted": m.group(1)}
            m = re.fullmatch(r"/queries/([^/]+)/restart", path)
            if m and method == "POST":
                stub.RestartQuery(pb.RestartQueryRequest(id=m.group(1)))
                return 200, {"restarted": m.group(1)}
            m = re.fullmatch(r"/queries/([^/]+)/health", path)
            if m and method == "GET":
                # per-query health rollup (ISSUE 13): OK/DEGRADED/
                # STALLED + reasons, 404 for unknown queries
                return 200, self._admin("health", query=m.group(1))
            m = re.fullmatch(r"/queries/([^/]+)/trace", path)
            if m and method == "GET":
                # the query's span ring as Chrome trace-event JSON
                return 200, self._admin("trace-spans", scope=m.group(1))
            m = re.fullmatch(r"/queries/([^/]+)/flightrec", path)
            if m and method == "GET":
                # flight-recorder postmortem bundles (ISSUE 18) — kept
                # past query deletion (404 only when none were captured)
                return 200, self._admin("flightrec", query=m.group(1))
            if path == "/programs" and method == "GET":
                # compiled-program inventory with XLA cost analysis
                return 200, self._admin("programs")

            if path == "/views" and method == "GET":
                out = stub.ListViews(pb.ListViewsRequest())
                return 200, [{"name": v.view_id, "status": v.status,
                              "sql": v.sql} for v in out.views]
            m = re.fullmatch(r"/views/([^/]+)", path)
            if m and method == "GET":
                resp = stub.ExecuteQuery(pb.CommandQuery(
                    stmt_text=f"SELECT * FROM {m.group(1)};"))
                return 200, [rec.struct_to_dict(s)
                             for s in resp.result_set]
            if m and method == "DELETE":
                stub.DeleteView(pb.DeleteViewRequest(view_id=m.group(1)))
                return 200, {"deleted": m.group(1)}

            if path == "/connectors" and method == "GET":
                out = stub.ListConnectors(pb.ListConnectorsRequest())
                return 200, [{"id": c.id, "status": c.status,
                              "config": c.config}
                             for c in out.connectors]
            if path == "/connectors" and method == "POST":
                cfg = (body or {}).get("config")
                if not cfg:
                    return 400, {"error": "body needs {\"config\": sql}"}
                c = stub.CreateSinkConnector(
                    pb.CreateSinkConnectorRequest(
                        config=cfg, id=(body or {}).get("id", "")))
                return 201, {"id": c.id, "status": c.status}
            m = re.fullmatch(r"/connectors/([^/]+)", path)
            if m and method == "DELETE":
                stub.DeleteConnector(
                    pb.DeleteConnectorRequest(id=m.group(1)))
                return 200, {"deleted": m.group(1)}

            if path == "/nodes" and method == "GET":
                out = stub.ListNodes(pb.ListNodesRequest())
                return 200, [{"id": n.id, "address": n.address,
                              "port": n.port, "status": n.status,
                              "roles": list(n.roles)}
                             for n in out.nodes]
            return 404, {"error": f"no route {method} {path}"}
        except grpc.RpcError as e:
            code = _STATUS.get(e.code(), 500)
            if e.code() == grpc.StatusCode.UNAVAILABLE:
                from hstream_tpu.client.retry import leader_hint_from_error

                hint = leader_hint_from_error(e)
                if hint:
                    # signal handle() to rebind + retry; if the retry
                    # fails too, the body still names the new leader
                    return (code, {"error": e.details() or "not leader",
                                   "leader_hint": hint},
                            {"x-follow-leader": hint})
            if code == 429:
                # flow-control refusal: surface the server's retry-after
                # hint as the standard header (seconds, rounded up)
                from hstream_tpu.client.retry import (
                    retry_after_ms_from_error,
                )

                ms = retry_after_ms_from_error(e)
                headers = {"Retry-After":
                           str(max(1, -(-ms // 1000)) if ms else 1)}
                return (code, {"error": e.details() or str(e.code()),
                               "retry_after_ms": ms}, headers)
            return code, {"error": e.details() or str(e.code())}
        except (TypeError, ValueError, AttributeError, KeyError) as e:
            # malformed request bodies (wrong field types etc.) must get
            # a JSON 400, not a dropped connection + server traceback
            return 400, {"error": f"bad request: {e}"}
        except Exception as e:  # noqa: BLE001 — HTTP boundary
            return 500, {"error": f"{type(e).__name__}: {e}"}

    def _query_json(self, q) -> dict:
        return {"id": q.id, "status": q.status,
                "created_time_ms": q.created_time_ms,
                "sql": q.query_text}

    def _admin(self, command: str, **kwargs) -> dict:
        resp = self.stub.SendAdminCommand(pb.AdminCommandRequest(
            command=command, args=rec.dict_to_struct(kwargs)))
        return json.loads(resp.result)

    def _overview(self) -> dict:
        """Cluster summary + the stats holder (reference Overview.hs —
        which never exposed stats; this does, via GetStats), plus the
        flow governor's shed/credit state and per-query pipeline stage
        occupancy — one scrape shows ingest, pipeline, and flow state
        together (ISSUE 3)."""
        stub = self.stub
        streams = stub.ListStreams(pb.ListStreamsRequest()).streams
        queries = stub.ListQueries(pb.ListQueriesRequest()).queries
        views = stub.ListViews(pb.ListViewsRequest()).views
        conns = stub.ListConnectors(pb.ListConnectorsRequest()).connectors
        nodes = stub.ListNodes(pb.ListNodesRequest()).nodes
        stats = stub.GetStats(pb.GetStatsRequest())
        try:
            flow = self._admin("flow-status")
        except grpc.RpcError:
            flow = None
        try:
            read_cache = self._admin("read-cache")
        except grpc.RpcError:
            read_cache = None
        pipeline: dict[str, Any] = {}
        qids = [q.id for q in queries] + [f"view-{v.view_id}"
                                          for v in views]
        for qid in qids:
            try:
                trace = rec.struct_to_dict(stub.GetQueryTrace(
                    pb.GetQueryRequest(id=qid)))
            except grpc.RpcError:
                continue  # not running here
            stages = trace.get("pipeline")
            if stages:
                pipeline[qid] = {k: round(v, 4) if
                                 isinstance(v, float) else v
                                 for k, v in stages.items()}
        return {
            "streams": len(streams),
            "queries": len(queries),
            "views": len(views),
            "connectors": len(conns),
            "nodes": [{"id": n.id, "status": n.status} for n in nodes],
            "stats": [{
                "stream": s.stream_name,
                "counters": dict(s.counters),
                "rates": {k: round(v, 3) for k, v in s.rates.items()},
            } for s in stats.stats],
            "flow": flow,
            "read_cache": read_cache,
            "pipeline_stages": pipeline,
        }


def _make_handler(gw: Gateway):
    class Handler(BaseHTTPRequestHandler):
        def _run(self, method: str) -> None:
            from urllib.parse import unquote, urlsplit

            # split query string, decode %-escapes in resource names
            # (before the body read: the framed-append route takes its
            # body RAW, everything else parses JSON)
            parts = urlsplit(self.path)
            path = unquote(parts.path)
            body = None
            length = int(self.headers.get("Content-Length") or 0)
            if length:
                raw = self.rfile.read(length)
                if path.rstrip("/").endswith("/appendColumnar"):
                    body = raw  # one framed columnar block, raw bytes
                else:
                    try:
                        body = json.loads(raw)
                    except ValueError:
                        self._send(400, {"error": "invalid JSON body"})
                        return
            # correlation: honor the caller's id, mint one otherwise;
            # the id rides the proxied gRPC metadata and echoes back
            rid = (self.headers.get("X-Request-Id")
                   or f"gw-{uuid.uuid4().hex[:12]}")
            self._rid = rid
            with request_context(rid):
                out = gw.handle(method, path.rstrip("/") or path, body,
                                query=parts.query)
            # (code, payload) or (code, payload, extra-headers)
            code, payload = out[0], out[1]
            headers = out[2] if len(out) > 2 else None
            self._send(code, payload, headers)

        def _send(self, code: int, payload: Any,
                  headers: dict[str, str] | None = None) -> None:
            headers = dict(headers or {})
            if isinstance(payload, (str, bytes)):
                # raw passthrough (/metrics text exposition)
                data = (payload.encode()
                        if isinstance(payload, str) else payload)
                ctype = headers.pop("Content-Type", "text/plain")
            else:
                data = json.dumps(payload).encode()
                ctype = headers.pop("Content-Type", "application/json")
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            rid = getattr(self, "_rid", None)
            if rid:
                self.send_header("X-Request-Id", rid)
            for k, v in headers.items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler API
            self._run("GET")

        def do_POST(self):  # noqa: N802
            self._run("POST")

        def do_DELETE(self):  # noqa: N802
            self._run("DELETE")

        def log_message(self, fmt, *args):  # quiet
            pass

    return Handler


def serve_gateway(server_addr: str, host: str = "127.0.0.1",
                  port: int = 6580) -> tuple[ThreadingHTTPServer, Gateway]:
    """Start the gateway; returns (httpd, gateway). Caller owns
    shutdown. Port 0 picks a free port (httpd.server_port)."""
    gw = Gateway(server_addr)
    httpd = ThreadingHTTPServer((host, port), _make_handler(gw))
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    return httpd, gw


SWAGGER = {
    "openapi": "3.0.0",
    "info": {"title": "hstream-tpu HTTP gateway", "version": "1.0"},
    "paths": {
        "/overview": {"get": {"summary": "cluster summary + stats + "
                                         "flow + pipeline stages"}},
        "/metrics": {"get": {"summary":
                             "Prometheus text exposition"}},
        "/events": {"get": {"summary": "event journal slice "
                                       "(kind/since/limit)"}},
        "/stats": {"get": {"summary": "per-entity rate-family tables "
                                      "(entity=streams|subscriptions|"
                                      "queries, interval=1min|10min|"
                                      "1h)"}},
        "/cluster-stats": {"get": {"summary": "federated node load "
                                              "reports merged across "
                                              "peers/followers"}},
        "/streams": {"get": {"summary": "list streams"},
                     "post": {"summary": "create stream"}},
        "/streams/{name}": {"delete": {"summary": "delete stream"}},
        "/streams/{name}/append": {
            "post": {"summary": "append JSON records"}},
        "/streams/{name}/appendColumnar": {
            "post": {"summary": "append one framed columnar block "
                                "(raw bytes, colframe wire format)"}},
        "/queries": {"get": {"summary": "list queries"},
                     "post": {"summary": "create push query"}},
        "/queries/{id}": {"get": {"summary": "get query"},
                          "delete": {"summary": "delete query"}},
        "/queries/{id}/restart": {"post": {"summary": "restart query"}},
        "/queries/{id}/health": {
            "get": {"summary": "health rollup: OK/DEGRADED/STALLED "
                               "with reasons + freshness evidence"}},
        "/queries/{id}/trace": {
            "get": {"summary": "span ring as Chrome trace-event JSON "
                               "(needs --trace-sample > 0)"}},
        "/queries/{id}/flightrec": {
            "get": {"summary": "flight-recorder postmortem bundles "
                               "(captured at STALLED / crash-loop "
                               "edges; survive query deletion)"}},
        "/programs": {
            "get": {"summary": "compiled-program inventory with XLA "
                               "cost-analysis flops/bytes"}},
        "/views": {"get": {"summary": "list views"}},
        "/views/{name}": {"get": {"summary": "pull-query the view"},
                          "delete": {"summary": "drop view"}},
        "/connectors": {"get": {"summary": "list connectors"},
                        "post": {"summary": "create sink connector"}},
        "/connectors/{id}": {"delete": {"summary": "delete connector"}},
        "/nodes": {"get": {"summary": "list server nodes"}},
    },
}


def main(argv=None) -> None:
    import argparse
    import signal

    ap = argparse.ArgumentParser("hstream-tpu-http-gateway")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=6580)
    ap.add_argument("--server", default="127.0.0.1:6570",
                    help="gRPC server address to proxy")
    args = ap.parse_args(argv)
    httpd, gw = serve_gateway(args.server, args.host, args.port)
    print(f"http gateway on {args.host}:{httpd.server_port} -> "
          f"{args.server}")
    ev = threading.Event()
    signal.signal(signal.SIGINT, lambda *a: ev.set())
    signal.signal(signal.SIGTERM, lambda *a: ev.set())
    ev.wait()
    httpd.shutdown()
    gw.close()


if __name__ == "__main__":
    main()
