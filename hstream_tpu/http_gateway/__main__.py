from hstream_tpu.http_gateway import main

main()
