"""Managed continuous-query tasks.

The reference runs each continuous query as a forked green thread: a
checkpointed reader polls the source stream(s), every record walks the
processor DAG, and sink processors append results downstream
(runTaskWrapper, Handler/Common.hs:169-180; runTask, Processor.hs:99-144).

Here a task is one daemon thread per query driving the batched engine:
read a chunk from the checkpointed reader -> decode JSON records ->
executor.process (the jitted lattice step) -> emit rows to the sink
callback -> checkpoint.

Checkpointing improves on the reference (which checkpoints readers only
— operator state is in-memory, so its restarts undercount every window
spanning them, Codegen.hs:374-385): read positions are committed ONLY
paired with an operator-state snapshot, in one atomic meta-KV write
(engine.snapshot). Resume restores the state and continues from the
paired LSNs — exact, modulo at-least-once re-emission of rows sunk
after the last snapshot.
"""

from __future__ import annotations

import threading
import time
import traceback
from typing import Any, Callable

from hstream_tpu.common import records as rec
from hstream_tpu.common.logger import get_logger
from hstream_tpu.engine.snapshot import (
    capture_executor,
    restore_executor,
    serialize_capture,
)
from hstream_tpu.server.persistence import QueryInfo, TaskStatus
from hstream_tpu.store.api import LSN_MIN, DataBatch
from hstream_tpu.store.checkpoint import CheckpointedReader
from hstream_tpu.store.streams import StreamType

log = get_logger("tasks")

SinkFn = Callable[[list[dict[str, Any]]], None]

READ_CHUNK = 256
POLL_TIMEOUT_MS = 50


def snapshot_key(query_id: str) -> str:
    """Meta-KV key holding a query's operator-state snapshot."""
    return f"qsnap/{query_id}"


class QueryTask(threading.Thread):
    """One continuous query: source stream(s) -> executor -> sink rows."""

    # state snapshot + checkpoint cadence; tests lower it
    snapshot_interval_ms: int = 1000

    def __init__(self, ctx, info: QueryInfo, plan, sink: SinkFn, *,
                 from_beginning: bool = True):
        super().__init__(name=f"query-{info.query_id}", daemon=True)
        self.ctx = ctx
        self.info = info
        self.plan = plan
        self.sink = sink
        self.from_beginning = from_beginning
        self.executor = None
        self.error: BaseException | None = None
        # serializes executor state mutation (this thread) against pull
        # queries peeking live state from gRPC threads (views.snapshot)
        self.state_lock = threading.RLock()
        # optional sink-side state riding in the snapshot (a view's
        # closed-row materialization survives restarts this way)
        self.sink_dump: Callable[[], Any] | None = None
        self.sink_load: Callable[[Any], None] | None = None
        self._stop_ev = threading.Event()
        self._sources: dict[int, str] = {}  # logid -> stream name
        for name in self.source_streams():
            self._sources[ctx.streams.get_logid(name)] = name
        self._reader: CheckpointedReader | None = None
        self._pending_ckps: dict[int, int] = {}  # processed, not committed
        self._last_snapshot_ms = 0.0
        self._dirty = False
        self._crash = False
        self._detach = False

    def source_streams(self) -> list[str]:
        names = [self.plan.source]
        if self.plan.join is not None:
            names.append(self.plan.join.right.name)
        return names

    @property
    def is_join(self) -> bool:
        return self.plan.join is not None

    # ---- lifecycle ---------------------------------------------------------

    def stop(self, timeout: float = 10.0, *, crash: bool = False,
             detach: bool = False) -> None:
        """Stop modes:
        default — user-initiated terminate: final snapshot + TERMINATED.
        detach=True — server shutdown: final snapshot but status stays
        RUNNING so boot-time resume_persisted relaunches the query.
        crash=True — fault injection (tests): no snapshot, no status
        update, like a killed process; resume replays from the last
        periodic snapshot."""
        if crash:
            self._crash = True
        if detach:
            self._detach = True
        self._stop_ev.set()
        if self.is_alive():
            self.join(timeout)

    def run(self) -> None:
        ctx = self.ctx
        try:
            reader = CheckpointedReader(
                f"query-{self.info.query_id}",
                ctx.store.new_reader(max_logs=len(self._sources)),
                ctx.ckp_store)
            self._reader = reader
            reader.set_timeout(POLL_TIMEOUT_MS)
            resumed = self._restore_state()
            for logid in self._sources:
                if resumed is not None and logid in resumed:
                    reader.start_reading(logid, resumed[logid] + 1)
                else:
                    reader.start_reading_from_checkpoint(logid, LSN_MIN)
            ctx.persistence.set_query_status(self.info.query_id,
                                             TaskStatus.RUNNING)
            while not self._stop_ev.is_set():
                results = reader.read(READ_CHUNK)
                if not results:
                    self._maybe_snapshot()
                    continue
                for r in results:
                    if isinstance(r, DataBatch):
                        self._process_batch(r)
                    lsn = (r.lsn if isinstance(r, DataBatch) else r.hi_lsn)
                    if lsn > self._pending_ckps.get(r.logid, 0):
                        self._pending_ckps[r.logid] = lsn
                        self._dirty = True
                self._maybe_snapshot()
            if not self._crash:
                self._snapshot_now()  # graceful stop: state is durable
                if not self._detach:
                    ctx.persistence.set_query_status(
                        self.info.query_id, TaskStatus.TERMINATED)
            # detach (server shutdown) and crash both leave status
            # RUNNING so boot-time resume_persisted relaunches the query
        except BaseException as e:  # noqa: BLE001 — status must reflect death
            self.error = e
            log.error("query %s died: %s\n%s", self.info.query_id, e,
                      traceback.format_exc())
            try:
                ctx.persistence.set_query_status(self.info.query_id,
                                                 TaskStatus.CONNECTION_ABORT)
            except Exception:
                pass
        finally:
            ctx.running_queries.pop(self.info.query_id, None)

    # ---- operator-state checkpointing --------------------------------------

    def _restore_state(self) -> dict[int, int] | None:
        """Restore executor + sink state from the last snapshot. Returns
        the read positions the state corresponds to (logid -> committed
        LSN), or None when starting fresh."""
        blob = self.ctx.store.meta_get(snapshot_key(self.info.query_id))
        if blob is None:
            return None
        with self.state_lock:
            self.executor, extra = restore_executor(self.plan, blob)
            if self.sink_load is not None and "sink" in extra:
                self.sink_load(extra["sink"])
        ckps = {int(k): int(v) for k, v in extra.get("ckps", {}).items()}
        self._pending_ckps = dict(ckps)
        self._last_snapshot_ms = time.monotonic() * 1000
        log.info("query %s resumed from snapshot at %s",
                 self.info.query_id, ckps)
        return ckps

    def _maybe_snapshot(self) -> None:
        if not self._dirty:
            return
        now = time.monotonic() * 1000
        if now - self._last_snapshot_ms >= self.snapshot_interval_ms:
            self._snapshot_now()

    def _snapshot_now(self) -> None:
        """Atomically persist (operator state, read checkpoints): one
        meta-KV write. Read positions NEVER advance past durable state —
        the reference's failure mode (commit-then-lose-state undercount)
        cannot happen. The ckp store mirrors the LSNs for observability."""
        if not self._dirty:
            return
        extra: dict[str, Any] = {
            "ckps": {str(k): v for k, v in self._pending_ckps.items()}}
        if self.executor is None:
            # nothing aggregated yet (e.g. raw records only): committing
            # the read position loses no state
            if self._reader is not None and self._pending_ckps:
                self._reader.write_checkpoints(self._pending_ckps)
            self._last_snapshot_ms = time.monotonic() * 1000
            self._dirty = False
            return
        # capture under the lock (cheap, consistent), serialize outside
        # (device sync + npz pack must not stall ingest or pull queries)
        with self.state_lock:
            if self.sink_dump is not None:
                extra["sink"] = self.sink_dump()
            meta, arrays = capture_executor(self.executor, extra)
        blob = serialize_capture(meta, arrays)
        self.ctx.store.meta_put(snapshot_key(self.info.query_id), blob)
        if self._reader is not None and self._pending_ckps:
            self._reader.write_checkpoints(self._pending_ckps)
        self._last_snapshot_ms = time.monotonic() * 1000
        self._dirty = False

    # ---- processing --------------------------------------------------------

    def _process_batch(self, batch: DataBatch) -> None:
        rows: list[dict[str, Any]] = []
        ts: list[int] = []
        for payload in batch.payloads:
            r = rec.parse_record(payload)
            d = rec.record_to_dict(r)
            if d is None:
                continue  # raw records skipped, like the reference's
                # JSON-flag filter (HStore.hs:119-143)
            rows.append(d)
            ts.append(r.header.publish_time_ms or batch.append_time_ms)
        if not rows:
            return
        with self.state_lock:
            if self.executor is None:
                from hstream_tpu.sql.codegen import make_executor

                self.executor = make_executor(self.plan, sample_rows=rows)
            if self.is_join:
                out = self.executor.process(
                    rows, ts, stream=self._sources[batch.logid])
            else:
                out = self.executor.process(rows, ts)
            # sink under the lock: a window removed from live state must
            # appear in the sink (view closed rows) atomically with the
            # removal, or a concurrent pull-query snapshot sees it in
            # neither half (no lock-order cycle: views.snapshot releases
            # the materialization lock before taking state_lock)
            if out:
                self.sink(out)


def stream_sink(ctx, sink_stream: str,
                stream_type: StreamType = StreamType.STREAM) -> SinkFn:
    """Sink emitting rows as JSON records onto a stream (the reference's
    internal sink processor, HStore.hs:152-163)."""
    logid = ctx.streams.get_logid(sink_stream, stream_type)

    def sink(rows: list[dict[str, Any]]) -> None:
        payloads = [rec.build_record(row).SerializeToString()
                    for row in rows]
        ctx.store.append_batch(logid, payloads)

    return sink
